"""Node heartbeating (reference: nomad/heartbeat.go — nodeHeartbeater:34,
resetHeartbeatTimer, invalidateHeartbeat:135, disconnectState:177).

Each node has a TTL; a missed TTL transitions the node to `down` — or to
`disconnected` when any alloc on it uses max_client_disconnect — and
triggers evaluations for every affected job.

Fleet scale: TTLs live in a hashed timing wheel (one bucket per tick,
enough buckets that a full TTL fits in one rotation), so re-arming a
node is O(1) remove+insert and a 10K-agent fleet heartbeating every
interval never grows a stale-tuple backlog the way a lazy-deletion heap
does.  The status/liveness writes those heartbeats imply coalesce
through HeartbeatBatcher into ONE NodeHeartbeatBatch raft entry per
flush tick — the node-plane analogue of the plan applier's
APPLY_PLAN_RESULTS batching — so steady-state heartbeat cost is
O(batches), not O(nodes), log entries.
"""
from __future__ import annotations

import logging
import os
import threading
import time as _time
from typing import Dict, Optional, Set, Tuple

from nomad_tpu import chaos, knobs
from nomad_tpu.structs.node import NodeStatus
from nomad_tpu.telemetry import global_metrics

log = logging.getLogger(__name__)


class HeartbeatTracker:
    def __init__(self, server, ttl: float = 10.0, tick: float = 0.1):
        self.server = server
        self.ttl = ttl
        self.tick = tick
        self._lock = threading.Lock()
        # wheel geometry: one bucket per tick; a deadline at most
        # ttl+retry ahead always lands within a single rotation
        self._span = max(tick, 0.001)
        self._nslots = max(8, int(ttl / self._span) + 4)
        self._slots: list = [set() for _ in range(self._nslots)]
        self._where: Dict[str, Tuple[int, float]] = {}
        self._cursor = _time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        with self._lock:
            # fresh per leadership tenure: deadlines armed under a
            # PREVIOUS tenure must not expire nodes out of this one —
            # the new leader re-arms every live node right after start()
            # (initializeHeartbeatTimers), and anything it does not
            # re-arm is by definition not its to expire
            self._slots = [set() for _ in range(self._nslots)]
            self._where.clear()
            self._cursor = _time.time()
        self._stop = threading.Event()   # fresh per leadership tenure
        self._thread = threading.Thread(target=self._run, name="heartbeat",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(1.0)

    def heartbeat(self, node_id: str) -> float:
        """Reset the node's TTL (Node.UpdateStatus/heartbeat RPC path).
        Returns the TTL so clients know their deadline."""
        if chaos.active is not None and chaos.should("node.churn_kill"):
            # swallow the re-arm: the node misses its TTL and expires
            # through the real _invalidate path (down/disconnected)
            return self.ttl
        deadline = _time.time() + self.ttl
        with self._lock:
            self._arm_locked(node_id, deadline)
        return self.ttl

    def _arm_locked(self, node_id: str, deadline: float) -> None:
        old = self._where.get(node_id)
        if old is not None:
            self._slots[old[0]].discard(node_id)
        slot = int(deadline / self._span) % self._nslots
        self._slots[slot].add(node_id)
        self._where[node_id] = (slot, deadline)

    def untrack(self, node_id: str) -> None:
        with self._lock:
            old = self._where.pop(node_id, None)
            if old is not None:
                self._slots[old[0]].discard(node_id)

    def tracked(self) -> int:
        """Number of armed TTLs (bench/telemetry)."""
        with self._lock:
            return len(self._where)

    def _run(self) -> None:
        while not self._stop.is_set():
            now = _time.time()
            expired = []
            with self._lock:
                start = int(self._cursor / self._span)
                end = int(now / self._span)
                if end - start >= self._nslots:
                    # clock jumped past a full rotation: one pass over
                    # every physical bucket covers all of it
                    start = end - self._nslots + 1
                for b in range(start, end + 1):
                    slot = self._slots[b % self._nslots]
                    for node_id in list(slot):
                        _, deadline = self._where[node_id]
                        if deadline <= now:
                            slot.discard(node_id)
                            del self._where[node_id]
                            expired.append(node_id)
                        # else: re-armed into this bucket's next
                        # rotation — its own turn will catch it
                self._cursor = now
            for node_id in expired:
                try:
                    self._invalidate(node_id)
                except Exception:           # noqa: BLE001
                    # a failed write (e.g. lost quorum mid-invalidate) must
                    # not kill the heartbeat loop for the whole tenure
                    log.exception("invalidate")
                    # the node was already dropped from the wheel; without
                    # a retry deadline it would stay tracked-as-alive
                    # forever despite the missed TTL.  Re-arm a short one
                    # (unless the node re-heartbeated meanwhile).
                    retry = _time.time() + min(self.ttl, 1.0)
                    with self._lock:
                        if node_id not in self._where:
                            self._arm_locked(node_id, retry)
            self._stop.wait(self.tick)

    def _invalidate(self, node_id: str) -> None:
        """Missed TTL (reference invalidateHeartbeat + disconnectState)."""
        server = self.server
        node = server.store.node_by_id(node_id)
        if node is None or node.status == NodeStatus.DOWN:
            return
        # disconnected iff any alloc on the node tolerates disconnects
        new_status = NodeStatus.DOWN
        for a in server.store.allocs_by_node(node_id):
            if a.terminal_status() or a.job is None:
                continue
            tg = a.job.lookup_task_group(a.task_group)
            if tg is not None and tg.max_client_disconnect_s is not None:
                new_status = NodeStatus.DISCONNECTED
                break
        # a churn storm expires nodes in waves: ride the batcher (one
        # raft entry per flush) instead of one entry per expiry
        batcher = getattr(server, "heartbeat_batch", None)
        if batcher is not None and batcher.running:
            batcher.note(node_id, new_status)
        else:
            server.update_node_status(node_id, new_status)


class HeartbeatBatcher:
    """Leader-side coalescer for heartbeat-driven FSM writes.

    Revivals (down/disconnected node heartbeats again), TTL expirations
    and periodic liveness stamps collect in a pending table keyed by
    node and flush as ONE NodeHeartbeatBatch log entry per tick, with
    node evals created only for real status transitions.  Liveness
    stamps are rate-limited to one per node per half-TTL — fresh enough
    that a failed-over leader re-arms timers off recent stamps, cheap
    enough that a 10K-agent fleet costs O(batches) log entries per
    tick.  `updated_at` is stamped here, at propose time: the FSM never
    reads the clock."""

    def __init__(self, server, interval: float = 0.05):
        self.server = server
        self.interval = interval
        self._lock = threading.Lock()
        self._pending: Dict[str, Tuple[str, float]] = {}
        self._transitions: Set[str] = set()
        self._last_stamp: Dict[str, float] = {}
        # device/attribute re-fingerprint deltas (Node.UpdateFingerprint):
        # coalesce per node, flush as ONE NodeFingerprintBatch entry —
        # a 1K-node fingerprint storm commits O(flush-ticks) raft
        # entries, not O(changes) full Node.Register round-trips
        self._fp_pending: Dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # bounded pending table: at the cap the writer forces a flush
        # (bypassing the chaos stall-skip) instead of growing without
        # limit — a stalled flusher plus a churn storm must cost O(cap)
        # memory, not O(storm)
        self.pending_max = max(1, knobs.get_int(
            "NOMAD_TPU_HB_PENDING_MAX"))
        self._force = threading.Event()

    def start(self) -> None:
        with self._lock:
            self._pending.clear()
            self._transitions.clear()
            self._last_stamp.clear()
            self._fp_pending.clear()
        self._stop = threading.Event()   # fresh per leadership tenure
        self._force = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="heartbeat-batch", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._force.set()               # wake the flusher promptly
        if self._thread:
            self._thread.join(1.0)
        with self._lock:
            # a deposed leader's queued writes die with its tenure; the
            # successor's own expiry/revival pass re-derives them (a
            # dropped fingerprint delta re-sends on the client's next
            # fingerprint pass or full re-register)
            self._pending.clear()
            self._transitions.clear()
            self._fp_pending.clear()

    @property
    def running(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._stop.is_set())

    def note(self, node_id: str, status: str) -> None:
        """Queue a status TRANSITION (revival, expiry) for the next
        flush; the flush creates the node's evals."""
        with self._lock:
            self._pending[node_id] = (status, _time.time())
            self._transitions.add(node_id)
            full = len(self._pending) >= self.pending_max
        if full:
            # never applies raft from the writer's thread (FSM watcher
            # re-entry): just wake the flusher out of its tick sleep
            self._force.set()

    def stamp(self, node_id: str, status: str) -> None:
        """Queue a liveness stamp (same status, fresh updated_at), at
        most one per node per half-TTL."""
        now = _time.time()
        half = self.server.config.heartbeat_ttl / 2.0
        with self._lock:
            if now - self._last_stamp.get(node_id, 0.0) < half:
                return
            self._last_stamp[node_id] = now
            if node_id not in self._pending:
                self._pending[node_id] = (status, now)
                full = len(self._pending) >= self.pending_max
            else:
                full = False
        if full:
            self._force.set()

    def note_fingerprint(self, node_id: str, update: dict) -> None:
        """Queue a device/attribute re-fingerprint delta for the next
        flush (newest delta per node wins — the client sends its full
        current device list, so deltas are self-superseding)."""
        with self._lock:
            u = self._fp_pending.setdefault(node_id,
                                            {"node_id": node_id})
            u.update(update)
            full = len(self._fp_pending) >= self.pending_max
        if full:
            self._force.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            forced = self._force.wait(self.interval)
            if self._stop.is_set():
                break
            if forced:
                self._force.clear()
                global_metrics.incr("heartbeat.batch_forced")
            try:
                self.flush(force=forced)
            except Exception:               # noqa: BLE001
                # deposed mid-flush (NotLeaderError) or a transient write
                # failure: stop() clears the queue when the tenure ends
                log.debug("heartbeat batch flush failed", exc_info=True)

    def flush(self, force: bool = False) -> None:
        """Drain the pending table into one batched FSM entry.  `force`
        (the pending table hit its cap) overrides the chaos stall-skip:
        a stalled flusher may defer work, never accumulate it without
        bound."""
        if chaos.active is not None and not force:
            if chaos.should("heartbeat.batch_stall"):
                # flush skipped this round: the pending table keeps
                # coalescing and the next tick carries the batch
                return
            chaos.maybe_delay("heartbeat.batch_stall")
        with self._lock:
            if not self._pending and not self._fp_pending:
                return
            pending = self._pending
            transitions = self._transitions
            fp_pending = self._fp_pending
            self._pending = {}
            self._transitions = set()
            self._fp_pending = {}
        from nomad_tpu.raft.fsm import MessageType
        if pending:
            self.server.apply(MessageType.NODE_HEARTBEAT_BATCH, {
                "updates": [{"node_id": nid, "status": st,
                             "updated_at": ts}
                            for nid, (st, ts) in pending.items()]})
            global_metrics.incr("heartbeat.batch_flush")
            global_metrics.incr("heartbeat.batch_nodes",
                                float(len(pending)))
        if fp_pending:
            self.server.apply(MessageType.NODE_FINGERPRINT_BATCH, {
                "updates": list(fp_pending.values())})
            global_metrics.incr("heartbeat.fingerprint_flush")
            global_metrics.incr("heartbeat.fingerprint_nodes",
                                float(len(fp_pending)))
        for nid in transitions:
            self.server.create_node_evals(nid)
