"""Periodic job dispatcher (reference: nomad/periodic.go — cron-style
launcher creating child jobs '<parent>/periodic-<ts>' with evals).

Supported specs: standard 5-field cron (minute hour dom month dow, with
*, */N, N, N-M, comma lists) and '@every <seconds>s'.
"""
from __future__ import annotations

import threading
import time as _time
from datetime import datetime, timedelta, timezone
from typing import List, Optional, Tuple


def _field_matches(field: str, value: int) -> bool:
    for part in field.split(","):
        if part == "*":
            return True
        if part.startswith("*/"):
            if value % int(part[2:]) == 0:
                return True
        elif "-" in part:
            lo, hi = part.split("-")
            if int(lo) <= value <= int(hi):
                return True
        elif part.isdigit() and int(part) == value:
            return True
    return False


def next_cron_after(spec: str, after: float) -> Optional[float]:
    """Next fire time strictly after `after` (UTC), or None."""
    if spec.startswith("@every"):
        secs = float(spec.split()[1].rstrip("s"))
        return after + secs
    fields = spec.split()
    if len(fields) != 5:
        return None
    minute, hour, dom, month, dow = fields
    t = datetime.fromtimestamp(after, tz=timezone.utc).replace(second=0, microsecond=0)
    t += timedelta(minutes=1)
    for _ in range(366 * 24 * 60):      # bounded search: one year
        # cron day-of-week: Sunday=0 (and 7 also means Sunday)
        cron_dow = (t.weekday() + 1) % 7
        dow_ok = _field_matches(dow, cron_dow) or (
            cron_dow == 0 and _field_matches(dow, 7))
        if (_field_matches(minute, t.minute) and _field_matches(hour, t.hour)
                and _field_matches(dom, t.day) and _field_matches(month, t.month)
                and dow_ok):
            return t.timestamp()
        t += timedelta(minutes=1)
    return None


class PeriodicDispatcher:
    def __init__(self, server, tick: float = 0.5):
        self.server = server
        self.tick = tick
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next_fire: dict = {}     # (ns, job_id) -> ts

    def start(self) -> None:
        self._stop = threading.Event()   # fresh per leadership tenure
        self._thread = threading.Thread(target=self._run, name="periodic",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(1.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.dispatch_due(_time.time())
            except Exception:           # noqa: BLE001
                import logging
                logging.getLogger(__name__).exception("periodic")
            self._stop.wait(self.tick)

    def dispatch_due(self, now: float) -> List[str]:
        launched = []
        for job in self.server.store.jobs():
            if not job.is_periodic() or job.stopped() or job.parent_id:
                continue
            if not job.periodic.enabled:
                continue
            key = (job.namespace, job.id)
            nxt = self._next_fire.get(key)
            if nxt is None:
                nxt = next_cron_after(job.periodic.spec, now)
                self._next_fire[key] = nxt
                continue
            if nxt is not None and now >= nxt:
                if job.periodic.prohibit_overlap and self._has_running_child(job):
                    self._next_fire[key] = next_cron_after(job.periodic.spec, now)
                    continue
                launched.append(self._launch(job, nxt))
                self._next_fire[key] = next_cron_after(job.periodic.spec, now)
        return launched

    def _has_running_child(self, job) -> bool:
        for j in self.server.store.jobs():
            if j.parent_id != job.id or j.status == "dead":
                continue
            allocs = self.server.store.allocs_by_job(j.namespace, j.id)
            if any(not a.terminal_status() for a in allocs):
                return True
            if not allocs:
                # child not placed yet (pending eval) still counts as
                # running for prohibit_overlap (reference periodic.go)
                evals = self.server.store.evals_by_job(j.namespace, j.id)
                if not allocs and (not evals
                                   or any(not e.terminal() for e in evals)):
                    return True
        return False

    def _launch(self, job, fire_time: float) -> str:
        """Create the child job '<id>/periodic-<unix>' (reference
        periodic.go derivedJob)."""
        child = job.copy()
        child.id = f"{job.id}/periodic-{int(fire_time)}"
        child.parent_id = job.id
        child.periodic = None
        self.server.register_job(child)
        return child.id
