"""Node drainer (reference: nomad/drainer/ — drainer.go:130 NodeDrainer,
run:225, handleDeadlinedNodes:243, watch_jobs.go migration batching).

Migrates allocations off draining nodes honoring each task group's
migrate.max_parallel, force-stops at the drain deadline, and marks the
node's drain complete when no migratable allocs remain.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional

from nomad_tpu.raft import MessageType
from nomad_tpu.structs import Allocation, Evaluation, EvalStatus, JobType
from nomad_tpu.structs.alloc import DesiredTransition
from nomad_tpu.structs.evaluation import EvalTrigger
from nomad_tpu.structs.node import DrainStrategy


class NodeDrainer:
    def __init__(self, server, interval: float = 0.1):
        self.server = server
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dirty = threading.Event()
        server.store.watch(self._on_change)

    def start(self) -> None:
        self._stop = threading.Event()   # fresh per leadership tenure
        self._thread = threading.Thread(target=self._run, name="drainer",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        if self._thread:
            self._thread.join(1.0)

    def _on_change(self, table: str, obj) -> None:
        if table in ("nodes", "allocs"):
            self._dirty.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait(timeout=self.interval)
            self._dirty.clear()
            if self._stop.is_set():
                return
            try:
                self.tick()
            except Exception:               # noqa: BLE001
                import logging
                logging.getLogger(__name__).exception("drainer")

    # ------------------------------------------------------------- API

    def drain_node(self, node_id: str, deadline_s: float = 3600.0,
                   ignore_system_jobs: bool = False) -> None:
        """Node.UpdateDrain RPC: set the drain strategy."""
        server = self.server
        strategy = DrainStrategy(
            deadline_s=deadline_s,
            ignore_system_jobs=ignore_system_jobs,
            force_deadline=_time.time() + deadline_s if deadline_s > 0 else 0.0,
            started_at=_time.time())
        server.apply(MessageType.NODE_UPDATE_DRAIN,
                     {"node_id": node_id, "drain_strategy": strategy})
        self._dirty.set()

    def cancel_drain(self, node_id: str) -> None:
        """Node.UpdateDrain with a nil spec: stop draining and restore
        eligibility (reference Node.UpdateDrain cancel form)."""
        self.server.apply(MessageType.NODE_UPDATE_DRAIN,
                          {"node_id": node_id, "drain_strategy": None,
                           "mark_eligible": True})

    # ------------------------------------------------------------- logic

    def tick(self, now: Optional[float] = None) -> None:
        now = now if now is not None else _time.time()
        server = self.server
        for node in server.store.nodes():
            if node.drain_strategy is None:
                continue
            self._process_node(node, now)

    def _drain_eval(self, a: Allocation, node_id: str) -> Evaluation:
        ev = Evaluation(
            namespace=a.namespace, priority=a.job.priority,
            type=a.job.type, job_id=a.job_id,
            triggered_by=EvalTrigger.NODE_DRAIN, node_id=node_id,
            status=EvalStatus.PENDING)
        # propose-time stamp (the FSM cone must stay deterministic)
        ev.create_time = ev.modify_time = _time.time()
        return ev

    def _process_node(self, node, now: float) -> None:
        server = self.server
        strategy = node.drain_strategy
        allocs = [a for a in server.store.allocs_by_node(node.id)
                  if not a.terminal_status()]
        migratable: List[Allocation] = []
        for a in allocs:
            if a.job is None:
                continue
            if a.job.type in (JobType.SYSTEM, JobType.SYSBATCH):
                if strategy.ignore_system_jobs:
                    continue
                migratable.append(a)   # stopped at deadline/completion
                continue
            migratable.append(a)

        if not migratable:
            # drain complete: clear strategy, node stays ineligible
            server.apply(MessageType.NODE_UPDATE_DRAIN,
                         {"node_id": node.id, "drain_strategy": None})
            return

        if node.status in ("down", "disconnected"):
            # hard-killed (or partitioned away) mid-drain: the node-update
            # eval path owns these allocs now — the reconciler marks them
            # lost and places replacements exactly once.  Migrate-marking
            # or force-stopping here would race that and double-handle.
            return

        deadlined = strategy.force_deadline and now >= strategy.force_deadline

        if deadlined:
            # handleDeadlinedNodes (drainer.go:243): force-stop remaining
            # allocs ONCE — the stop makes them server-terminal, so they
            # drop out of `migratable` and this branch does not re-fire.
            # Stops and their follow-up evals ride ONE raft entry: a
            # partition between two entries could commit the stops but
            # lose the evals, stranding the job under count with nothing
            # left to trigger replacement.
            updates = []
            evals: Dict[str, Evaluation] = {}
            for a in migratable:
                u = a.copy()
                u.desired_status = "stop"
                u.desired_description = "alloc stopped because drain deadline reached"
                updates.append(u)
                key = (a.namespace, a.job_id)
                if key not in evals and a.job is not None:
                    evals[key] = self._drain_eval(a, node.id)
            if updates:
                server.apply(MessageType.ALLOC_UPDATE_DESIRED_TRANSITION,
                             {"allocs": updates,
                              "evals": list(evals.values())})
            return

        # group migrate marks per job so each job's transitions and its
        # NODE_DRAIN eval commit in one raft entry (same strand hazard as
        # the deadline branch: a mark without its eval never reschedules)
        by_job: Dict[str, List[Allocation]] = {}
        eval_for: Dict[str, Evaluation] = {}
        marked: Dict[tuple, int] = {}
        for a in migratable:
            if a.desired_transition.should_migrate():
                continue   # already in flight
            tg = a.job.lookup_task_group(a.task_group)
            max_parallel = tg.migrate.max_parallel if tg is not None else 1
            # respect per-group migrate.max_parallel: count of this
            # group's allocs already migrating across the cluster, plus
            # the marks batched this tick but not yet applied
            group_key = (a.namespace, a.job_id, a.task_group)
            in_flight = marked.get(group_key, 0) + sum(
                1 for other in server.store.allocs_by_job(a.namespace, a.job_id)
                if other.task_group == a.task_group
                and not other.terminal_status()
                and other.desired_transition.should_migrate())
            if in_flight >= max_parallel:
                continue
            marked[group_key] = marked.get(group_key, 0) + 1
            u = a.copy()
            u.desired_transition = DesiredTransition(migrate=True)
            key = (a.namespace, a.job_id)
            by_job.setdefault(key, []).append(u)
            if key not in eval_for and a.job is not None:
                eval_for[key] = self._drain_eval(a, node.id)
        for key, updates in by_job.items():
            ev = eval_for.get(key)
            server.apply(MessageType.ALLOC_UPDATE_DESIRED_TRANSITION,
                         {"allocs": updates,
                          "evals": [ev] if ev is not None else []})
