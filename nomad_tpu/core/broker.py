"""Evaluation broker (reference: nomad/eval_broker.go — EvalBroker:47,
Enqueue:182, Dequeue:335, Ack/Nack:537,601, delayed evals:758, priority
heap:888-925).

Semantics reproduced:
- priority queues per scheduler type; FIFO within a priority
- one eval per (namespace, job) outstanding; later ones wait in a per-job
  pending queue and are released on Ack (dedup of pending evals per job)
- dequeue hands out a lease token; Ack/Nack must present it
- Nack requeues with attempt count; after `delivery_limit` attempts the
  eval is routed to the `_failed` queue (reaped by the leader loop)
- `wait_until` evals sit in a delay heap until due
- expired leases auto-nack (checked lazily on broker operations)

Weighted fair dequeue (this repo's multi-tenant extension, following
stride scheduling — Waldspurger & Weihl, OSDI '95 — over per-namespace
queues, the broker-level analog of DRF's dominant-share ordering): the
ready queues are partitioned per (scheduler type, namespace); each
namespace carries a virtual-time `pass` advanced by `stride = K/weight`
on every dequeue, and the next eval comes from the runnable namespace
with the minimum pass.  A namespace that wakes from idle has its pass
floored to the runnable minimum, so sleeping never banks credit.  With
one namespace (or fairness disabled via the replicated
SchedulerConfiguration) the order degenerates to the global
(-priority, seq) order, byte-for-byte the pre-fairness behavior.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time as _time
import uuid
from collections import defaultdict, deque
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu import chaos
from nomad_tpu import deadline as request_deadline
from nomad_tpu import tracing
from nomad_tpu.analysis import race
from nomad_tpu.structs import Evaluation
from nomad_tpu.utils import requires_lock

FAILED_QUEUE = "_failed"


class _Lease:
    __slots__ = ("eval", "token", "expires_at")

    def __init__(self, ev: Evaluation, token: str, expires_at: float):
        self.eval = ev
        self.token = token
        self.expires_at = expires_at


class EvalBroker:
    # Lock discipline (see nomad_tpu.analysis): the queue tables below
    # are only touched under `self._lock` or in @requires_lock helpers.
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({
        "_ns_ready", "_ns_nonempty", "_fair_pass", "_fair_weights",
        "_unack", "_attempts", "_pending", "_active_jobs",
        "_delayed", "_requeued",
    })
    # happens-before (nomad_tpu.analysis): the lease table is touched by
    # every scheduler worker (dequeue/ack/nack), the timer poll, and the
    # plan-submit gate (outstanding); the race detector traces it.
    _RACE_TRACED = {"_unack": "_lock"}

    def __init__(self, nack_timeout: float = 60.0, delivery_limit: int = 3,
                 initial_nack_delay: float = 1.0, subsequent_nack_delay: float = 20.0):
        self._lock = threading.Condition()
        self.enabled = False
        self.nack_timeout = nack_timeout
        self.delivery_limit = delivery_limit
        self.initial_nack_delay = initial_nack_delay
        self.subsequent_nack_delay = subsequent_nack_delay
        self._counter = itertools.count()
        # scheduler type -> namespace -> heap of (-priority, seq, eval);
        # the per-namespace partition is what fair dequeue picks over
        self._ns_ready: Dict[str, Dict[str, List[Tuple[int, int, Evaluation]]]] = \
            defaultdict(dict)
        # scheduler type -> set of namespaces with a non-empty heap (the
        # dequeue scan walks only runnable namespaces)
        self._ns_nonempty: Dict[str, set] = defaultdict(set)
        # stride accounting: namespace -> virtual pass; weights come from
        # the replicated SchedulerConfiguration via set_fair_config
        self._fair_pass: Dict[str, float] = {}
        self._fair_enabled = True
        self._fair_default_weight = 1
        self._fair_weights: Dict[str, int] = {}
        self._unack: Dict[str, _Lease] = {}
        self._attempts: Dict[str, int] = defaultdict(int)
        # (namespace, job_id) -> deque of evals waiting for the active one.
        # A job is "active" from the moment one of its evals enters the
        # ready queue (not just at dequeue) until that eval is acked or
        # dead-lettered — the reference dedups at enqueue time across
        # ready+unack, preventing two schedulers from planning the same job
        # concurrently.
        self._pending: Dict[Tuple[str, str], deque] = defaultdict(deque)
        self._active_jobs: Set[Tuple[str, str]] = set()
        self._delayed: List[Tuple[float, int, Evaluation]] = []
        self._requeued: List[Tuple[float, int, Evaluation]] = []   # nack delay heap
        self.stats = defaultdict(int)

    # ------------------------------------------------------------- control

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self.enabled = enabled
            if not enabled:
                self.flush()

    def set_fair_config(self, cfg) -> None:
        """Adopt the replicated SchedulerConfiguration's fairness knobs
        (live-tunable: the FSM's leader hook pushes every applied
        config entry here)."""
        with self._lock:
            self._fair_enabled = bool(
                getattr(cfg, "fair_dequeue_enabled", True))
            self._fair_default_weight = max(
                1, int(getattr(cfg, "default_namespace_weight", 1) or 1))
            self._fair_weights = dict(
                getattr(cfg, "namespace_weights", None) or {})
            self._lock.notify_all()

    @requires_lock("_lock")
    def _stride(self, namespace: str) -> float:
        weight = self._fair_weights.get(
            namespace, self._fair_default_weight)
        return 1000.0 / max(1, int(weight))

    @requires_lock("_lock")
    def flush(self) -> None:
        race.write("EvalBroker._unack", self)
        self._ns_ready.clear()
        self._ns_nonempty.clear()
        self._fair_pass.clear()
        self._unack.clear()
        self._attempts.clear()
        self._pending.clear()
        self._active_jobs.clear()
        self._delayed = []
        self._requeued = []

    # ------------------------------------------------------------- enqueue

    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(ev)
            self._lock.notify_all()

    def enqueue_all(self, evals: List[Evaluation]) -> None:
        with self._lock:
            for ev in evals:
                self._enqueue_locked(ev)
            self._lock.notify_all()

    @requires_lock("_lock")
    def _enqueue_locked(self, ev: Evaluation) -> None:
        if not self.enabled:
            return
        now = _time.time()
        if ev.wait_until and ev.wait_until > now:
            heapq.heappush(self._delayed, (ev.wait_until, next(self._counter), ev))
            self.stats["delayed"] += 1
            return
        key = (ev.namespace, ev.job_id)
        if ev.job_id and key in self._active_jobs:
            self._pending[key].append(ev)
            self.stats["pending_dedup"] += 1
            return
        if ev.job_id:
            self._active_jobs.add(key)
        self._push_ready_locked(ev)
        self.stats["enqueued"] += 1

    @requires_lock("_lock")
    def _push_ready_locked(self, ev: Evaluation) -> None:
        heap = self._ns_ready[ev.type].setdefault(ev.namespace, [])
        if not heap:
            # namespace becomes runnable for this scheduler type.  If it
            # was idle EVERYWHERE, floor its pass to the runnable
            # minimum: a sleeper must not bank virtual time and then
            # monopolize the broker on wake (stride scheduling's
            # standard re-admission rule).
            was_runnable = any(ev.namespace in nss
                               for nss in self._ns_nonempty.values())
            if not was_runnable:
                floor = min((self._fair_pass.get(ns, 0.0)
                             for nss in self._ns_nonempty.values()
                             for ns in nss), default=0.0)
                self._fair_pass[ev.namespace] = max(
                    self._fair_pass.get(ev.namespace, 0.0), floor)
            self._ns_nonempty[ev.type].add(ev.namespace)
        heapq.heappush(heap, (-ev.priority, next(self._counter), ev))

    # ------------------------------------------------------------- dequeue

    @requires_lock("_lock")
    def _poll_timers_locked(self) -> None:
        now = _time.time()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, ev = heapq.heappop(self._delayed)
            ev.wait_until = 0.0
            self._enqueue_locked(ev)
        while self._requeued and self._requeued[0][0] <= now:
            _, _, ev = heapq.heappop(self._requeued)
            self._push_ready_locked(ev)   # job stays active; no dedup
        # expire stale leases -> auto-nack
        race.write("EvalBroker._unack", self)
        expired = [t for t, l in self._unack.items() if l.expires_at <= now]
        for token in expired:
            lease = self._unack.pop(token)
            self._nack_locked(lease.eval, requeue_now=True)

    @requires_lock("_lock")
    def _pick_locked(self, schedulers: List[str]
                     ) -> Optional[Tuple[Evaluation, str]]:
        """One fair pick + lease mint, or None when nothing is ready.
        Shared by dequeue (one pick per lock pass) and dequeue_batch
        (repeated picks draining a wave in one pass)."""
        # fair pick: the runnable namespace with the minimum
        # stride pass (ties broken by the global head order so
        # equal-pass namespaces keep FIFO-within-priority);
        # fairness off -> pure global (-priority, seq) order
        fair = self._fair_enabled
        if fair and chaos.active is not None and \
                chaos.active.should("broker.unfair_burst"):
            # one dequeue slips past the stride accounting, as
            # if a burst raced the pick; the pass charge below
            # still lands, so the debt is repaid on the next
            # picks and the starvation bound must still hold
            fair = False
            self.stats["fair_bypassed"] += 1
        best_q, best_ns, best_key = None, None, None
        for s in schedulers:
            for ns in self._ns_nonempty.get(s, ()):
                head = self._ns_ready[s][ns][0]
                key = (self._fair_pass.get(ns, 0.0),
                       head[0], head[1]) if fair \
                    else (head[0], head[1])
                if best_key is None or key < best_key:
                    best_q, best_ns, best_key = s, ns, key
        if best_ns is None:
            return None
        heap = self._ns_ready[best_q][best_ns]
        best = heapq.heappop(heap)
        if not heap:
            del self._ns_ready[best_q][best_ns]
            self._ns_nonempty[best_q].discard(best_ns)
        if self._fair_enabled:
            self._fair_pass[best_ns] = \
                self._fair_pass.get(best_ns, 0.0) + \
                self._stride(best_ns)
            self.stats["fair_picks"] += 1
        ev = best[2]
        token = str(uuid.uuid4())
        expires = _time.time() + self.nack_timeout
        if chaos.active is not None and \
                chaos.active.should("broker.lease_expire"):
            # hand out an already-expired lease: the next timer
            # poll auto-nacks it, so the worker's eventual ack
            # or plan submit sees a stale token
            expires = _time.time()
            self.stats["chaos_lease_expired"] += 1
        race.write("EvalBroker._unack", self)
        self._unack[token] = _Lease(ev, token, expires)
        self.stats["dequeued"] += 1
        tracer = tracing.active
        if tracer is not None:
            # queue-wait span, stitched from the propose-time
            # note (the FSM's leader hook enqueues inside the
            # apply cone, so nothing is stamped there); the
            # context is re-noted for the dequeuing worker
            note = tracer.take_eval_note(ev.id)
            if note is not None:
                ctx, enq_ts = note
                tracer.emit(
                    ctx, "broker.wait", enq_ts, _time.time(),
                    node=getattr(self, "node_name", ""),
                    eval_id=ev.id, sched=ev.type)
                tracer.note_eval(ev.id, ctx)
        return ev, token

    def dequeue(self, schedulers: List[str], timeout: float = 0.0
                ) -> Tuple[Optional[Evaluation], str]:
        """-> (eval, token) or (None, '')."""
        deadline = _time.time() + timeout
        with self._lock:
            while True:
                self._poll_timers_locked()
                if request_deadline.check("broker"):
                    # the caller's end-to-end budget died waiting: the
                    # checked-before-pick order means no lease is ever
                    # minted for a doomed dequeue — the eval stays
                    # queued for a caller that can still use it
                    return None, ""
                got = self._pick_locked(schedulers)
                if got is not None:
                    return got
                remaining = deadline - _time.time()
                budget = request_deadline.remaining()
                if budget is not None:
                    remaining = min(remaining, budget)
                if remaining <= 0:
                    return None, ""
                # wake early enough to serve delay heaps
                wake = min(remaining, 0.05)
                self._lock.wait(wake)

    def dequeue_batch(self, schedulers: List[str], max_n: int,
                      timeout: float = 0.0
                      ) -> List[Tuple[Evaluation, str]]:
        """Wave dequeue: block up to `timeout` for the FIRST ready eval,
        then drain up to max_n in the SAME lock pass — one fair pick and
        one lease per eval, so fairness accounting and job dedup are
        byte-identical to max_n sequential dequeues.  Never waits for
        the batch to fill: a shallow queue returns what exists so wave
        batching can't add latency when traffic is light."""
        deadline = _time.time() + timeout
        out: List[Tuple[Evaluation, str]] = []
        with self._lock:
            while True:
                self._poll_timers_locked()
                if request_deadline.check("broker"):
                    # caller's budget exhausted: mint nothing (see
                    # dequeue) — anything already picked this pass is
                    # still leased and returned, never half-dropped
                    return out
                while len(out) < max_n:
                    got = self._pick_locked(schedulers)
                    if got is None:
                        break
                    out.append(got)
                if out:
                    return out
                remaining = deadline - _time.time()
                budget = request_deadline.remaining()
                if budget is not None:
                    remaining = min(remaining, budget)
                if remaining <= 0:
                    return out
                self._lock.wait(min(remaining, 0.05))

    # ------------------------------------------------------------- ack/nack

    def ack(self, eval_id: str, token: str) -> bool:
        with self._lock:
            race.write("EvalBroker._unack", self)
            lease = self._unack.get(token)
            if lease is None or lease.eval.id != eval_id:
                return False
            del self._unack[token]
            self._attempts.pop(eval_id, None)
            ev = lease.eval
            key = (ev.namespace, ev.job_id)
            self._active_jobs.discard(key)
            self._release_pending_locked(key)
            self.stats["acked"] += 1
            self._lock.notify_all()
            return True

    def nack(self, eval_id: str, token: str) -> bool:
        with self._lock:
            race.write("EvalBroker._unack", self)
            lease = self._unack.get(token)
            if lease is None or lease.eval.id != eval_id:
                return False
            del self._unack[token]
            ev = lease.eval
            # the job stays active: the eval will re-enter the ready queue
            # (or dead-letter, which releases it in _nack_locked)
            self._nack_locked(ev)
            self._lock.notify_all()
            return True

    @requires_lock("_lock")
    def _nack_locked(self, ev: Evaluation, requeue_now: bool = False) -> None:
        self._attempts[ev.id] += 1
        attempts = self._attempts[ev.id]
        if attempts >= self.delivery_limit:
            # dead-letter: hand to the failed queue for the leader reaper
            # and release the job so a fresh eval can be scheduled
            self._active_jobs.discard((ev.namespace, ev.job_id))
            self._release_pending_locked((ev.namespace, ev.job_id))
            heap = self._ns_ready[FAILED_QUEUE].setdefault(ev.namespace, [])
            if not heap:
                self._ns_nonempty[FAILED_QUEUE].add(ev.namespace)
            heapq.heappush(heap, (-ev.priority, next(self._counter), ev))
            self.stats["failed"] += 1
            return
        delay = (self.initial_nack_delay if attempts == 1
                 else self.subsequent_nack_delay)
        if requeue_now:
            delay = 0.0
        heapq.heappush(self._requeued,
                       (_time.time() + delay, next(self._counter), ev))
        self.stats["nacked"] += 1

    @requires_lock("_lock")
    def _release_pending_locked(self, key: Tuple[str, str]) -> None:
        pending = self._pending.get(key)
        if pending:
            nxt = pending.popleft()
            if not pending:
                del self._pending[key]
            self._enqueue_locked(nxt)

    # ------------------------------------------------------------- inspect

    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            # settle expired leases first so a stale token is never
            # reported as live (the plan-submit gate relies on this)
            self._poll_timers_locked()
            race.read("EvalBroker._unack", self)
            for token, lease in self._unack.items():
                if lease.eval.id == eval_id:
                    return token
        return None

    def outstanding_reset(self, eval_id: str, token: str) -> bool:
        """Extend the lease (reference OutstandingReset for long scheds)."""
        with self._lock:
            race.write("EvalBroker._unack", self)
            lease = self._unack.get(token)
            if lease is None or lease.eval.id != eval_id:
                return False
            lease.expires_at = _time.time() + self.nack_timeout
            return True

    def unacked_count(self) -> int:
        with self._lock:
            race.read("EvalBroker._unack", self)
            return len(self._unack)

    def ready_count(self) -> int:
        with self._lock:
            self._poll_timers_locked()
            return sum(len(q)
                       for s, per_ns in self._ns_ready.items()
                       if s != FAILED_QUEUE
                       for q in per_ns.values())

    def fair_stats(self) -> dict:
        """broker.fair_* telemetry snapshot: per-namespace pass/weight
        plus runnable namespace count."""
        with self._lock:
            runnable = set()
            for nss in self._ns_nonempty.values():
                runnable |= nss
            return {
                "enabled": self._fair_enabled,
                "runnable_namespaces": len(runnable),
                "pass": dict(self._fair_pass),
                "weights": dict(self._fair_weights),
                "default_weight": self._fair_default_weight,
                "picks": self.stats["fair_picks"],
                "bypassed": self.stats["fair_bypassed"],
            }


class EvalWaveFeeder:
    """Wave-aligned front of `EvalBroker.dequeue` for a local worker
    pool.

    Whichever worker finds the shared buffer empty becomes the filler
    and drains a whole ready wave in ONE broker lock pass
    (`dequeue_batch`); its peers take from the buffered wave without
    touching the broker at all.  A burst of ready evals therefore
    reaches every scheduler at the same instant — instead of
    arrival-jittered single dequeues — so the PlacementEngine's
    dispatch coalescing sees full-wave batches end to end (broker wave
    -> scheduler pool -> one fused device dispatch).

    Buffered entries already hold their lease: the filler hands them to
    peers within one scheduling pass (the wave is bounded by the pool
    size), far inside the nack timeout, and `close()` nacks anything
    still buffered at teardown so shutdown never strands a lease.
    """

    def __init__(self, broker: EvalBroker, max_n: int = 48):
        self.broker = broker
        self.max_n = max(1, max_n)
        self._lock = threading.Condition()
        self._buf: Dict[tuple, deque] = {}
        self._filling: Set[tuple] = set()
        # wave_ns_max: peak count of DISTINCT namespaces in one wave —
        # the 2-D mesh's wave-lane parallelism feeds on exactly this
        # diversity (engine lane binning keys on the eval's namespace)
        self.stats = {"waves": 0, "wave_evals": 0, "max_wave": 0,
                      "wave_ns_max": 0}

    def get(self, schedulers: List[str], timeout: float = 0.1
            ) -> Optional[Tuple[Evaluation, str]]:
        key = tuple(schedulers)
        deadline = _time.time() + timeout
        with self._lock:
            while True:
                buf = self._buf.get(key)
                if buf:
                    return buf.popleft()
                if key not in self._filling:
                    self._filling.add(key)
                    break
                remaining = deadline - _time.time()
                if remaining <= 0:
                    return None
                self._lock.wait(min(remaining, 0.05))
        wave: List[Tuple[Evaluation, str]] = []
        try:
            wave = self.broker.dequeue_batch(
                list(key), self.max_n,
                timeout=max(0.0, deadline - _time.time()))
        finally:
            with self._lock:
                self._filling.discard(key)
                if len(wave) > 1:
                    self._buf.setdefault(key, deque()).extend(wave[1:])
                if wave:
                    self.stats["waves"] += 1
                    self.stats["wave_evals"] += len(wave)
                    self.stats["max_wave"] = max(self.stats["max_wave"],
                                                 len(wave))
                    self.stats["wave_ns_max"] = max(
                        self.stats["wave_ns_max"],
                        len({ev.namespace for ev, _ in wave}))
                self._lock.notify_all()
        return wave[0] if wave else None

    def close(self) -> None:
        """Nack every still-buffered lease (leadership loss / stop)."""
        with self._lock:
            bufs, self._buf = self._buf, {}
        for buf in bufs.values():
            for ev, token in buf:
                try:
                    self.broker.nack(ev.id, token)
                except Exception:                   # noqa: BLE001
                    pass
