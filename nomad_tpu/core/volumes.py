"""CSI volume watcher (reference nomad/volumewatcher/volumes_watcher.go +
volume_watcher.go): a leader-side control loop that releases volume
claims as their allocations terminate, so blocked single-writer volumes
become schedulable again without operator action.

The reference runs one goroutine per volume fed by blocking queries; here
one thread drains a queue fed by the store's watch hook (alloc and volume
table changes both trigger a sweep of the affected volume)."""
from __future__ import annotations

import logging
import threading
from typing import List, Optional

from nomad_tpu.raft.fsm import MessageType
from nomad_tpu.structs import csi as csistructs

log = logging.getLogger(__name__)


class VolumeWatcher:
    def __init__(self, server):
        self.server = server
        self._queue: List[object] = []     # volumes to (re)check
        self._cv = threading.Condition()
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        server.store.watch(self.watch_state)

    # ------------------------------------------------------------- wiring

    def watch_state(self, table: str, obj) -> None:
        if self._stop is None or self._stop.is_set():
            return
        if table == "csi_volumes":
            self._enqueue(obj)
        elif table == "allocs" and obj.terminal_status():
            # find volumes claimed by this alloc
            store = self.server.store
            with store._lock:
                vols = [v for v in store._csi_volumes.values()
                        if obj.id in v.read_claims
                        or obj.id in v.write_claims]
            for v in vols:
                self._enqueue(v)

    def _enqueue(self, vol) -> None:
        with self._cv:
            self._queue.append(vol)
            self._cv.notify()

    def start(self) -> None:
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="volume-watcher", daemon=True)
        self._thread.start()
        # initial sweep: claims whose allocs died while there was no leader
        for vol in self.server.store.csi_volumes():
            self._enqueue(vol)

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        with self._cv:
            self._cv.notify()
        if self._thread is not None:
            self._thread.join(1.0)
            self._thread = None

    # ------------------------------------------------------------- loop

    def _run(self) -> None:
        stop = self._stop
        while not stop.is_set():
            with self._cv:
                while not self._queue and not stop.is_set():
                    self._cv.wait(timeout=0.5)
                vols, self._queue = self._queue, []
            seen = set()
            for vol in vols:
                key = (vol.namespace, vol.id)
                if key in seen:
                    continue
                seen.add(key)
                try:
                    self._reap(vol)
                except Exception:               # noqa: BLE001
                    log.exception("volume watcher: reap %s failed", vol.id)

    def _reap(self, vol) -> None:
        """volumeReapImpl: release claims held by terminal or vanished
        allocations (volume_watcher.go)."""
        store = self.server.store
        fresh = store.csi_volume_by_id(vol.namespace, vol.id)
        if fresh is None:
            return
        for alloc_id in list(fresh.read_claims) + list(fresh.write_claims):
            alloc = store.alloc_by_id(alloc_id)
            if alloc is None or alloc.terminal_status():
                claim = fresh.read_claims.get(alloc_id) or \
                    fresh.write_claims.get(alloc_id)
                self.server.apply(MessageType.CSI_VOLUME_CLAIM, {
                    "namespace": fresh.namespace,
                    "volume_id": fresh.id,
                    "claim": csistructs.CSIVolumeClaim(
                        alloc_id=alloc_id,
                        node_id=claim.node_id if claim else "",
                        mode=claim.mode if claim else csistructs.CLAIM_READ,
                        state=csistructs.CLAIM_STATE_READY_TO_FREE),
                })
                # capacity change: a blocked single-writer job can go again
                self.server.blocked_evals.unblock_all(store.latest_index)
