"""Blocked evaluations (reference: nomad/blocked_evals.go — Block:151,
Unblock:403, UnblockNode:486, watchCapacity:507, GetDuplicates:632).

Evals that failed to place all allocations wait here and re-enter the
broker when capacity changes: keyed by computed node class (an eval records
which classes it found eligible/ineligible; an unseen class unblocks it),
by quota, or by node id (for system evals).
"""
from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.structs import Evaluation, EvalStatus


class BlockedStats:
    def __init__(self):
        self.total_blocked = 0
        self.total_escaped = 0
        self.total_quota_limit = 0


class BlockedEvals:
    def __init__(self, broker):
        self._lock = threading.Lock()
        self.broker = broker
        self.enabled = False
        # eval_id -> eval
        self._captured: Dict[str, Evaluation] = {}
        # escaped evals bypass class tracking (had non-class constraints)
        self._escaped: Set[str] = set()
        # job key -> eval id (one blocked eval per job; dupes cancelled)
        self._jobs: Dict[Tuple[str, str], str] = {}
        self._duplicates: List[Evaluation] = []
        # eval ids already given their one overlay-drain second chance
        self._drain_woken: Set[str] = set()
        # (namespace, job) of evals blocked on quota -> quota name
        self._quota: Dict[str, Set[str]] = {}
        # per-class (and global) capacity-change indexes for missed-unblock
        # detection (reference blocked_evals.go unblockIndexes/missedUnblock):
        # a capacity event that fires between an eval's snapshot and its
        # block() call must immediately requeue it instead of blocking.
        self._unblock_indexes: Dict[str, int] = {}
        # quota name -> index of the last quota-spec change; a quota raise
        # that lands between an eval's snapshot and its block() call must
        # requeue it (mirrors the class-keyed table above)
        self._quota_unblock_indexes: Dict[str, int] = {}
        self._global_unblock_index = 0
        self.stats = BlockedStats()

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self.enabled = enabled
            if not enabled:
                self._captured.clear()
                self._escaped.clear()
                self._jobs.clear()
                self._duplicates.clear()
                self._quota.clear()

    # ------------------------------------------------------------- block

    def _missed_unblock_locked(self, ev: Evaluation) -> bool:
        """Did a relevant capacity change land after this eval's snapshot?"""
        if self._global_unblock_index > ev.snapshot_index:
            return True
        if ev.quota_limit_reached:
            qidx = self._quota_unblock_indexes.get(ev.quota_limit_reached, 0)
            if qidx > ev.snapshot_index:
                return True
        elig = ev.class_eligibility or {}
        for cls, idx in self._unblock_indexes.items():
            if idx <= ev.snapshot_index:
                continue
            if ev.escaped_computed_class:
                return True
            if cls not in elig or elig.get(cls):
                return True
        return False

    def block(self, ev: Evaluation) -> None:
        with self._lock:

            if not self.enabled:
                return
            if self._missed_unblock_locked(ev):
                # capacity changed between the eval's snapshot and now:
                # requeue immediately instead of blocking forever
                latest = max([self._global_unblock_index,
                              *self._unblock_indexes.values(),
                              *self._quota_unblock_indexes.values()])
                missed = ev
            else:
                missed = None
            key = (ev.namespace, ev.job_id)
            prior_id = self._jobs.get(key)
            if prior_id is not None:
                prior = self._captured.get(prior_id)
                # keep the newer eval, cancel the older as duplicate
                if prior is not None:
                    if prior.create_index <= ev.create_index:
                        self._drop_locked(prior_id)
                        self._duplicates.append(prior)
                    else:
                        self._duplicates.append(ev)
                        return
            if missed is not None:
                self._lock.release()
                try:
                    self._requeue([missed], latest)
                finally:
                    self._lock.acquire()
                return
            self._captured[ev.id] = ev
            self._jobs[key] = ev.id
            if ev.escaped_computed_class:
                self._escaped.add(ev.id)
                self.stats.total_escaped += 1
            if ev.quota_limit_reached:
                self._quota.setdefault(ev.quota_limit_reached, set()).add(ev.id)
                self.stats.total_quota_limit += 1
            self.stats.total_blocked += 1

    def _drop_locked(self, eval_id: str) -> None:
        ev = self._captured.pop(eval_id, None)
        if ev is None:
            return
        self._escaped.discard(eval_id)
        key = (ev.namespace, ev.job_id)
        if self._jobs.get(key) == eval_id:
            del self._jobs[key]
        for s in self._quota.values():
            s.discard(eval_id)
        self.stats.total_blocked -= 1

    def untrack(self, namespace: str, job_id: str) -> None:
        """Job deregistered: forget its blocked eval (reference Untrack)."""
        with self._lock:
            eid = self._jobs.get((namespace, job_id))
            if eid:
                self._drop_locked(eid)

    # ------------------------------------------------------------- unblock

    def unblock(self, computed_class: str, index: int) -> List[Evaluation]:
        """Capacity became available in `computed_class` (node registered /
        drained alloc freed / alloc stopped).  Returns the released evals
        (they are also re-enqueued into the broker)."""
        with self._lock:
            if not self.enabled:
                return []
            self._unblock_indexes[computed_class] = max(
                index, self._unblock_indexes.get(computed_class, 0))
            self._drain_woken.clear()   # real change: re-arm second chances
            to_release = []
            for eid, ev in list(self._captured.items()):
                if eid in self._escaped:
                    to_release.append(ev)
                    continue
                elig = ev.class_eligibility or {}
                seen = computed_class in elig
                if not seen or elig.get(computed_class):
                    # unseen class: might be feasible now; eligible class:
                    # new capacity
                    to_release.append(ev)
            for ev in to_release:
                self._drop_locked(ev.id)
        self._requeue(to_release, index)
        return to_release

    def unblock_all(self, index: int) -> List[Evaluation]:
        with self._lock:
            self._global_unblock_index = max(self._global_unblock_index, index)
            released = list(self._captured.values())
            for ev in released:
                self._drop_locked(ev.id)
            self._drain_woken.clear()   # real change: re-arm second chances
        self._requeue(released, index)
        return released

    def unblock_once(self, index: int) -> List[Evaluation]:
        """Requeue blocked evals that have not been woken by this path
        before (one second chance per blocked instance).  Used by the
        engine's overlay-drain hook: an eval that failed against phantom
        in-flight usage deserves one clean retry, but a genuinely
        unplaceable eval must not ping-pong forever."""
        with self._lock:
            released = [ev for ev in self._captured.values()
                        if ev.id not in self._drain_woken]
            for ev in released:
                self._drain_woken.add(ev.id)
                self._drop_locked(ev.id)
        self._requeue(released, index)
        return released

    def unblock_quota(self, quota: str, index: int) -> List[Evaluation]:
        with self._lock:
            self._quota_unblock_indexes[quota] = max(
                index, self._quota_unblock_indexes.get(quota, 0))
            self._drain_woken.clear()   # real change: re-arm second chances
            ids = list(self._quota.get(quota, ()))
            released = [self._captured[i] for i in ids if i in self._captured]
            for ev in released:
                self._drop_locked(ev.id)
        self._requeue(released, index)
        return released

    def _requeue(self, evals: List[Evaluation], index: int) -> None:
        for ev in evals:
            e = ev.copy()
            e.status = EvalStatus.PENDING
            e.snapshot_index = index
            self.broker.enqueue(e)

    # ------------------------------------------------------------- readers

    def get_duplicates(self) -> List[Evaluation]:
        with self._lock:
            dups = self._duplicates
            self._duplicates = []
            return dups

    def blocked_count(self) -> int:
        with self._lock:
            return len(self._captured)

    def watch_state(self, table: str, obj) -> None:
        """StateStore watcher hook: node capacity changes unblock by class
        (reference watchCapacity fed by the FSM)."""
        if table != "nodes":
            return
        node = obj
        if node.ready():
            self.unblock(node.computed_class, getattr(node, "modify_index", 0))
