"""Gossip membership (reference: nomad/serf.go — hashicorp/serf's SWIM
gossip giving member discovery, failure detection and leave events).

SWIM-lite over the cluster transport: each member keeps a table
{name -> (addr, incarnation, status, heard_at)} and periodically syncs it
with one random live peer (push-pull, the dominant convergence mechanism
in SWIM); an unreachable peer is marked suspect after `suspect_after`
without contact and failed after `fail_after`.  A member that learns it
is suspected refutes by bumping its own incarnation (SWIM's refutation).
Addresses learned from the table feed the transport's address book, so a
member only needs ONE seed address to join a cluster.

Flap/rejoin correctness (serf's refutation + tombstones):

- A member that restarts with a STALE incarnation (fresh process, inc 0)
  re-asserts aliveness past any lingering ``SUSPECT``/``FAILED``/``LEFT``
  entry about itself: seeing such an entry at ``inc >= mine`` bumps its
  own incarnation past it, so the next gossip round's ``ALIVE`` outranks
  the stale claim.
- ``LEFT``/``FAILED`` entries are reaped from the table after
  ``reap_after`` into incarnation tombstones: an old push-pull sync
  carrying a pre-leave ``ALIVE`` entry cannot resurrect the member —
  only the member itself rejoining with a HIGHER incarnation clears the
  tombstone.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from nomad_tpu import chaos
from nomad_tpu.analysis import race

log = logging.getLogger(__name__)

ALIVE, SUSPECT, FAILED, LEFT = "alive", "suspect", "failed", "left"


@dataclass
class Member:
    name: str
    addr: Tuple[str, int]
    incarnation: int = 0
    status: str = ALIVE
    heard_at: float = field(default_factory=time.monotonic)
    # gossiped key/value metadata (serf tags): the WAN pool rides region
    # and leader-ness here.  Tags travel with the incarnation — a member
    # re-tags itself by bumping its own incarnation, so the new tags
    # outrank every older entry in other tables.
    tags: Dict[str, object] = field(default_factory=dict)

    def wire(self) -> dict:
        return {"name": self.name, "addr": tuple(self.addr),
                "incarnation": self.incarnation, "status": self.status,
                "tags": dict(self.tags)}


class Membership:
    # the member table and tombstones move under `self._lock` only; the
    # happens-before checker cross-checks the race hooks below
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"members"})
    _RACE_TRACED = {"members": "_lock"}

    def __init__(self, transport, name: str, addr: Tuple[str, int],
                 interval: float = 0.2, suspect_after: float = 1.0,
                 fail_after: float = 3.0, reap_after: float = 5.0,
                 on_change: Optional[Callable[[Member], None]] = None,
                 channel: str = "gossip",
                 tags: Optional[Dict[str, object]] = None):
        self.transport = transport
        self.name = name
        self.interval = interval
        self.suspect_after = suspect_after
        self.fail_after = fail_after
        self.reap_after = reap_after
        self.on_change = on_change or (lambda m: None)
        # handler-name prefix: the LAN pool owns "gossip:{name}"; a
        # second pool on the same transport (the WAN federation pool)
        # picks a distinct channel so both can coexist on one member
        # (TcpTransport maps any "prefix:name" to the member's address)
        self.channel = channel
        self._lock = threading.Lock()
        self.members: Dict[str, Member] = {
            name: Member(name=name, addr=tuple(addr),
                         tags=dict(tags or {}))}
        # name -> last seen incarnation of a reaped LEFT/FAILED member:
        # inserts at <= that incarnation are stale resurrections
        self._tombstones: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        transport.register(f"{channel}:{name}", self._handle)

    # ------------------------------------------------------------- admin

    def join(self, seeds: List[Tuple[str, Tuple[str, int]]]) -> None:
        """Seed the member table with (name, addr) pairs and sync once."""
        chaos.maybe_delay("member.join_stall")
        with self._lock:
            race.write("Membership.members", self)
            for name, addr in seeds:
                if name != self.name and name not in self.members:
                    self.members[name] = Member(name=name, addr=tuple(addr))
                if hasattr(self.transport, "add_peer"):
                    self.transport.add_peer(name, addr)
        self._gossip_once()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"gossip-{self.name}")
        self._thread.start()

    def leave(self) -> None:
        """Graceful leave: bump incarnation, broadcast LEFT, stop."""
        with self._lock:
            me = self.members[self.name]
            me.incarnation += 1
            me.status = LEFT
        for peer in self._peers():
            try:
                self.transport.call(self.name,
                                    f"{self.channel}:{peer.name}",
                                    "sync", {"table": self._wire_table()})
            except Exception:                       # noqa: BLE001
                pass
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(1.0)
        self.transport.deregister(f"{self.channel}:{self.name}")

    def set_tags(self, tags: Dict[str, object]) -> None:
        """Re-tag this member (serf SetTags).  Bumps our incarnation so
        the change outranks every older entry about us and propagates on
        the next gossip round (leader changes ride this)."""
        with self._lock:
            race.write("Membership.members", self)
            me = self.members[self.name]
            if dict(tags) == me.tags:
                return
            me.tags = dict(tags)
            me.incarnation += 1

    def alive_members(self) -> List[Member]:
        with self._lock:
            return [m for m in self.members.values() if m.status == ALIVE]

    def member_list(self) -> List[dict]:
        with self._lock:
            race.read("Membership.members", self)
            return [m.wire() for m in
                    sorted(self.members.values(), key=lambda m: m.name)]

    # ------------------------------------------------------------- loop

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._gossip_once()
                self._sweep()
            except Exception:                       # noqa: BLE001
                log.debug("gossip round failed", exc_info=True)

    def _peers(self) -> List[Member]:
        with self._lock:
            return [m for m in self.members.values()
                    if m.name != self.name and m.status in (ALIVE, SUSPECT)]

    def _gossip_once(self) -> None:
        peers = self._peers()
        if not peers:
            return
        peer = random.choice(peers)
        try:
            resp = self.transport.call(
                self.name, f"{self.channel}:{peer.name}", "sync",
                {"table": self._wire_table()})
            self._merge(resp.get("table", []))
            with self._lock:
                m = self.members.get(peer.name)
                if m is not None:
                    m.heard_at = time.monotonic()
                    if m.status == SUSPECT:
                        self._set_status(m, ALIVE)
        except Exception:                           # noqa: BLE001
            pass   # the sweep drives suspicion from silence

    def _sweep(self) -> None:
        now = time.monotonic()
        with self._lock:
            race.write("Membership.members", self)
            for m in list(self.members.values()):
                if m.name == self.name:
                    continue
                silent = now - m.heard_at
                if m.status in (FAILED, LEFT):
                    if silent > self.reap_after:
                        # reap into a tombstone: the name disappears from
                        # the table but its incarnation keeps gating
                        # stale resurrections (old syncs carrying a
                        # pre-leave ALIVE entry)
                        self._tombstones[m.name] = max(
                            m.incarnation,
                            self._tombstones.get(m.name, -1))
                        del self.members[m.name]
                    continue
                if m.status == ALIVE and silent > self.suspect_after:
                    self._set_status(m, SUSPECT)
                elif m.status == SUSPECT and silent > self.fail_after:
                    self._set_status(m, FAILED)

    # ------------------------------------------------------------- merge

    def _handle(self, method: str, args: dict) -> dict:
        if method != "sync":
            raise ValueError(f"unknown gossip method {method}")
        self._merge(args.get("table", []))
        return {"table": self._wire_table()}

    def _wire_table(self) -> List[dict]:
        with self._lock:
            return [m.wire() for m in self.members.values()]

    def _merge(self, table: List[dict]) -> None:
        with self._lock:
            race.write("Membership.members", self)
            for entry in table:
                name = entry["name"]
                inc = entry["incarnation"]
                status = entry["status"]
                if name == self.name:
                    # SWIM refutation: someone thinks we're gone — bump
                    # our incarnation so ALIVE outranks their claim.
                    # LEFT counts too: a member that left and restarted
                    # at incarnation 0 could otherwise NEVER rejoin (the
                    # lingering LEFT outranks everything at its inc).
                    # While we are deliberately leaving, don't refute —
                    # that would resurrect us mid-goodbye.
                    me = self.members[self.name]
                    if me.status != LEFT \
                            and status in (SUSPECT, FAILED, LEFT) \
                            and inc >= me.incarnation:
                        me.incarnation = inc + 1
                    continue
                cur = self.members.get(name)
                if cur is None:
                    # tombstone gate: a reaped LEFT/FAILED member may only
                    # come back with a strictly higher incarnation (a
                    # genuine rejoin); an old sync replaying the pre-leave
                    # entry is dropped here
                    tomb = self._tombstones.get(name)
                    if tomb is not None:
                        if inc <= tomb:
                            continue
                        del self._tombstones[name]
                    cur = self.members[name] = Member(
                        name=name, addr=tuple(entry["addr"]),
                        incarnation=inc, status=status,
                        tags=dict(entry.get("tags") or {}))
                    if hasattr(self.transport, "add_peer"):
                        self.transport.add_peer(name, cur.addr)
                    self.on_change(cur)
                    continue
                # higher incarnation always wins; same incarnation:
                # dead-ish states override alive (SWIM precedence)
                rank = {ALIVE: 0, SUSPECT: 1, FAILED: 2, LEFT: 3}
                if inc > cur.incarnation or (
                        inc == cur.incarnation
                        and rank[status] > rank[cur.status]):
                    cur.incarnation = inc
                    # tags ride the incarnation: the winning entry's tags
                    # are by construction at least as fresh as ours
                    cur.tags = dict(entry.get("tags") or {})
                    new_addr = tuple(entry["addr"])
                    if new_addr != cur.addr:
                        # a member that came back on a new port: refresh
                        # the transport address book, not just the table
                        cur.addr = new_addr
                        if hasattr(self.transport, "add_peer"):
                            self.transport.add_peer(name, new_addr)
                    if status != cur.status:
                        self._set_status(cur, status)
                    if status == ALIVE:
                        cur.heard_at = time.monotonic()
                elif inc == cur.incarnation and not cur.tags \
                        and entry.get("tags"):
                    # a join() seed is a bare (name, addr) stub with no
                    # tags at incarnation 0 — the member's own entry at
                    # the SAME incarnation carries its real tags, and
                    # adopting them is monotone (empty -> the one
                    # tag-set anyone has published at this incarnation)
                    cur.tags = dict(entry["tags"])

    def _set_status(self, m: Member, status: str) -> None:
        m.status = status
        if status == ALIVE:
            m.heard_at = time.monotonic()
        try:
            self.on_change(m)
        except Exception:                           # noqa: BLE001
            log.debug("membership on_change failed", exc_info=True)
