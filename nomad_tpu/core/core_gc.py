"""Core scheduler: internal GC job (reference: nomad/core_sched.go —
CoreScheduler.Process:44, jobGC:94, evalGC:231, nodeGC:434,
deploymentGC:545; enqueued by the leader's periodic timers,
leader.go:782-810).

Eval types: 'job-gc', 'eval-gc', 'node-gc', 'deployment-gc', or the
'force-gc' catch-all.
"""
from __future__ import annotations

import time as _time
from typing import List, Optional

from nomad_tpu.raft import MessageType
from nomad_tpu.structs import EvalStatus, JobStatus, JobType
from nomad_tpu.structs.deployment import DeploymentStatus
from nomad_tpu.structs.node import NodeStatus

JOB_GC_THRESHOLD = 4 * 3600.0
EVAL_GC_THRESHOLD = 1 * 3600.0
NODE_GC_THRESHOLD = 24 * 3600.0
DEPLOYMENT_GC_THRESHOLD = 1 * 3600.0


class CoreScheduler:
    """Registered under the '_core' job type; processes GC evals."""

    def __init__(self, server):
        self.server = server

    def process(self, gc_type: str, now: Optional[float] = None,
                force: bool = False) -> dict:
        now = now if now is not None else _time.time()
        stats = {}
        if gc_type in ("eval-gc", "force-gc"):
            stats["evals"] = self.eval_gc(now, force)
        if gc_type in ("job-gc", "force-gc"):
            stats["jobs"] = self.job_gc(now, force)
        if gc_type in ("node-gc", "force-gc"):
            stats["nodes"] = self.node_gc(now, force)
        if gc_type in ("deployment-gc", "force-gc"):
            stats["deployments"] = self.deployment_gc(now, force)
        if gc_type in ("service-gc", "force-gc"):
            stats["services"] = self.service_gc()
        return stats

    # ------------------------------------------------------------- passes

    def _old_enough(self, ts: float, now: float, threshold: float,
                    force: bool) -> bool:
        return force or (ts and now - ts >= threshold)

    def eval_gc(self, now: float, force: bool = False) -> int:
        """Terminal evals (and their terminal allocs) past the threshold."""
        store = self.server.store
        gc_evals, gc_allocs = [], []
        for ev in store.evals():
            if not ev.terminal():
                continue
            if not self._old_enough(ev.modify_time or ev.create_time, now,
                                    EVAL_GC_THRESHOLD, force):
                continue
            allocs = store.allocs_by_eval(ev.id)
            if all(a.terminal_status() for a in allocs):
                gc_evals.append(ev.id)
                gc_allocs.extend(a.id for a in allocs)
        if gc_evals:
            self.server.apply(MessageType.EVAL_DELETE,
                              {"eval_ids": gc_evals, "alloc_ids": gc_allocs})
        return len(gc_evals)

    def job_gc(self, now: float, force: bool = False) -> int:
        """Dead jobs with only terminal allocs and terminal evals."""
        store = self.server.store
        n = 0
        for job in store.jobs():
            if job.status != JobStatus.DEAD and not job.stop:
                continue
            if job.is_periodic() and not job.stop:
                continue
            if not self._old_enough(job.submit_time, now, JOB_GC_THRESHOLD,
                                    force):
                continue
            allocs = store.allocs_by_job(job.namespace, job.id)
            evals = store.evals_by_job(job.namespace, job.id)
            if all(a.terminal_status() for a in allocs) and \
                    all(e.terminal() for e in evals):
                self.server.apply(MessageType.EVAL_DELETE,
                                  {"eval_ids": [e.id for e in evals],
                                   "alloc_ids": [a.id for a in allocs]})
                self.server.apply(MessageType.JOB_DEREGISTER,
                                  {"namespace": job.namespace,
                                   "job_id": job.id, "purge": True})
                n += 1
        return n

    def node_gc(self, now: float, force: bool = False) -> int:
        """Down nodes with no non-terminal allocs."""
        store = self.server.store
        n = 0
        for node in store.nodes():
            if node.status != NodeStatus.DOWN:
                continue
            if not self._old_enough(node.status_updated_at, now,
                                    NODE_GC_THRESHOLD, force):
                continue
            if any(not a.terminal_status()
                   for a in store.allocs_by_node(node.id)):
                continue
            self.server.apply(MessageType.NODE_DEREGISTER,
                              {"node_id": node.id})
            n += 1
        return n

    def service_gc(self) -> int:
        """Orphaned nomad-service registrations: a client that dies
        without deregistering leaves rows behind; sweep any registration
        whose allocation is gone or terminal (reference
        core_sched.go csiPluginGC analog for service_registrations)."""
        store = self.server.store
        doomed = []
        for sr in store.services():
            a = store.alloc_by_id(sr.alloc_id)
            if a is None or a.terminal_status():
                doomed.append(sr.id)
        if doomed:
            self.server.apply(MessageType.SERVICE_DEREGISTER,
                              {"ids": doomed})
        return len(doomed)

    def deployment_gc(self, now: float, force: bool = False) -> int:
        store = self.server.store
        n = 0
        for d in store.deployments():
            if d.active():
                continue
            if not self._old_enough(d.modify_time or d.create_time, now,
                                    DEPLOYMENT_GC_THRESHOLD, force):
                continue
            self.server.apply(MessageType.DEPLOYMENT_DELETE,
                              {"deployment_id": d.id})
            n += 1
        return n
