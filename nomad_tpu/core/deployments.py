"""Deployment watcher (reference: nomad/deploymentwatcher/ —
deployments_watcher.go:60 Watcher, deployment_watcher.go per-deployment
logic): drives rolling updates, canary auto-promotion, auto-revert, and
progress deadlines by watching allocation health and emitting evaluations.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional

from nomad_tpu.raft import MessageType
from nomad_tpu.structs import (
    AllocClientStatus,
    Deployment,
    DeploymentStatus,
    Evaluation,
    EvalStatus,
)
from nomad_tpu.structs.evaluation import EvalTrigger


def _stamp(d: Deployment) -> Deployment:
    """Propose-time timestamps: they ride in the raft log payload so the
    FSM never reads the clock (replicas/replay must agree byte-for-byte;
    see nomad_tpu.analysis fsm-determinism)."""
    d.modify_time = _time.time()
    if not d.create_time:
        d.create_time = d.modify_time
    return d


class DeploymentWatcher:
    def __init__(self, server, interval: float = 0.1):
        self.server = server
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dirty = threading.Event()
        # subscribe to alloc/deployment changes
        server.store.watch(self._on_change)

    def start(self) -> None:
        self._stop = threading.Event()   # fresh per leadership tenure
        self._thread = threading.Thread(target=self._run, name="deploy-watcher",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        if self._thread:
            self._thread.join(1.0)

    def _on_change(self, table: str, obj) -> None:
        if table in ("allocs", "deployments"):
            self._dirty.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait(timeout=self.interval)
            self._dirty.clear()
            if self._stop.is_set():
                return
            try:
                self.reconcile_all()
            except Exception:               # noqa: BLE001
                import logging
                logging.getLogger(__name__).exception("deployment watcher")

    # ------------------------------------------------------------- logic

    def reconcile_all(self, now: Optional[float] = None) -> None:
        now = now if now is not None else _time.time()
        for d in self.server.store.deployments():
            if d.active():
                self._reconcile(d, now)
            elif d.status == DeploymentStatus.FAILED:
                self._retry_revert(d)
            elif d.status == DeploymentStatus.SUCCESSFUL \
                    and d.is_multiregion and not d.multiregion_kicked:
                # sequential multiregion rollout: this region is healthy,
                # start the NEXT region.  Retried every pass until the
                # kick lands — a partitioned next region halts the
                # rollout here and it resumes after heal.
                self._kick_next_region(d)

    def _retry_revert(self, d: Deployment) -> None:
        """A FAILED auto-revert deployment whose revert register_job was
        lost (leadership churn or partition between the FAILED upsert and
        the revert landing) leaves the job stuck on the bad version with
        nothing to retry it — the deployment is no longer active, so
        _reconcile never sees it again.  Retry while the job still sits
        at the deployment's version.  Any version advance (the revert
        landing, or a newer registration) makes this a no-op, so a
        re-entered watcher pass can never double-revert or touch a
        deployment that has been superseded."""
        if not any(s.auto_revert for s in d.task_groups.values()):
            return
        server = self.server
        job = server.store.job_by_id(d.namespace, d.job_id)
        if job is None or job.stop or job.version != d.job_version:
            return
        stable = self._latest_stable(d.namespace, d.job_id, d.job_version)
        if stable is not None:
            server.register_job(stable.copy())

    def _kick_next_region(self, d: Deployment) -> None:
        """Sequential multiregion rollout (reference: nomad multiregion
        deployments): region N+1 is registered only once region N's
        deployment went SUCCESSFUL.  Best-effort cross-region RPC — if
        the next region is dark the kick is simply retried on the next
        watcher pass, so a partition halts the rollout at the region
        boundary without corrupting anything, and it resumes after heal.
        The kicked flag is replicated so a new leader never double-kicks;
        a Job.GetJob probe makes the kick idempotent even if the flag
        write itself was lost to churn."""
        from nomad_tpu.raft.transport import Unreachable
        from nomad_tpu.rpc.endpoints import RpcError

        server = self.server
        job = server.store.job_by_id(d.namespace, d.job_id)
        if (job is None or job.multiregion is None
                or job.version != d.job_version):
            # superseded by a newer registration — that version's own
            # deployment owns the rollout now
            self._mark_kicked(d)
            return
        regions = job.multiregion.region_names()
        rollout = job.meta.get("multiregion.rollout", "")
        if job.region not in regions or not rollout:
            self._mark_kicked(d)
            return
        idx = regions.index(job.region)
        if idx + 1 >= len(regions):
            self._mark_kicked(d)            # last region: rollout done
            return
        next_region = regions[idx + 1]
        try:
            remote = server.rpc_region(next_region, "Job.GetJob", {
                "namespace": d.namespace, "job_id": d.job_id})
            already = (remote is not None and
                       getattr(remote, "meta", {}).get(
                           "multiregion.rollout") == rollout)
            if not already:
                nxt = job.multiregion_copy(next_region, rollout)
                # must look like a fresh submission over there — strip
                # the replicated indexes this region's store stamped on
                nxt.version = 0
                nxt.stable = False
                nxt.create_index = nxt.modify_index = 0
                nxt.job_modify_index = 0
                server.rpc_region(next_region, "Job.Register", {"job": nxt})
            self._mark_kicked(d)
        except (Unreachable, RpcError):
            return                          # region dark/churning: retry

    def _mark_kicked(self, d: Deployment) -> None:
        updated = d.copy()
        updated.multiregion_kicked = True
        self.server.apply(MessageType.DEPLOYMENT_UPSERT,
                          {"deployment": _stamp(updated)})

    def _reconcile(self, d: Deployment, now: float) -> None:
        server = self.server
        store = server.store
        allocs = [a for a in store.allocs_by_job(d.namespace, d.job_id)
                  if a.deployment_id == d.id]

        updated = d.copy()
        failed = False
        unhealthy_alloc = None
        for state in updated.task_groups.values():
            state.placed_allocs = 0
            state.healthy_allocs = 0
            state.unhealthy_allocs = 0
        for a in allocs:
            state = updated.task_groups.get(a.task_group)
            if state is None:
                continue
            if not a.server_terminal_status():
                state.placed_allocs += 1
            if a.is_healthy():
                state.healthy_allocs += 1
            elif a.is_unhealthy():
                state.unhealthy_allocs += 1
                failed = True
                unhealthy_alloc = a
            if a.client_status == AllocClientStatus.FAILED:
                failed = True
                unhealthy_alloc = a

        # progress deadline
        deadline_failed = any(
            s.require_progress_by and now > s.require_progress_by
            and s.healthy_allocs < s.desired_total
            for s in updated.task_groups.values())

        if failed or deadline_failed:
            self._fail_deployment(updated, deadline_failed)
            return

        # canary auto-promotion: all canaries healthy -> promote
        if updated.has_auto_promote() and not all(
                s.promoted for s in updated.task_groups.values()
                if s.desired_canaries > 0):
            ready = all(
                len([c for c in s.placed_canaries
                     if (al := store.alloc_by_id(c)) is not None and al.is_healthy()])
                >= s.desired_canaries
                for s in updated.task_groups.values() if s.desired_canaries > 0)
            if ready:
                self.promote(updated.id)
                return

        # successful when every group reached desired healthy count
        complete = all(
            s.healthy_allocs >= s.desired_total
            and (s.desired_canaries == 0 or s.promoted)
            for s in updated.task_groups.values())
        if complete and updated.task_groups:
            updated.status = DeploymentStatus.SUCCESSFUL
            updated.status_description = DeploymentStatus.DESC_SUCCESSFUL
            server.apply(MessageType.DEPLOYMENT_UPSERT, {"deployment": _stamp(updated)})
            self._mark_job_stable(d)
            return

        # health progressed: emit an eval so the reconciler can continue
        # the rollout (the reference watcher creates evals on alloc health
        # transitions, deployment_watcher.go)
        def counts(dep):
            return {k: (s.placed_allocs, s.healthy_allocs, s.unhealthy_allocs,
                        s.promoted) for k, s in dep.task_groups.items()}

        progressed = any(
            k in d.task_groups
            and updated.task_groups[k].healthy_allocs
            > d.task_groups[k].healthy_allocs
            for k in updated.task_groups)
        # only write when something actually changed — an unconditional
        # upsert re-triggers this watcher through its own state watch
        if counts(updated) != counts(d) or updated.status != d.status:
            server.apply(MessageType.DEPLOYMENT_UPSERT, {"deployment": _stamp(updated)})
        if progressed:
            self._emit_eval(updated)

    def _mark_job_stable(self, d: Deployment) -> None:
        self.server.set_job_stability(d.namespace, d.job_id, d.job_version, True)

    def _fail_deployment(self, d: Deployment, deadline: bool,
                         from_peer_region: bool = False) -> None:
        server = self.server
        d.status = DeploymentStatus.FAILED
        if from_peer_region:
            d.status_description = DeploymentStatus.DESC_MULTIREGION_FAIL
        else:
            d.status_description = (
                DeploymentStatus.DESC_PROGRESS_DEADLINE
                if deadline else DeploymentStatus.DESC_FAILED_ALLOCATIONS)
        server.apply(MessageType.DEPLOYMENT_UPSERT, {"deployment": _stamp(d)})
        # a locally-failed multiregion deployment fails its siblings too
        # (the from_peer_region guard stops the notification ping-ponging
        # back to us)
        if d.is_multiregion and not from_peer_region:
            self._fail_sibling_regions(d)
        # auto-revert to the latest stable version
        if any(s.auto_revert for s in d.task_groups.values()):
            job = server.store.job_by_id(d.namespace, d.job_id)
            if job is not None and job.version == d.job_version:
                stable = self._latest_stable(d.namespace, d.job_id, d.job_version)
                if stable is not None:
                    revert = stable.copy()
                    server.register_job(revert)
                    return
        self._emit_eval(d)

    def _fail_sibling_regions(self, d: Deployment) -> None:
        """Cross-region failure propagation: tell every peer region in
        the rollout to fail (and auto-revert) its copy of this job.
        Best-effort — a dark region just misses the notification; its
        rollout was halted at the region boundary anyway because the
        SUCCESSFUL→kick chain can't cross a failed region."""
        from nomad_tpu.raft.transport import Unreachable
        from nomad_tpu.rpc.endpoints import RpcError

        server = self.server
        job = server.store.job_by_id(d.namespace, d.job_id)
        if job is None or job.multiregion is None:
            return
        if job.multiregion.strategy.on_failure == "fail_local":
            return
        rollout = job.meta.get("multiregion.rollout", "")
        for region in job.multiregion.region_names():
            if region == server.region:
                continue
            try:
                server.rpc_region(region, "Deployment.MultiregionFail", {
                    "namespace": d.namespace, "job_id": d.job_id,
                    "rollout": rollout})
            except (Unreachable, RpcError):
                continue

    def multiregion_fail(self, namespace: str, job_id: str,
                         rollout: str = "") -> bool:
        """Receiving side of cross-region failure propagation: fail any
        active multiregion deployment for this job (which triggers the
        normal auto-revert path), and revert an already-promoted
        SUCCESSFUL one back to its latest stable version.  Idempotent —
        deployments already failed or superseded are left alone."""
        server = self.server
        job = server.store.job_by_id(namespace, job_id)
        if (rollout and job is not None
                and job.meta.get("multiregion.rollout") != rollout):
            return False                    # different rollout generation
        handled = False
        for d in server.store.deployments():
            if (d.namespace != namespace or d.job_id != job_id
                    or not d.is_multiregion):
                continue
            if d.active():
                self._fail_deployment(d.copy(), deadline=False,
                                      from_peer_region=True)
                handled = True
            elif (d.status == DeploymentStatus.SUCCESSFUL
                    and job is not None and job.version == d.job_version):
                stable = self._latest_stable(namespace, job_id, d.job_version)
                if stable is not None:
                    server.register_job(stable.copy())
                    handled = True
        return handled

    def _latest_stable(self, namespace: str, job_id: str, before_version: int):
        versions = self.server.store.job_versions(namespace, job_id)
        for j in sorted(versions, key=lambda x: -x.version):
            if j.stable and j.version < before_version:
                return j
        return None

    def _emit_eval(self, d: Deployment) -> None:
        job = self.server.store.job_by_id(d.namespace, d.job_id)
        if job is None:
            return
        self.server.create_evals([Evaluation(
            namespace=d.namespace, priority=d.eval_priority, type=job.type,
            job_id=d.job_id, deployment_id=d.id,
            triggered_by=EvalTrigger.DEPLOYMENT_WATCHER,
            status=EvalStatus.PENDING)])

    # ------------------------------------------------------------- API

    def promote(self, deployment_id: str, groups: Optional[List[str]] = None) -> bool:
        """Deployment.Promote RPC: mark canaries promoted, emit an eval so
        the reconciler replaces the remaining old-version allocs."""
        server = self.server
        d = server.store.deployment_by_id(deployment_id)
        if d is None or not d.active():
            return False
        updated = d.copy()
        for name, state in updated.task_groups.items():
            if groups is None or name in groups:
                state.promoted = True
        server.apply(MessageType.DEPLOYMENT_UPSERT, {"deployment": _stamp(updated)})
        self._emit_eval(updated)
        return True

    def fail(self, deployment_id: str) -> bool:
        d = self.server.store.deployment_by_id(deployment_id)
        if d is None or not d.active():
            return False
        self._fail_deployment(d.copy(), deadline=False)
        return True

    def pause(self, deployment_id: str, pause: bool) -> bool:
        d = self.server.store.deployment_by_id(deployment_id)
        if d is None or not d.active():
            return False
        updated = d.copy()
        updated.status = (DeploymentStatus.PAUSED if pause
                          else DeploymentStatus.RUNNING)
        self.server.apply(MessageType.DEPLOYMENT_UPSERT, {"deployment": _stamp(updated)})
        if not pause:
            self._emit_eval(updated)
        return True
