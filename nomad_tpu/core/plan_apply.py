"""Serialized plan applier (reference: nomad/plan_apply.go — planApply:71,
evaluatePlan:400, evaluatePlanPlacements:439, evaluateNodePlan:640,
applyPlan:204).

The single point where optimistic scheduler output meets ground truth:
every placement is re-validated against the latest committed state (the
incremental ClusterMatrix *is* that state, so validation is vectorized
array math instead of the reference's per-node EvaluatePool fan-out), nodes
that fail are partially rejected, and the surviving plans are committed to
the state store in coalesced indexed writes.

Lock discipline (the commit pipeline):
  * `_lock` covers ONLY evaluation ordering — the snapshot a plan is
    validated against plus its overlay registration must be atomic so
    plan N+1 sees plan N's accepted effects.
  * `_commit_lock` covers ONLY commit ordering — indexed store/raft
    writes stay strictly sequential.
  * All per-plan Python work (diff flattening, alloc serialization into
    AppliedPlanResults, future resolution, ticket release) happens off
    both locks, on the background commit thread.
Plans drained together from the queue (`dequeue_batch`) are committed as
ONE batched write — one lock acquisition, one raft apply, one index —
mirroring the reference's optimistic pipeline (plan_apply.go:71-178)
with coalescing layered on top.
"""
from __future__ import annotations

import os
import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from nomad_tpu.encode.matrixizer import comparable_vec, NUM_RESOURCE_DIMS

from nomad_tpu import chaos, deadline, knobs, tracing
from nomad_tpu.analysis import race
from nomad_tpu.state.store import AppliedPlanResults, StateStore
from nomad_tpu.structs import Allocation, Node
from nomad_tpu.structs.namespace import alloc_quota_usage, usage_add
from nomad_tpu.structs.node import NodeStatus
from nomad_tpu.structs.plan import Plan, PlanResult
from nomad_tpu.telemetry import global_metrics


class PlanApplier:
    """Serialized: one plan at a time, guarded by a lock (the reference
    serializes via the single planApply goroutine)."""

    # happens-before (nomad_tpu.analysis): the pipelining overlay is
    # written by the evaluation path (_overlay_add) and popped by the
    # background commit thread; every access must hold _overlay_lock.
    _RACE_TRACED = {"_overlay": "_overlay_lock"}

    def __init__(self, store: StateStore, commit_fn=None):
        self.store = store
        # commit_fn(AppliedPlanResults) -> index routes the commit through
        # the Raft/FSM write path (reference: applyPlan raft.Apply of an
        # ApplyPlanResultsRequest, plan_apply.go:204); None = direct store
        # write (the scheduler Harness mode, testing.go:180)
        self._commit_fn = commit_fn
        # called after a commit that evicted allocs (the preempted list);
        # the server creates PreemptionEvals here, outside the raft lock
        self.on_preempted = None
        self._lock = threading.Lock()
        self._commit_lock = threading.Lock()
        # plans coalesced per commit (one indexed write for the whole
        # batch); the 48-worker C2M legs drive queue depth well past 1,
        # and the wave-aligned dequeue front (EvalWaveFeeder) lands a
        # whole worker pool's plans nearly at once — size the commit
        # batch to swallow a full wave in one raft apply
        self.batch_n = max(1, knobs.get_int("NOMAD_TPU_PLAN_BATCH"))
        # pipelining overlay: accepted-but-not-yet-committed plan effects,
        # keyed by plan eval token/id (reference plan_apply.go:71-178
        # evaluates plan N+1 against a snapshot with plan N applied while
        # N's raft.Apply is still in flight)
        self._overlay_lock = threading.Lock()
        self._overlay: Dict[int, tuple] = {}
        self._overlay_seq = 0
        # (t0, t1) wall windows where the commit thread held the raft
        # append + fsync in flight; bench intersects these with the
        # engine's device-blocked windows to report pipeline_overlap_s
        # (device time hidden under durability waits).  Appends happen
        # only on the single commit thread; readers tolerate staleness.
        self.commit_windows = deque(maxlen=8192)
        self.stats = {"applied": 0, "rejected_nodes": 0, "partial": 0,
                      "pipelined": 0}

    # ------------------------------------------------------------- public

    def apply(self, plan: Plan) -> PlanResult:
        with self._lock:
            result = self._evaluate(plan)
            token = self._overlay_add(plan, result)
        # flatten + commit off the evaluation lock; the overlay entry
        # keeps the accepted effects visible to concurrent evaluations
        # until the store write lands
        try:
            self._commit(plan, result)
        finally:
            with self._overlay_lock:
                race.write("PlanApplier._overlay", self)
                self._overlay.pop(token, None)
        return result

    def run_loop(self, queue, stop_event: threading.Event) -> None:
        """Leader plan-apply loop draining the PlanQueue.

        Pipelined (plan_apply.go:71-178): while batch N's commit (raft
        apply) is in flight on a background thread, batch N+1's plans are
        already being evaluated against committed state + the in-flight
        overlays.  Adjacent plans drained together coalesce into ONE
        indexed commit.  Commits stay strictly ordered — the next commit
        starts only after the previous one finishes."""
        commit_t: Optional[threading.Thread] = None
        while not stop_event.is_set():
            batch = queue.dequeue_batch(self.batch_n, timeout=0.1)
            if chaos.active is not None:
                # overload chaos: the drain loop stalls per round, aging
                # queued plans toward their deadlines
                chaos.maybe_delay("overload.applier_stall")
            if not batch:
                continue
            staged: List[tuple] = []
            for pending in batch:
                if pending.deadline is not None and \
                        _time.monotonic() > pending.deadline:
                    # the submitter's budget died in the queue: refuse
                    # BEFORE the raft append + fsync — committing a plan
                    # nobody is waiting for wastes the durability edge
                    # and strands its allocs on a caller that already
                    # timed out
                    deadline.expire("applier")
                    err = deadline.DeadlineExceeded(
                        "plan deadline exceeded before commit")
                    pending.future.set_exception(err)
                    if not pending.evaluated.done():
                        pending.evaluated.set_exception(err)
                    continue
                try:
                    tracer = tracing.active
                    tnote = pending.trace if tracer is not None else None
                    t0 = _time.time()
                    if tnote is not None:
                        tracer.emit(tnote[0], "plan.queue_wait",
                                    tnote[1], t0,
                                    node=getattr(self, "node_name", ""))
                    # snapshot BEFORE evaluating: if the commit finishes
                    # while _evaluate reads the double-counted window, an
                    # after-the-fact is_alive() check would skip the
                    # second look and let the stale rejection stand
                    commit_in_flight = (commit_t is not None
                                        and commit_t.is_alive())
                    result = self._evaluate(pending.plan)
                    global_metrics.measure_since("nomad.plan.evaluate", t0)
                    if commit_in_flight and \
                            self._result_rejected_something(pending.plan,
                                                            result):
                        # the in-flight commit's usage is counted twice
                        # (store write + its overlay entry) until it pops;
                        # a rejection in that window may be pure
                        # over-reservation — settle the commit and give
                        # the plan one clean second look before failing it
                        # back to the scheduler (a full eval recompute).
                        # Plans staged in THIS batch are overlay-only, so
                        # they are never double-counted.
                        commit_t.join()
                        self.stats["revalidated"] = \
                            self.stats.get("revalidated", 0) + 1
                        result = self._evaluate(pending.plan)
                    token = self._overlay_add(pending.plan, result)
                    if tnote is not None:
                        tracer.emit(tnote[0], "plan.evaluate",
                                    t0, _time.time(),
                                    node=getattr(self, "node_name", ""))
                except Exception as e:            # noqa: BLE001
                    pending.future.set_exception(e)
                    if not pending.evaluated.done():
                        pending.evaluated.set_exception(e)
                    continue
                staged.append((pending, result, token))
                # the plan is validated and its overlay registered: a
                # pipelined submitter may continue scheduling off this
                # result while the durable commit is still in flight
                # (plan_apply.go:71-178's optimistic snapshot, extended
                # to the worker side)
                if not pending.evaluated.done():
                    pending.evaluated.set_result(result)
            if not staged:
                continue
            if commit_t is not None:
                commit_t.join()
                self.stats["pipelined"] += 1
            if len(staged) > 1:
                self.stats["coalesced"] = \
                    self.stats.get("coalesced", 0) + len(staged)
            commit_t = threading.Thread(
                target=self._commit_batch_and_resolve, args=(staged,),
                name="plan-commit", daemon=True)
            commit_t.start()
        if commit_t is not None:
            commit_t.join()

    @staticmethod
    def _result_rejected_something(plan: Plan, result: PlanResult) -> bool:
        want = sum(len(v) for v in plan.node_allocation.values())
        got = sum(len(v) for v in result.node_allocation.values())
        return got < want

    def _commit_batch_and_resolve(self, staged: List[tuple]) -> None:
        """Commit a batch of evaluated plans as ONE indexed write, then
        resolve every submitter's future.  All flattening/serialization
        happens here, off the evaluation lock; overlay entries pop only
        after the write lands (never a double-free window)."""
        try:
            entries = [(pending, result,
                        self._applied_for(pending.plan, result))
                       for pending, result, _token in staged]
            applied_list = [ap for _, _, ap in entries if ap is not None]
            index = None
            if applied_list:
                if chaos.active is not None:
                    chaos.fire("plan.crash_before_commit")
                # a coalesced batch commits as ONE raft apply: bind the
                # first sampled plan's context so the synchronous raft
                # write path on this thread emits append/commit spans
                # into that trace
                tprev, tbound = None, False
                if tracing.active is not None:
                    for pending, _r, _ap in entries:
                        if pending.trace is not None:
                            tprev = tracing.bind(pending.trace[0])
                            tbound = True
                            break
                t0c = _time.time()
                if chaos.active is not None:
                    # slow fsync: stretch the durability wait the next
                    # wave is evaluating (and dispatching) under
                    chaos.maybe_delay("plan.commit_stall")
                try:
                    with self._commit_lock:
                        if self._commit_fn is not None:
                            index = self._commit_fn(
                                applied_list if len(applied_list) > 1
                                else applied_list[0])
                        else:
                            index = self.store.latest_index + 1
                            self.store.upsert_plan_results_many(
                                index, applied_list)
                finally:
                    if tbound:
                        tracing.bind(tprev)
                self.commit_windows.append((t0c, _time.time()))
                if chaos.active is not None:
                    # the write landed but futures have not resolved: the
                    # submitter sees an error, retries, and the plan-id
                    # dedup in the store makes the replay a no-op
                    chaos.fire("plan.crash_after_commit")
            for pending, result, applied in entries:
                try:
                    self._post_commit(pending.plan, result, applied, index)
                    pending.future.set_result(result)
                except Exception as e:            # noqa: BLE001
                    pending.future.set_exception(e)
        except Exception as e:                    # noqa: BLE001
            from nomad_tpu.parallel.engine import get_engine
            eng = get_engine()
            for pending, _result, _token in staged:
                if pending.future.done():
                    continue
                # a pipelined submitter continued off `evaluated` and
                # skipped its early ticket release — free the engine
                # overlay here so a failed commit never leaks phantom
                # usage (plans that reached _post_commit released theirs
                # already; complete_many is idempotent regardless)
                if eng is not None and pending.plan.engine_tickets:
                    eng.complete_many(pending.plan.engine_tickets)
                pending.future.set_exception(e)
        finally:
            with self._overlay_lock:
                race.write("PlanApplier._overlay", self)
                for _pending, _result, token in staged:
                    self._overlay.pop(token, None)

    # ------------------------------------------------------------- overlay

    def _overlay_add(self, plan: Plan, result: PlanResult) -> int:
        """Record the accepted plan's usage/port effects so the next
        evaluation sees them before the commit lands."""
        cm = self.store.matrix
        used_delta: Dict[int, np.ndarray] = {}
        port_claim: Dict[int, Set[int]] = {}
        port_free: Dict[int, Set[int]] = {}
        for node_id, allocs in result.node_allocation.items():
            row = cm.row_of.get(node_id)
            if row is None:
                continue
            vec = np.zeros(NUM_RESOURCE_DIMS, np.float32)
            for a in allocs:
                vec += comparable_vec(a.comparable_resources())
                port_claim.setdefault(row, set()).update(_alloc_ports(a))
            used_delta[row] = used_delta.get(
                row, np.zeros(NUM_RESOURCE_DIMS, np.float32)) + vec
        # NOTE: stops/preemptions are deliberately NOT overlaid.  The
        # overlay lives until the commit thread pops it *after* the store
        # write, so during that window effects would be counted twice.
        # Double-counted placements only over-reserve (spurious rejection
        # -> scheduler retry, safe); double-counted frees would validate
        # overcommitting plans.  Untracked in-flight frees merely delay
        # reuse of the space by one commit.
        # The same asymmetry holds for the quota overlay below: accepted
        # placements of quota-governed namespaces count against the
        # budget until their commit pops; frees never do.
        quota_delta: Dict[str, Dict[str, int]] = {}
        governed: Dict[str, bool] = {}
        for allocs in result.node_allocation.values():
            for a in allocs:
                gov = governed.get(a.namespace)
                if gov is None:
                    ns_obj = self.store.namespace(a.namespace)
                    gov = governed[a.namespace] = \
                        ns_obj is not None and bool(ns_obj.quota)
                if gov:
                    usage_add(quota_delta.setdefault(a.namespace, {}),
                              alloc_quota_usage(a), +1)
        with self._overlay_lock:
            race.write("PlanApplier._overlay", self)
            self._overlay_seq += 1
            token = self._overlay_seq
            self._overlay[token] = (used_delta, port_claim, port_free,
                                    quota_delta)
        return token

    def _overlay_views(self, cm):
        """(used, port_words) with any in-flight overlay applied.  Copies
        are taken under the store lock so a concurrent commit thread
        cannot tear the matrices mid-read."""
        with self._overlay_lock:
            race.read("PlanApplier._overlay", self)
            if not self._overlay:
                return cm.used, cm.port_words
            with self.store._lock:
                used = cm.used.copy()
                port_words = cm.port_words.copy()
            for used_delta, port_claim, port_free, _qd in \
                    self._overlay.values():
                for row, vec in used_delta.items():
                    if row < used.shape[0]:
                        used[row] += vec
                for row, ports in port_claim.items():
                    for p in ports:
                        port_words[row, p >> 5] |= np.uint32(1 << (p & 31))
            return used, port_words

    # ------------------------------------------------------------- evaluate

    def _node_ok_for_placement(self, node: Optional[Node]) -> bool:
        """evaluateNodePlan's node-state gate (plan_apply.go:653-668)."""
        if node is None:
            return False
        if node.status in (NodeStatus.DOWN, NodeStatus.DISCONNECTED):
            return False
        # ineligible nodes reject new work at *scheduling* time; the applier
        # only rejects unsafe nodes (down/disconnected/draining), mirroring
        # the reference's check of Status and Drain but not eligibility
        return node.drain_strategy is None

    def _evaluate(self, plan: Plan) -> PlanResult:
        """Validate placements per node against committed state; drop
        failing nodes (partial commit) or everything for all_at_once."""
        store = self.store
        cm = store.matrix
        result = PlanResult()
        result.node_update = {k: list(v) for k, v in plan.node_update.items()}
        result.node_preemptions = {k: list(v) for k, v in plan.node_preemptions.items()}
        result.deployment = plan.deployment
        result.deployment_updates = list(plan.deployment_updates)

        # resources freed on each node by this plan's stops/preemptions
        freed: Dict[str, np.ndarray] = {}
        freed_ports: Dict[str, Set[int]] = {}
        for node_id, stops in list(plan.node_update.items()) + \
                list(plan.node_preemptions.items()):
            vec = np.zeros(NUM_RESOURCE_DIMS, np.float32)
            ports: Set[int] = set()
            for a in stops:
                live = store.alloc_by_id(a.id)
                src = live if live is not None else a
                if live is not None and live.terminal_status():
                    continue   # already free in committed state
                cr = src.comparable_resources()
                vec += comparable_vec(cr)
                ports.update(_alloc_ports(src))
            freed[node_id] = vec
            freed_ports[node_id] = ports

        # batched per-node validation — the reference fans this across an
        # EvaluatePool (plan_apply_pool.go); here it is ONE native call
        # over all touched nodes (nomad_tpu.native.validate_plan, C++)
        from nomad_tpu import native as _native
        node_ids = list(plan.node_allocation.keys())
        g = len(node_ids)
        rows = np.full(g, -1, np.int32)
        demand = np.zeros((g, NUM_RESOURCE_DIMS), np.float32)
        freed_vecs = np.zeros((g, NUM_RESOURCE_DIMS), np.float32)
        group_ports: List[List[int]] = []
        group_freed: List[List[int]] = []
        for i, node_id in enumerate(node_ids):
            node = store.node_by_id(node_id)
            row = cm.row_of.get(node_id)
            ports: List[int] = []
            if self._node_ok_for_placement(node) and row is not None:
                rows[i] = row
            for a in plan.node_allocation[node_id]:
                cr = a.comparable_resources()
                demand[i] += comparable_vec(cr)
                ports.extend(_alloc_ports(a))
            freed_vecs[i] = freed.get(node_id, 0.0)
            group_ports.append(ports)
            group_freed.append(sorted(freed_ports.get(node_id, ())))
        used_eff, port_words_eff = self._overlay_views(cm)
        ok = _native.validate_plan(
            cm.capacity, used_eff, port_words_eff, rows, demand,
            freed_vecs, group_ports, group_freed) if g else []

        rejected: List[str] = []
        # csi write-claim exclusion across concurrent plans (the reference
        # rejects the claim at the state store, csi.go ClaimWrite; here the
        # serialized applier is the authority): (ns, vol) -> job ids that
        # claimed a write in THIS plan evaluation
        pending_writers: Dict[Tuple[str, str], Set[str]] = {}
        for i, node_id in enumerate(node_ids):
            if ok[i] and not self._csi_claims_ok(
                    plan.node_allocation[node_id], pending_writers):
                ok[i] = False
            if ok[i] and not self._device_claims_ok(
                    plan, node_id, plan.node_allocation[node_id]):
                ok[i] = False
        for i, node_id in enumerate(node_ids):
            if ok[i]:
                result.node_allocation[node_id] = \
                    list(plan.node_allocation[node_id])
            else:
                rejected.append(node_id)

        # namespace quota admission at propose time, in the same
        # placement order the FSM will apply (node_allocation insertion
        # order == _applied_for's flatten order), against committed
        # usage + the in-flight quota overlay − this plan's own frees.
        # The FSM re-checks authoritatively at apply (the leader-churn
        # backstop: two leaders can each propose within-budget plans
        # that only overflow combined); on a stable leader this check
        # is never more permissive than the FSM's, so a propose-admit
        # implies an apply-admit and the plan result stays truthful.
        if chaos.active is not None:
            chaos.maybe_delay("quota.apply_stall")
        quota_dropped = self._quota_filter(plan, result)

        if (rejected or quota_dropped) and plan.all_at_once:
            # the reference nils updates, placements, preemptions AND the
            # deployment together when AllAtOnce fails (plan_apply.go:428-436)
            result.node_allocation = {}
            result.node_update = {}
            result.node_preemptions = {}
            result.deployment = None
            result.deployment_updates = []
        if rejected:
            result.rejected_nodes = rejected
            result.refresh_index = store.latest_index
            self.stats["partial"] += 1
            self.stats["rejected_nodes"] += len(rejected)
        return result

    def _quota_filter(self, plan: Plan, result: PlanResult) -> int:
        """Drop over-quota placements from the evaluated result.  Returns
        the number of placements dropped; sets
        ``result.quota_limit_reached`` to the exhausted spec's name so
        the scheduler blocks the eval keyed on it instead of retrying."""
        store = self.store
        # resolve the governing spec per namespace in the placements
        specs: Dict[str, object] = {}
        for allocs in result.node_allocation.values():
            for a in allocs:
                if a.namespace in specs:
                    continue
                ns_obj = store.namespace(a.namespace)
                spec = None
                if ns_obj is not None and ns_obj.quota:
                    spec = store.quota_spec(ns_obj.quota)
                specs[a.namespace] = spec
        if not any(spec is not None for spec in specs.values()):
            return 0

        # working view: committed usage + in-flight overlays − this
        # plan's frees (live, non-terminal stops only — same condition
        # as the resource `freed` vectors above)
        view: Dict[str, Dict[str, int]] = {}

        def usage(ns: str) -> Dict[str, int]:
            got = view.get(ns)
            if got is None:
                got = view[ns] = store.quota_usage(ns)
            return got

        with self._overlay_lock:
            race.read("PlanApplier._overlay", self)
            overlay_qd = [entry[3] for entry in self._overlay.values()]
        for qd in overlay_qd:
            for ns, vec in qd.items():
                if specs.get(ns) is not None:
                    usage_add(usage(ns), vec, +1)
        for stops in list(plan.node_update.values()) + \
                list(plan.node_preemptions.values()):
            for a in stops:
                live = store.alloc_by_id(a.id)
                if live is None or live.terminal_status():
                    continue
                if specs.get(live.namespace) is not None:
                    usage_add(usage(live.namespace),
                              alloc_quota_usage(live), -1)

        dropped = 0
        for node_id in list(result.node_allocation.keys()):
            kept: List[Allocation] = []
            for a in result.node_allocation[node_id]:
                spec = specs.get(a.namespace)
                if spec is None or store.alloc_by_id(a.id) is not None:
                    # ungoverned namespace, or an update of an existing
                    # alloc (the FSM admits those unconditionally too)
                    kept.append(a)
                    continue
                would = dict(usage(a.namespace))
                usage_add(would, alloc_quota_usage(a), +1)
                if spec.admits(would):
                    view[a.namespace] = would
                    kept.append(a)
                else:
                    dropped += 1
                    result.quota_limit_reached = spec.name
            if dropped and len(kept) != len(result.node_allocation[node_id]):
                if kept:
                    result.node_allocation[node_id] = kept
                else:
                    del result.node_allocation[node_id]
        if dropped:
            self.stats["quota_dropped"] = \
                self.stats.get("quota_dropped", 0) + dropped
            global_metrics.incr("nomad.plan.quota_dropped", dropped)
        return dropped

    def _csi_claims_ok(self, allocs: List[Allocation],
                       pending_writers: Dict[Tuple[str, str], Set[str]]
                       ) -> bool:
        """Write-claim feasibility for a node's placements: existing write
        claims may only be held by the same job (the checker's own
        exception, feasible.go:336-358 — covers destructive updates);
        write claims taken earlier in this same plan pass by another job
        reject the node."""
        for a in allocs:
            job = a.job
            tg = job.lookup_task_group(a.task_group) if job else None
            if tg is None:
                continue
            for req in tg.volumes.values():
                if req.type != "csi" or req.read_only:
                    continue
                key = (job.namespace, req.source)
                vol = self.store.csi_volume_by_id(*key)
                if vol is None:
                    return False
                others = pending_writers.get(key, set()) - {job.id}
                if others:
                    return False
                if not vol.has_free_write_claims():
                    for alloc_id in vol.write_claims:
                        holder = self.store.alloc_by_id(alloc_id)
                        if holder is None or \
                                holder.namespace != job.namespace or \
                                holder.job_id != job.id:
                            return False
                pending_writers.setdefault(key, set()).add(job.id)
        return True

    def _device_claims_ok(self, plan: Plan, node_id: str,
                          allocs: List[Allocation]) -> bool:
        """Device instance exclusivity at commit (the reference's
        DeviceAccounter collision check, structs/devices.go): the plan's
        placements must not claim instance ids already held by live
        allocs on the node (minus the plan's own stops/evictions) or by
        each other."""
        wanted: Dict[str, Set[str]] = {}
        any_dev = False
        for a in allocs:
            for tr in a.allocated_resources.tasks.values():
                for d in tr.devices:
                    any_dev = True
                    gid = f"{d['vendor']}/{d['type']}/{d['name']}"
                    ids = set(d.get("device_ids", []))
                    if ids & wanted.get(gid, set()):
                        return False          # duplicate within the plan
                    wanted.setdefault(gid, set()).update(ids)
        if not any_dev:
            return True
        dropped = {a.id for a in plan.node_update.get(node_id, [])}
        dropped |= {a.id for a in plan.node_preemptions.get(node_id, [])}
        for live in self.store.allocs_by_node(node_id):
            if live.terminal_status() or live.id in dropped:
                continue
            for tr in live.allocated_resources.tasks.values():
                for d in tr.devices:
                    gid = f"{d['vendor']}/{d['type']}/{d['name']}"
                    if set(d.get("device_ids", ())) & wanted.get(gid, set()):
                        return False
        return True

    # ------------------------------------------------------------- commit

    @staticmethod
    def _applied_for(plan: Plan,
                     result: PlanResult) -> Optional["AppliedPlanResults"]:
        """Flatten an evaluated plan into its raft payload; None for a
        no-op plan (nothing to write)."""
        if (not result.node_allocation and not result.node_update
                and not result.node_preemptions and result.deployment is None
                and not result.deployment_updates):
            return None
        if result.deployment is not None:
            # stamp here (propose side) so the FSM applies carried values
            # instead of reading the clock under fsm.apply
            d = result.deployment
            d.modify_time = _time.time()
            if not d.create_time:
                d.create_time = d.modify_time
        return AppliedPlanResults(
            alloc_updates=[a for v in result.node_update.values() for a in v],
            allocs_to_place=[a for v in result.node_allocation.values() for a in v],
            allocs_preempted=[a for v in result.node_preemptions.values() for a in v],
            deployment=result.deployment,
            deployment_updates=result.deployment_updates,
            eval_id=plan.eval_id,
            plan_id=getattr(plan, "plan_id", ""),
        )

    def _post_commit(self, plan: Plan, result: PlanResult,
                     applied: Optional["AppliedPlanResults"],
                     index: Optional[int]) -> None:
        """Per-plan bookkeeping after the store write: release the
        scheduler's in-flight overlay tickets NOW — the usage just became
        committed state, and any window where both the store and the
        overlay count it makes concurrent kernels see phantom usage."""
        if plan.engine_tickets:
            from nomad_tpu.parallel.engine import get_engine
            eng = get_engine()
            if eng is not None:
                eng.complete_many(plan.engine_tickets)
        if applied is None:
            return
        result.alloc_index = index
        self.stats["applied"] += 1
        if applied.allocs_preempted and self.on_preempted is not None:
            try:
                self.on_preempted(applied.allocs_preempted)
            except Exception:                  # noqa: BLE001
                pass

    def _commit(self, plan: Plan, result: PlanResult) -> None:
        applied = self._applied_for(plan, result)
        index = None
        if applied is not None:
            if chaos.active is not None:
                chaos.fire("plan.crash_before_commit")
            with self._commit_lock:
                if self._commit_fn is not None:
                    index = self._commit_fn(applied)
                else:
                    index = self.store.latest_index + 1
                    self.store.upsert_plan_results(index, applied)
            if chaos.active is not None:
                chaos.fire("plan.crash_after_commit")
        self._post_commit(plan, result, applied, index)


def _alloc_ports(a: Allocation) -> List[int]:
    out = []
    for net in a.comparable_resources().networks:
        out += [p.value for p in net.reserved_ports if p.value]
        out += [p.value for p in net.dynamic_ports if p.value]
    out += [p.value for p in a.allocated_resources.shared_ports if p.value]
    return out
