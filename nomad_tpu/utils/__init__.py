"""Small shared helpers (reference: helper/ package family)."""
from __future__ import annotations

import os

from nomad_tpu import knobs


_cache_enabled = False


def _machine_cache_key() -> str:
    """Short digest of the TARGET MACHINE's features, used to partition
    the persistent compile cache: an AOT-cached executable deserialized
    on a host with a different ISA/accelerator can SIGILL or miscompute
    (observed as cross-host reuse warnings in multichip runs).  Keyed on
    arch + CPU feature flags + accelerator selection, all readable
    without forcing JAX backend init."""
    import hashlib
    import platform

    parts = [platform.machine(), platform.system()]
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("flags", "Features")):
                    parts.append(" ".join(sorted(line.split(":", 1)[1]
                                                 .split())))
                    break
    except OSError:
        pass
    # accelerator identity without initializing a backend: the env vars
    # that select it are what distinguishes cache-incompatible hosts
    for var in ("JAX_PLATFORMS", "JAX_PLATFORM_NAME", "TPU_ACCELERATOR_TYPE",
                "TPU_VERSION", "TPU_CHIPS_PER_HOST_BOUNDS"):
        parts.append(f"{var}={os.environ.get(var, '')}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX at an on-disk compilation cache so a fresh process
    deserializes the placement-kernel variant grid (~100ms/executable)
    instead of recompiling it (~3-5s/variant, ~46s total on TPU).  The
    reference keeps scheduler workers hot at leadership (nomad/worker.go);
    for an XLA-compiled scheduler the equivalent serving-readiness lever
    is a persistent compile cache + AOT warmup.

    The cache lives in a per-machine-feature subdirectory (see
    _machine_cache_key) so executables never cross incompatible hosts.

    Defaults to `<repo root>/.jax_cache/<machine-key>`; override the root
    with NOMAD_TPU_JAX_CACHE_DIR, disable with NOMAD_TPU_JAX_CACHE=0.
    Returns the cache dir in use (None when disabled)."""
    global _cache_enabled
    if not knobs.get_bool("NOMAD_TPU_JAX_CACHE"):
        return None
    if _cache_enabled:
        import jax
        return jax.config.jax_compilation_cache_dir
    root = (path or knobs.get_str("NOMAD_TPU_JAX_CACHE_DIR")
            or os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"))
    path = os.path.join(root, _machine_cache_key())
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _cache_enabled = True
        return path
    except Exception:               # noqa: BLE001 — cache is best-effort
        return None


def requires_lock(lockname: str = "_lock"):
    """Marker decorator: the decorated method must only be called with
    `lockname` already held by the caller.  Runtime no-op; the static
    lock-discipline checker (nomad_tpu.analysis) treats the body as
    lock-covered and every caller remains obligated to hold the lock at
    the call site."""
    def mark(fn):
        fn.__requires_lock__ = lockname
        return fn
    return mark


def generate_uuid() -> str:
    """RFC-4122-shaped random id, ~10x faster than uuid.uuid4() (which
    dominates profiles at thousands of allocs/evals per second; the
    reference's helper/uuid/uuid.go does exactly this — raw random bytes
    formatted with dashes)."""
    h = os.urandom(16).hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"
