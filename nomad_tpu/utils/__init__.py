"""Small shared helpers (reference: helper/ package family)."""
from __future__ import annotations

import os


def generate_uuid() -> str:
    """RFC-4122-shaped random id, ~10x faster than uuid.uuid4() (which
    dominates profiles at thousands of allocs/evals per second; the
    reference's helper/uuid/uuid.go does exactly this — raw random bytes
    formatted with dashes)."""
    h = os.urandom(16).hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"
