"""Kernel-stage attribution probe for the bulk placement dispatch.

`device_s` dominates the C2M headline (ROADMAP item 1) but go-metrics
timers cannot say WHICH stage of the feasibility -> fit -> score ->
argmax -> scatter wave pipeline to fuse first: the wave runs as one jit
and XLA gives wall time per dispatch, not per stage.  This probe re-times
each stage as its own small jitted kernel at the bench's representative
shapes ([N, M=_FILL_GRID, R] — the exact grid `bulk_wave_grid` builds),
derives per-stage fractions, and attributes the MEASURED `device_s`
across them, so the BENCH JSON's `"device_stages"` section names the
dominant stage by construction (stage sum == device_s).

Deliberately NOT `_RECOMPILE_TRACKED` and NOT `_TRANSFER_HOT_PATH`: the
probe is an offline attribution tool that must only run AFTER the bench's
steady-state gate has exited — its compiles and transfers are not part of
the serving hot path and must never count against the recompile budget or
the transfer guard.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from nomad_tpu import tracing
from nomad_tpu.encode.matrixizer import NUM_RESOURCE_DIMS

# the canonical stage order of the wave pipeline (bulk_wave_grid +
# _bulk_loop body); README's span-name table and BENCH_r06 use these keys
STAGES = ("feasibility", "fit", "score", "argmax", "scatter")


def interval_overlap_s(a, b) -> float:
    """Total seconds where two sets of (t0, t1) wall windows intersect.
    Used for `pipeline_overlap_s` (the engine's host upload/dispatch
    windows against in-flight device windows — host prep for wave N+1
    hidden under wave N's compute) and for `commit_overlap_s` (device
    windows against the applier's commit-fsync windows — device time
    hidden under durability waits)."""
    a, b = sorted(a), sorted(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _stage_fns():
    """One small jit per pipeline stage, mirroring bulk_wave_grid /
    _bulk_loop exactly (ops/place.py) so the relative costs transfer."""
    import jax
    import jax.numpy as jnp

    from nomad_tpu.ops.fit import score_fit

    @jax.jit
    def feasibility(capacity, used, demand, feasible, ms):
        # the [N, M, R] fill-grid mask: "does m more instances still fit"
        util_m = used[:, None, :] + ms[None, :, None] * demand
        fits_m = (jnp.all(util_m <= capacity[:, None, :], axis=-1)
                  & feasible[:, None])
        return util_m, fits_m

    @jax.jit
    def fit(capacity, util_m):
        return score_fit(capacity[:, None, :], util_m, False) / 18.0

    @jax.jit
    def score(fit_m, coll, ms, desired_f, penalty, affinity,
              has_affinity):
        coll_m = coll[:, None].astype(jnp.float32) + ms[None, :] - 1.0
        total_m = fit_m
        n_sc = jnp.ones_like(fit_m)
        anti_m = -(coll_m + 1.0) / jnp.maximum(desired_f, 1.0)
        has_coll_m = coll_m > 0.0
        total_m = total_m + jnp.where(has_coll_m, anti_m, 0.0)
        n_sc = n_sc + has_coll_m
        total_m = total_m - penalty[:, None]
        n_sc = n_sc + penalty[:, None]
        aff_on = has_affinity & (affinity != 0.0)
        total_m = total_m + jnp.where(aff_on[:, None],
                                      affinity[:, None], 0.0)
        n_sc = n_sc + aff_on[:, None]
        return total_m / n_sc

    @jax.jit
    def argmax(fits_m, score_m, ms):
        fits = fits_m[:, 0]
        cur = jnp.where(fits, score_m[:, 0], -jnp.inf)
        top2 = jax.lax.top_k(cur, 2)[0]
        second = jnp.where(cur == top2[0], top2[1], top2[0])
        ok_m = fits_m & ((score_m > second[:, None])
                         | (ms[None, :] == 1.0))
        run = jnp.sum(jnp.cumprod(ok_m.astype(jnp.int32), axis=1),
                      axis=1).astype(jnp.int32)
        wave = fits & (cur == top2[0])
        order = jnp.argsort(jnp.where(wave, -cur, jnp.inf))
        return run, order

    @jax.jit
    def scatter(run, order, count, used, demand, coll):
        base_sorted = run[order]
        prefix = jnp.cumsum(base_sorted) - base_sorted
        alloc_sorted = jnp.clip(count - prefix, 0, base_sorted)
        per_node = jnp.zeros(run.shape[0],
                             jnp.int32).at[order].set(alloc_sorted)
        used2 = used + per_node[:, None].astype(jnp.float32) * demand
        return used2, coll + per_node, jnp.sum(per_node)

    return feasibility, fit, score, argmax, scatter


def probe(n_nodes: int, r_dims: int = NUM_RESOURCE_DIMS,
          iters: int = 10, warmup: int = 2,
          fill_grid: Optional[int] = None) -> Dict[str, float]:
    """Raw per-stage wall seconds (best-of-`iters` after `warmup`) at
    shape [n_nodes, fill_grid, r_dims] (default the full _FILL_GRID
    wave width).  Best-of is deliberate — it strips dispatch jitter,
    which is exactly what fractions must not carry."""
    import jax

    from nomad_tpu.ops.place import _FILL_GRID

    rng = np.random.default_rng(0)
    N, R = int(n_nodes), int(r_dims)
    M = int(fill_grid) if fill_grid else int(_FILL_GRID)
    dev = lambda a: jax.device_put(a)   # noqa: E731
    capacity = dev(rng.uniform(100.0, 1000.0,
                               (N, R)).astype(np.float32))
    used = dev(rng.uniform(0.0, 50.0, (N, R)).astype(np.float32))
    demand = dev(rng.uniform(1.0, 10.0, R).astype(np.float32))
    feasible = dev(rng.random(N) < 0.9)
    ms = dev(np.arange(1, M + 1, dtype=np.float32))
    coll = dev(rng.integers(0, 3, N).astype(np.int32))
    penalty = dev((rng.random(N) < 0.05).astype(np.float32))
    affinity = dev(rng.uniform(-1.0, 1.0, N).astype(np.float32))
    count = np.int32(256)
    desired_f = np.float32(8.0)
    has_affinity = np.bool_(True)

    f_feas, f_fit, f_score, f_argmax, f_scatter = _stage_fns()
    util_m, fits_m = f_feas(capacity, used, demand, feasible, ms)
    fit_m = f_fit(capacity, util_m)
    score_m = f_score(fit_m, coll, ms, desired_f, penalty, affinity,
                      has_affinity)
    run, order = f_argmax(fits_m, score_m, ms)

    calls = [
        ("feasibility", lambda: f_feas(capacity, used, demand,
                                       feasible, ms)),
        ("fit", lambda: f_fit(capacity, util_m)),
        ("score", lambda: f_score(fit_m, coll, ms, desired_f, penalty,
                                  affinity, has_affinity)),
        ("argmax", lambda: f_argmax(fits_m, score_m, ms)),
        ("scatter", lambda: f_scatter(run, order, count, used, demand,
                                      coll)),
    ]
    out: Dict[str, float] = {}
    for name, call in calls:
        for _ in range(warmup):
            jax.block_until_ready(call())
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(call())
            best = min(best, time.perf_counter() - t0)
        out[name] = best
    return out


def probe_fused(n_nodes: int, r_dims: int = NUM_RESOURCE_DIMS,
                iters: int = 10, warmup: int = 2,
                fill_grid: Optional[int] = None) -> float:
    """Best-of wall seconds for ONE fused wave — the real production
    composition (`bulk_wave_grid` + run-length argmax + scatter) traced
    as a single jit, at the same shapes the per-phase `probe` uses.
    Comparing against the per-phase sum measures what fusing the five
    dispatches into one program actually buys at this shape."""
    import functools

    import jax
    import jax.numpy as jnp

    from nomad_tpu.ops.place import _FILL_GRID, bulk_run_lengths, \
        bulk_wave_grid

    @functools.partial(jax.jit, static_argnames=("fill_grid",))
    def fused_wave(capacity, used, demand, feasible, affinity,
                   has_affinity, desired_f, penalty, coll, count,
                   fill_grid):
        ms, fits_m, score_m = bulk_wave_grid(
            capacity, used, demand, feasible, affinity, has_affinity,
            desired_f, penalty, coll, False, fill_grid)
        fits = fits_m[:, 0]
        cur = jnp.where(fits, score_m[:, 0], -jnp.inf)
        top2 = jax.lax.top_k(cur, 2)[0]
        second = jnp.where(cur == top2[0], top2[1], top2[0])
        run = bulk_run_lengths(ms, fits_m, score_m, second)
        wave = fits & (cur == top2[0])
        order = jnp.argsort(jnp.where(wave, -cur, jnp.inf))
        base_sorted = run[order]
        prefix = jnp.cumsum(base_sorted) - base_sorted
        alloc_sorted = jnp.clip(count - prefix, 0, base_sorted)
        per_node = jnp.zeros(run.shape[0],
                             jnp.int32).at[order].set(alloc_sorted)
        used2 = used + per_node[:, None].astype(jnp.float32) * demand
        return used2, coll + per_node, jnp.sum(per_node)

    rng = np.random.default_rng(0)
    N, R = int(n_nodes), int(r_dims)
    M = int(fill_grid) if fill_grid else int(_FILL_GRID)
    dev = lambda a: jax.device_put(a)   # noqa: E731
    capacity = dev(rng.uniform(100.0, 1000.0, (N, R)).astype(np.float32))
    used = dev(rng.uniform(0.0, 50.0, (N, R)).astype(np.float32))
    demand = dev(rng.uniform(1.0, 10.0, R).astype(np.float32))
    feasible = dev(rng.random(N) < 0.9)
    coll = dev(rng.integers(0, 3, N).astype(np.int32))
    penalty = dev((rng.random(N) < 0.05).astype(np.float32))
    affinity = dev(rng.uniform(-1.0, 1.0, N).astype(np.float32))

    call = lambda: fused_wave(                       # noqa: E731
        capacity, used, demand, feasible, affinity, np.bool_(True),
        np.float32(8.0), penalty, coll, np.int32(256), fill_grid=M)
    for _ in range(warmup):
        jax.block_until_ready(call())
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(call())
        best = min(best, time.perf_counter() - t0)
    return best


def device_stages(engine_stats: dict, n_nodes: int,
                  r_dims: int = NUM_RESOURCE_DIMS,
                  iters: int = 10, fill_grid: Optional[int] = None,
                  pipeline_overlap_s: Optional[float] = None,
                  commit_overlap_s: Optional[float] = None,
                  wave: Optional[dict] = None
                  ) -> Optional[dict]:
    """The BENCH JSON `"device_stages"` section: the run's measured
    `device_s` attributed across the wave pipeline by probed per-stage
    fractions (stage sum == device_s by construction), plus the
    dirty-row upload time the engine already measures directly.  The
    fused production kernel is probed as one more unit (`fused`): its
    single-dispatch wave time against the five-dispatch phase sum, at
    the same [N, fill_grid] shape the run used.

    `pipeline_overlap_s` is the tentpole upload/compute overlap: host
    prep windows (engine.upload_windows — stack + dirty-row update +
    dispatch of wave N+1) intersected with in-flight device windows
    (engine.device_windows of wave N) via `interval_overlap_s`.
    `commit_overlap_s` is the older commit-pipeline metric (device time
    hidden under raft append + fsync).  `wave` carries the 2-D-mesh
    lane occupancy block (wave_lanes / lane_evals / lane_slots /
    donated_carries / overlap_chained engine counters).  Returns None
    when the run recorded no device time.  When a tracer is installed
    the probe timings are also recorded as child spans of a
    `device.stage_probe` trace (Perfetto-exportable like any other)."""
    device_s = float(engine_stats.get("device_s", 0.0))
    if device_s <= 0.0:
        return None
    raw = probe(n_nodes, r_dims=r_dims, iters=iters, fill_grid=fill_grid)
    total = sum(raw.values()) or 1.0
    fused_s = probe_fused(n_nodes, r_dims=r_dims, iters=iters,
                          fill_grid=fill_grid)
    stages = {name: device_s * (raw[name] / total) for name in STAGES}
    dominant = max(stages, key=stages.get)
    from nomad_tpu.ops.place import _FILL_GRID
    section = {
        "stages_s": {k: round(v, 6) for k, v in stages.items()},
        "fractions": {k: round(raw[k] / total, 4) for k in STAGES},
        "probe_raw_s": {k: round(raw[k], 6) for k in STAGES},
        "device_s": round(device_s, 6),
        "dirty_row_upload_s": round(
            float(engine_stats.get("put_basis_s", 0.0)), 6),
        "dominant_stage": dominant,
        "n_nodes": int(n_nodes),
        "fill_grid": int(fill_grid) if fill_grid else int(_FILL_GRID),
        "fused": {
            "wave_s": round(fused_s, 6),
            "phase_sum_s": round(total, 6),
            "fusion_speedup": round(total / fused_s, 3)
            if fused_s > 0 else None,
        },
        "pipeline_overlap_s": round(float(pipeline_overlap_s or 0.0), 6),
        "commit_overlap_s": round(float(commit_overlap_s or 0.0), 6),
    }
    if wave:
        lanes = int(wave.get("wave_lanes", 0))
        evals = int(wave.get("lane_evals", 0))
        slots = int(wave.get("lane_slots", 0))
        section["wave"] = {
            "wave_lanes": lanes,
            "lane_evals": evals,
            "lane_slots": slots,
            "lane_occupancy": round(evals / slots, 4) if slots else None,
            "donated_carries": int(wave.get("donated_carries", 0)),
            "overlap_chained": int(wave.get("overlap_chained", 0)),
        }
    tracer = tracing.active
    if tracer is not None:
        ctx = tracer.new_context()
        if ctx is not None:
            root = tracer.start(ctx, "device.stage_probe", "bench")
            child = tracer.child_ctx(ctx, root)
            now = time.time()
            t = now
            for name in STAGES:
                tracer.emit(child, f"device.{name}", t,
                            t + stages[name], node="bench",
                            fraction=section["fractions"][name])
                t += stages[name]
            tracer.finish(root, end=t)
    return section
