"""Sharded placement: the dense engine over a ('node_shard', 'wave') mesh.

The serving mesh is 2-D.  Along `node_shard` each device owns a
contiguous row shard of the [N, R] world: inside one scan step every
shard scores its local nodes, the global best node is found with pmax
(max score) + pmin (lowest global row among ties, matching the
single-chip argmax tie-break), and each shard applies the carry update
only to rows it owns.  Cross-shard information (the selected node's
spread value indices) moves via psum of a masked gather — an
ICI-friendly scalar collective rather than an all-gather of the whole
matrix.

Along `wave`, INDEPENDENT ready waves (bulk evals from different
namespaces, binned by the engine's wave_key) score concurrently on
disjoint device columns: each lane runs its own chained eval scan
against the shared usage basis, and the merged basis is the psum of the
lane deltas.  Lanes are blind to each other within one dispatch — the
plan applier's overlay/commit validation remains the capacity authority,
exactly as it is for evals split across dispatches.

`wave_mesh_shape` factors a device count into the (node_shard, wave)
grid; `NOMAD_TPU_WAVE_SHARDS` pins the wave extent.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nomad_tpu import knobs
from nomad_tpu.analysis import recompile
from nomad_tpu.ops.fit import score_fit
from nomad_tpu.ops.place import PlaceInputs, PlaceResult, TOP_K

# transfer-purity / recompile-budget (nomad_tpu.analysis): mesh dispatch
# is hot-path code; every jit built here is registered with the budget
_TRANSFER_HOT_PATH = True
_RECOMPILE_TRACKED = True

BIG = jnp.int32(2**31 - 1)

# mesh axis names: rows of the world along NODE_AXIS, independent eval
# waves along WAVE_AXIS
NODE_AXIS_NAME = "node_shard"
WAVE_AXIS_NAME = "wave"


def mesh_key(mesh) -> Optional[tuple]:
    """Stable identity of a device mesh: axis layout + device ids.

    `id(mesh)` is NOT a mesh identity — a re-created Mesh object can
    reuse the id of a dead one and resurrect its cache entries with
    stale shardings; conversely two distinct but equal Mesh objects must
    hit the same kernel cache entry (re-creating the mesh must not
    recompile).  Two meshes with the same axes over the same devices are
    interchangeable for sharding purposes."""
    if mesh is None:
        return None
    return (tuple(mesh.shape.items()),
            tuple(d.id for d in mesh.devices.flat))


def _put_host(mesh, spec, x):  # analysis: allow(transfer-purity) — per-wave delta/field operands are payload, shipped explicitly with their mesh sharding so the runtime guard stays "disallow"
    """Explicitly upload a host operand with its mesh sharding.  Device
    arrays pass through untouched (no reshard, no transfer); numpy
    operands would otherwise trip the steady-state transfer guard as
    implicit host->device (or, placed on one device, device->device)
    transfers inside jit."""
    if isinstance(x, np.ndarray):
        return jax.device_put(x, NamedSharding(mesh, spec))
    return x


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable shard_map: `jax.shard_map` (jax >= 0.6, kwarg
    `check_vma`) falls back to `jax.experimental.shard_map` (jax 0.4.x,
    kwarg `check_rep`).  Every shard_map in this package routes through
    here — calling `jax.shard_map` directly breaks on the pinned 0.4.x
    toolchain (the symbol simply doesn't exist there)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def wave_mesh_shape(n_devices: int,
                    wave_shards: Optional[int] = None) -> Tuple[int, int]:
    """Factor a device count into the (node_shard, wave) grid.

    Node sharding is the always-profitable axis (it divides the [N, M]
    scoring grids, where the FLOPs live), so the wave extent is the
    LARGEST divisor of `n_devices` that is <= sqrt(n_devices): 1 -> 1x1,
    2 -> 2x1, 4 -> 2x2, 8 -> 4x2.  `wave_shards` (or the
    NOMAD_TPU_WAVE_SHARDS env knob) pins the wave extent instead; a
    value that does not divide the device count falls back to 1 rather
    than dropping devices from the mesh."""
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    if wave_shards is None:
        wave_shards = knobs.get_int("NOMAD_TPU_WAVE_SHARDS")
    if wave_shards is not None:
        w = max(1, int(wave_shards))
        if n_devices % w != 0:
            w = 1
        return n_devices // w, w
    w = max(d for d in range(1, math.isqrt(n_devices) + 1)
            if n_devices % d == 0)
    return n_devices // w, w


def make_mesh(n_wave_shards: Optional[int] = None,
              n_node_shards: Optional[int] = None, devices=None) -> Mesh:
    """Named 2-D ('node_shard', 'wave') device mesh.  With no explicit
    shape, `wave_mesh_shape` picks the factorization for the full
    device set."""
    devices = list(devices if devices is not None else jax.devices())
    if n_wave_shards is None and n_node_shards is None:
        n_node_shards, n_wave_shards = wave_mesh_shape(len(devices))
    elif n_node_shards is None:
        n_node_shards = len(devices) // n_wave_shards
    elif n_wave_shards is None:
        n_wave_shards = len(devices) // n_node_shards
    dev = np.array(devices[:n_wave_shards * n_node_shards]).reshape(
        n_node_shards, n_wave_shards)
    return Mesh(dev, (NODE_AXIS_NAME, WAVE_AXIS_NAME))


def stack_inputs(inputs) -> PlaceInputs:
    """Stack a list of PlaceInputs (same padded shapes) along a leading
    eval-batch axis."""
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *inputs)


# PartitionSpecs for one eval's PlaceInputs, node axis sharded.  A leading
# 'wave' batch axis is prepended by place_eval_batch_sharded.
_NODE_AXIS = {
    "capacity": 0, "used": 0,
    "feasible": 1, "affinity": 1, "penalty": 1, "tg_count": 1,
    "spread_vidx": 2, "place_cap": 1,
    "has_affinity": None, "desired_count": None,
    "spread_desired": None, "spread_targeted": None, "spread_wfrac": None,
    "spread_counts": None, "spread_active": None,
    "demand": None, "slot_tg": None, "slot_active": None,
}


def _input_specs(batched: bool) -> PlaceInputs:
    specs = {}
    for name, axis in _NODE_AXIS.items():
        ndim = {"capacity": 2, "used": 2, "feasible": 2, "affinity": 2,
                "penalty": 2, "tg_count": 2, "spread_vidx": 3,
                "place_cap": 2,
                "has_affinity": 1, "desired_count": 1, "spread_desired": 3,
                "spread_targeted": 2, "spread_wfrac": 2, "spread_counts": 3,
                "spread_active": 2, "demand": 2, "slot_tg": 1,
                "slot_active": 1}[name]
        parts = [None] * ndim
        if axis is not None:
            parts[axis] = NODE_AXIS_NAME
        if batched:
            parts = [WAVE_AXIS_NAME] + parts
        specs[name] = P(*parts)
    return PlaceInputs(**specs)


def _place_step_sharded(inp: PlaceInputs, spread_algorithm: bool,
                        shard_offset: jax.Array, carry, slot):
    """One placement step on a node shard (mirrors ops.place._place_step;
    the selection and carry updates go through 'node_shard'
    collectives)."""
    used, tg_count, spread_counts, place_cap = carry
    g = inp.slot_tg[slot]
    d = inp.demand[slot]
    active = inp.slot_active[slot]
    n_local = used.shape[0]
    global_rows = shard_offset + jnp.arange(n_local)

    feas = inp.feasible[g] & (place_cap[g] != 0)
    util = used + d
    fits = jnp.all(util <= inp.capacity, axis=-1) & feas

    fit_score = score_fit(inp.capacity, util, spread_algorithm) / 18.0
    total = fit_score
    n_scorers = jnp.ones_like(fit_score)

    coll = tg_count[g].astype(jnp.float32)
    anti = -(coll + 1.0) / jnp.maximum(inp.desired_count[g].astype(jnp.float32), 1.0)
    has_coll = coll > 0.0
    total = total + jnp.where(has_coll, anti, 0.0)
    n_scorers = n_scorers + has_coll

    pen = inp.penalty[g]
    total = total - pen
    n_scorers = n_scorers + pen

    aff = inp.affinity[g]
    aff_on = inp.has_affinity[g] & (aff != 0.0)
    total = total + jnp.where(aff_on, aff, 0.0)
    n_scorers = n_scorers + aff_on

    # spread scoring: counts carry is replicated; per-node boost local
    from nomad_tpu.ops.place import _spread_boost
    sboost = _spread_boost(inp, g, spread_counts[g])
    sb_on = jnp.any(inp.spread_active[g]) & (sboost != 0.0)
    total = total + jnp.where(sb_on, sboost, 0.0)
    n_scorers = n_scorers + sb_on

    final = total / n_scorers
    masked = jnp.where(fits & active, final, -jnp.inf)

    # --- global argmax over 'node_shard': pmax score, pmin row among ties
    local_best = jnp.max(masked)
    global_best = jax.lax.pmax(local_best, NODE_AXIS_NAME)
    local_idx = jnp.argmax(masked)
    cand = jnp.where((local_best == global_best) & (global_best > -jnp.inf),
                     global_rows[local_idx], BIG)
    sel = jax.lax.pmin(cand, NODE_AXIS_NAME)
    ok = sel < BIG

    # --- carry updates: only the owning shard touches its rows
    sel_local = (global_rows == sel) & ok
    used = used + jnp.where(sel_local[:, None], d, 0.0)
    tg_count = tg_count + jnp.where(
        (jnp.arange(tg_count.shape[0]) == g)[:, None] & sel_local[None, :],
        1, 0)
    place_cap = place_cap - jnp.where(
        (jnp.arange(place_cap.shape[0]) == g)[:, None]
        & sel_local[None, :] & (place_cap > 0), 1, 0)
    # selected node's spread value indices: psum of masked gather
    K = inp.spread_vidx.shape[1]
    Vp1 = spread_counts.shape[-1]
    v_local = jnp.sum(jnp.where(sel_local[None, :], inp.spread_vidx[g], 0), axis=1)
    v = jax.lax.psum(v_local, NODE_AXIS_NAME)             # i32[K]
    upd = jax.nn.one_hot(jnp.minimum(v, Vp1 - 1), Vp1, dtype=spread_counts.dtype)
    upd = upd * (inp.spread_active[g] & (v < Vp1 - 1))[:, None] * ok
    spread_counts = spread_counts.at[g].add(upd)

    # per-slot metrics (global)
    fit_sel = jax.lax.psum(
        jnp.sum(jnp.where(sel_local, fit_score, 0.0)), NODE_AXIS_NAME)
    n_eval = jax.lax.psum(jnp.sum(feas & active), NODE_AXIS_NAME)
    n_exh = jax.lax.psum(jnp.sum(feas & ~fits & active), NODE_AXIS_NAME)
    k_local = min(TOP_K, masked.shape[0])
    top_s_l, top_i_l = jax.lax.top_k(masked, k_local)
    top_s = jax.lax.all_gather(top_s_l, NODE_AXIS_NAME, tiled=True)
    top_i = jax.lax.all_gather(global_rows[top_i_l], NODE_AXIS_NAME,
                               tiled=True)
    order = jnp.argsort(-top_s)[:TOP_K]

    out = (
        jnp.where(ok, sel, -1).astype(jnp.int32),
        jnp.where(ok, global_best, 0.0),
        jnp.where(ok, fit_sel, 0.0),
        n_eval.astype(jnp.int32),
        n_exh.astype(jnp.int32),
        top_i[order].astype(jnp.int32),
        top_s[order],
    )
    return (used, tg_count, spread_counts, place_cap), out


def _shard_body(inp: PlaceInputs, spread_algorithm: bool):
    """Runs inside shard_map for one eval: scan over slots."""
    idx = jax.lax.axis_index(NODE_AXIS_NAME)
    n_local = inp.used.shape[0]
    shard_offset = idx * n_local
    S = inp.demand.shape[0]
    carry0 = (inp.used, inp.tg_count, inp.spread_counts, inp.place_cap)
    step = functools.partial(_place_step_sharded, inp, spread_algorithm,
                             shard_offset)
    (used, _, _, _), outs = jax.lax.scan(step, carry0, jnp.arange(S))
    node, score, fit_s, n_eval, n_exh, top_i, top_s = outs
    return node, score, fit_s, n_eval, n_exh, top_i, top_s, used


def place_eval_batch_sharded(mesh: Mesh, stacked: PlaceInputs,
                             spread_algorithm: bool = False):
    """Place a batch of evals over the ('node_shard','wave') mesh.

    `stacked` has a leading eval-batch axis on every field (see
    stack_inputs); the batch is sharded over 'wave' and the node axis
    over 'node_shard'.  Returns per-eval (node, score, fit_score,
    nodes_evaluated, nodes_exhausted, top_nodes, top_scores, used_final).
    """
    in_specs = _input_specs(batched=True)

    def body(inp: PlaceInputs):
        # inside shard_map each device holds a local slice of the eval
        # batch; vmap over it (collectives batch across the vmapped axis)
        return jax.vmap(lambda one: _shard_body(one, spread_algorithm))(inp)

    W, NS = WAVE_AXIS_NAME, NODE_AXIS_NAME
    out_specs = (
        P(W, None), P(W, None), P(W, None),
        P(W, None), P(W, None), P(W, None, None),
        P(W, None, None), P(W, NS, None),
    )
    key = ("eval_batch", mesh_key(mesh), spread_algorithm)
    fn = _SERVING_FN_CACHE.get(key)
    if fn is None:
        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(in_specs,),
                               out_specs=out_specs, check_vma=False))
        recompile.register("sharded.eval_batch", fn)
        _SERVING_FN_CACHE[key] = fn
    return fn(stacked)


# --------------------------------------------------------------------------
# Serving-path kernels: the PlacementEngine's chained batch semantics over
# the 2-D serving mesh.  Within a wave lane the eval axis stays a lax.scan
# (eval e+1 scores against usage including eval e's placements — identical
# placements to the single-device engine, the property the conflict-free
# design relies on); the node axis, where the FLOPs live, shards across
# 'node_shard'.  Selection/ordering runs on [N]-vector collectives
# (all_gather/pmax/psum over ICI), which are KBs per wave — the scoring
# stacks and the [N, M] fill grid never leave their shard.
# --------------------------------------------------------------------------


def _apply_deltas_local(used, delta_rows, delta_vals, shard_offset):
    """Scatter global-row sparse deltas into a node-sharded usage matrix
    (rows outside this shard drop)."""
    n_local = used.shape[0]
    lrows = delta_rows - shard_offset
    ok = (lrows >= 0) & (lrows < n_local)
    lrows = jnp.where(ok, lrows, n_local)
    return used.at[lrows].add(
        jnp.where(ok[:, None], delta_vals, 0.0), mode="drop")


def make_serving_mesh(devices=None,
                      wave_shards: Optional[int] = None) -> Mesh:
    """The engine's serving mesh: the 2-D ('node_shard','wave')
    factorization over all devices.  Basis/capacity shard over
    'node_shard' (replicated across wave columns); only the laned bulk
    kernel populates the 'wave' axis."""
    devices = list(devices if devices is not None else jax.devices())
    n_node, n_wave = wave_mesh_shape(len(devices), wave_shards)
    dev = np.array(devices[:n_node * n_wave]).reshape(n_node, n_wave)
    return Mesh(dev, (NODE_AXIS_NAME, WAVE_AXIS_NAME))


def _set_rows_local(dev, rows, vals):
    """Shard-local row SET: global `rows` translate to this shard's
    local indices; rows outside the shard (and the row==N pad slots)
    drop, so each device writes only rows it owns."""
    n_local = dev.shape[0]
    lrows = rows - jax.lax.axis_index(NODE_AXIS_NAME) * n_local
    ok = (lrows >= 0) & (lrows < n_local)
    lrows = jnp.where(ok, lrows, n_local)
    return dev.at[lrows].set(vals, mode="drop")


def _add_rank1_local(dev, rows, counts, demand):
    """Shard-local twin of the native scatter_add_rank1 export:
    dev[rows[k]] += counts[k] * demand, rows translated per shard."""
    n_local = dev.shape[0]
    lrows = rows - jax.lax.axis_index(NODE_AXIS_NAME) * n_local
    ok = (lrows >= 0) & (lrows < n_local)
    lrows = jnp.where(ok, lrows, n_local)
    vals = counts[:, None].astype(jnp.float32) * demand
    return dev.at[lrows].add(vals, mode="drop")


def serving_update_fns(mesh: Mesh):
    """Jitted (set_rows, add_rank1) scatter pair for a node-sharded
    [N, R] resident matrix (parallel.world.DeviceWorld).  Rows/values are
    replicated operands (KBs); the sharded matrix never moves — each
    shard scatters its own rows, no cross-device gather of the operand."""
    key = ("update", mesh_key(mesh))
    fns = _SERVING_FN_CACHE.get(key)
    if fns is None:
        NS = NODE_AXIS_NAME
        set_fn = jax.jit(shard_map(
            _set_rows_local, mesh=mesh,
            in_specs=(P(NS, None), P(None), P(None, None)),
            out_specs=P(NS, None), check_vma=False))
        add_fn = jax.jit(shard_map(
            _add_rank1_local, mesh=mesh,
            in_specs=(P(NS, None), P(None), P(None), P(None)),
            out_specs=P(NS, None), check_vma=False))
        recompile.register("sharded.serving_set", set_fn)
        recompile.register("sharded.serving_add", add_fn)
        fns = (set_fn, add_fn)
        _SERVING_FN_CACHE[key] = fns
    return fns


def _field_specs_batched() -> dict:
    """PartitionSpecs for the per-eval field dict (PlaceInputs minus the
    shared capacity/used basis), leading eval batch axis unsharded on
    the serving mesh (the eval axis is a chained scan)."""
    specs = {}
    for name, axis in _NODE_AXIS.items():
        if name in ("capacity", "used"):
            continue
        ndim = {"feasible": 2, "affinity": 2, "penalty": 2, "tg_count": 2,
                "spread_vidx": 3, "place_cap": 2, "has_affinity": 1,
                "desired_count": 1, "spread_desired": 3,
                "spread_targeted": 2, "spread_wfrac": 2,
                "spread_counts": 3, "spread_active": 2, "demand": 2,
                "slot_tg": 1, "slot_active": 1}[name]
        parts = [None] * ndim
        if axis is not None:
            parts[axis] = NODE_AXIS_NAME
        specs[name] = P(*([None] + parts))
    return specs


_SERVING_FN_CACHE: dict = {}

# Loan/adopt protocol for every donate_argnums jit in this module (the
# donation-safety checker fails an undeclared donating jit).  `fn` is
# the bulk serving kernel built in place_bulk_batch_sharded and
# registered as "sharded.bulk".
_DONATE_PROTOCOL = {
    "fn":
        "arg 1 (used0) is the loaned usage basis: the engine takes it "
        "via world.loan_basis() before dispatch, never reads the "
        "loaned buffer in flight, and adopts the psum-merged carry "
        "(used_tot) via world.adopt_basis() — or invalidates the "
        "basis when the dispatch fails",
}


def place_batch_sharded(mesh: Mesh, capacity, used0, fields: dict,
                        delta_rows, delta_vals,
                        spread_algorithm: bool = False):
    """Chained scan-path batch (engine _dispatch_group) over the serving
    mesh.  `fields`: per-eval PlaceInputs fields (minus capacity/used,
    which ride separately as the batch-shared basis), each with a leading
    E axis; `delta_rows` i32[E, D] / `delta_vals` f32[E, D, R] are each
    eval's sparse usage adjustments (row == N drops).  Returns (packed
    f32[E, S, 5+2K] — the engine's unpack_outputs layout — and the
    node-sharded final usage)."""
    from nomad_tpu.ops.place import _pack_outputs

    def body(cap, u0, flds, drows, dvals):
        idx = jax.lax.axis_index(NODE_AXIS_NAME)
        n_local = cap.shape[0]
        shard_offset = idx * n_local

        def eval_step(used, ev):
            one, dr, dv = ev
            used = _apply_deltas_local(used, dr, dv, shard_offset)
            inp = PlaceInputs(capacity=cap, used=used, **one)
            S = inp.demand.shape[0]
            carry0 = (used, inp.tg_count, inp.spread_counts,
                      inp.place_cap)
            step = functools.partial(_place_step_sharded, inp,
                                     spread_algorithm, shard_offset)
            (used_f, _, _, _), outs = jax.lax.scan(step, carry0,
                                                   jnp.arange(S))
            return used_f, _pack_outputs(*outs)

        used_final, packed = jax.lax.scan(eval_step, u0,
                                          (flds, drows, dvals))
        return packed, used_final

    NS = NODE_AXIS_NAME
    key = ("scan", mesh_key(mesh), spread_algorithm)
    fn = _SERVING_FN_CACHE.get(key)
    if fn is None:
        in_specs = (P(NS, None), P(NS, None),
                    _field_specs_batched(), P(None, None),
                    P(None, None, None))
        out_specs = (P(None, None, None), P(NS, None))
        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_vma=False))
        recompile.register("sharded.scan", fn)
        _SERVING_FN_CACHE[key] = fn
    return fn(capacity, used0, fields,
              _put_host(mesh, P(None, None), delta_rows),
              _put_host(mesh, P(None, None, None), delta_vals))


def place_bulk_batch_sharded(mesh: Mesh, capacity, used0,
                             feasible, affinity, has_affinity, desired,
                             penalty, coll0, demand, count,
                             delta_rows, delta_vals,
                             spread_algorithm: bool = False,
                             max_waves: int = 65536,
                             fill_grid: int = 64,
                             donate: bool = False):
    """Laned chained bulk wavefront batch (engine place_bulk) over the
    2-D ('node_shard','wave') mesh — the C2M-scale multi-chip path.

    Every per-eval input carries leading [W, E] axes, W the mesh's wave
    extent: lane w holds its own chained eval sequence (the engine bins
    requests into lanes by wave_key; pad slots ride with count == 0).
    Node-axis fields are [W, E, N] sharded P('wave', None, 'node_shard');
    scalars [W, E].  Within a lane each wave computes its [N_local, M]
    scoring/fill grid on the shard, then resolves the global greedy
    order from two all_gathered [N] vectors (wave-start score +
    per-node run), every device in the lane's column deriving the
    identical per-node placement so only its own rows mutate.  Lanes
    never communicate until the final basis merge:
    `used_final = u0 + psum_over_wave(lane_delta)`.

    Returns (assign i32[W, E, N], scores f32[W, E, N], placed/n_eval/
    n_exh/waves i32[W, E] each, used_final node-sharded).  With
    `donate=True` the `used0` buffer is donated to the kernel — the
    caller hands over its resident basis and adopts `used_final` in its
    place (world.loan_basis / adopt_basis), so the steady state ships
    zero basis bytes."""
    from nomad_tpu.ops.place import (
        _bulk_scores,
        bulk_run_lengths as _bulk_run_lengths,
        bulk_wave_grid as _bulk_wave_grid,
    )

    def body(cap, u0, feas_l, aff_l, hasa_l, des_l, pen_l, coll_l,
             dem_l, cnt_l, drows_l, dvals_l):
        idx = jax.lax.axis_index(NODE_AXIS_NAME)
        n_local = cap.shape[0]
        shard_offset = idx * n_local
        # lane-local blocks arrive [1, E, ...]: drop the unit wave axis
        feas_e, aff_e, pen_e, coll_e = (
            feas_l[0], aff_l[0], pen_l[0], coll_l[0])
        hasa_e, des_e, cnt_e = hasa_l[0], des_l[0], cnt_l[0]
        dem_e, drows, dvals = dem_l[0], drows_l[0], dvals_l[0]

        def eval_step(carry, ev):
            used_in, exact = carry
            feasible, affinity, has_aff, desired, penalty, coll0, \
                demand, count, dr, dv = ev
            # deltas are scoped to THIS eval (backed out of the carry
            # below), matching place_bulk_batch_jit: uncommitted stops of
            # one eval never leak into another's scoring
            used = _apply_deltas_local(used_in, dr, dv, shard_offset)
            delta_local = used - used_in
            desired_f = desired.astype(jnp.float32)

            def cond(c):
                u, coll, placed, assign, stuck, waves = c
                return (placed < count) & ~stuck & (waves < max_waves)

            def wave(c):
                u, coll, placed, assign, stuck, waves = c
                # the shared single-source-of-truth scoring grid
                # (ops.place.bulk_wave_grid) on this shard's rows; only
                # the reductions/selection go through collectives
                ms, fits_m, score_m = _bulk_wave_grid(
                    cap, u, demand, feasible, affinity, has_aff,
                    desired_f, penalty, coll, spread_algorithm,
                    fill_grid)

                fits = fits_m[:, 0]
                cur = jnp.where(fits, score_m[:, 0], -jnp.inf)
                any_fit = jax.lax.pmax(
                    jnp.any(fits).astype(jnp.int32), NODE_AXIS_NAME) > 0
                s_star = jax.lax.pmax(
                    jnp.max(jnp.where(fits_m[:, 1], score_m[:, 1],
                                      -jnp.inf)), NODE_AXIS_NAME)
                # global top-2 of cur: local top-2, gathered
                l2 = jax.lax.top_k(cur, 2)[0]
                g2 = jax.lax.top_k(
                    jax.lax.all_gather(l2, NODE_AXIS_NAME, tiled=True),
                    2)[0]
                gmax, gsecond = g2[0], g2[1]
                strict = fits & (cur > s_star)
                use_strict = jax.lax.pmax(
                    jnp.any(strict).astype(jnp.int32), NODE_AXIS_NAME) > 0
                tie = fits & (cur == gmax)
                wv = jnp.where(use_strict, strict, tie)
                second = jnp.where(cur == gmax, gsecond, gmax)
                run = _bulk_run_lengths(ms, fits_m, score_m, second)
                base = jnp.where(wv, run, 0).astype(jnp.int32)

                # global greedy order from gathered [N] vectors; every
                # shard computes the identical per-node allocation and
                # slices out its own rows
                cur_g = jax.lax.all_gather(cur, NODE_AXIS_NAME,
                                           tiled=True)
                base_g = jax.lax.all_gather(base, NODE_AXIS_NAME,
                                            tiled=True)
                wave_g = base_g > 0
                order = jnp.argsort(jnp.where(wave_g, -cur_g, jnp.inf))
                base_sorted = base_g[order]
                prefix = jnp.cumsum(base_sorted) - base_sorted
                remaining = count - placed
                alloc_sorted = jnp.clip(remaining - prefix, 0,
                                        base_sorted)
                per_node_g = jnp.zeros(base_g.shape[0], jnp.int32) \
                    .at[order].set(alloc_sorted)
                per_node = jax.lax.dynamic_slice(
                    per_node_g, (shard_offset,), (n_local,))

                u = u + per_node[:, None].astype(jnp.float32) * demand
                coll = coll + per_node
                assign = assign + per_node
                placed = placed + jnp.sum(per_node_g)
                return (u, coll, placed, assign, ~any_fit, waves + 1)

            c0 = (used, coll0, jnp.int32(0),
                  jnp.zeros(n_local, jnp.int32), jnp.array(False),
                  jnp.int32(0))
            used_f, coll_f, placed, assign, _, waves = \
                jax.lax.while_loop(cond, wave, c0)

            # final scores + metrics via the shared scoring stack
            # (ops.place._bulk_scores on local rows; counts via psum)
            scores, fits_f = _bulk_scores(
                cap, used_f, demand, feasible, affinity, has_aff,
                desired, penalty, coll_f, spread_algorithm)
            n_eval = jax.lax.psum(jnp.sum(feasible), NODE_AXIS_NAME)
            n_exh = jax.lax.psum(jnp.sum(feasible & ~fits_f),
                                 NODE_AXIS_NAME)
            out = (assign, scores, placed.astype(jnp.int32),
                   n_eval.astype(jnp.int32), n_exh.astype(jnp.int32),
                   waves.astype(jnp.int32))
            # two carries (see ops.place._place_bulk_batch exact_out):
            # the chain carry keeps the wavefront's incremental adds
            # (scoring parity with the single-device kernel), the exact
            # carry is the rank-1 reconstruction the adopted basis uses
            exact = exact + assign[:, None].astype(jnp.float32) * demand
            return (used_f - delta_local, exact), out

        (used_final, exact_final), outs = jax.lax.scan(
            eval_step, (u0, u0),
            (feas_e, aff_e, hasa_e, des_e, pen_e, coll_e, dem_e, cnt_e,
             drows, dvals))
        # merge lanes: each lane chained independently against the
        # shared basis; the combined usage is the basis plus every
        # lane's net rank-1 placement delta (the psum result is
        # identical on all wave columns, satisfying the replicated
        # out_spec; inactive lanes contribute exact zeros)
        used_tot = u0 + jax.lax.psum(exact_final - u0, WAVE_AXIS_NAME)
        assign, scores, placed, n_eval, n_exh, waves = outs
        return (assign[None], scores[None], placed[None], n_eval[None],
                n_exh[None], waves[None], used_tot)

    # Loan/adopt protocol for the donating jit below (`fn`, registered
    # as "sharded.bulk"): arg 1 (used0) is the loaned usage basis —
    # world.loan_basis() before dispatch, no reads of the loaned buffer
    # until world.adopt_basis(used_tot) lands the psum-merged carry.
    NS, W = NODE_AXIS_NAME, WAVE_AXIS_NAME
    in_specs = (P(NS, None), P(NS, None),
                P(W, None, NS), P(W, None, NS), P(W, None), P(W, None),
                P(W, None, NS), P(W, None, NS), P(W, None, None),
                P(W, None), P(W, None, None), P(W, None, None, None))
    key = ("bulk", mesh_key(mesh), spread_algorithm, max_waves,
           fill_grid, donate)
    fn = _SERVING_FN_CACHE.get(key)
    if fn is None:
        out_specs = (P(W, None, NS), P(W, None, NS), P(W, None),
                     P(W, None), P(W, None), P(W, None), P(NS, None))
        mapped = shard_map(body, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
        # donate_argnums=(1,): used0 and used_final share shape [N, R]
        # and sharding P('node_shard', None), so XLA aliases the carry
        # in place of a fresh allocation + a host re-upload next wave
        fn = jax.jit(mapped, donate_argnums=(1,)) if donate \
            else jax.jit(mapped)
        recompile.register("sharded.bulk", fn)
        _SERVING_FN_CACHE[key] = fn
    args = [capacity, used0, feasible, affinity, has_affinity, desired,
            penalty, coll0, demand, count, delta_rows, delta_vals]
    return fn(*[_put_host(mesh, spec, a) for spec, a in zip(in_specs, args)])
