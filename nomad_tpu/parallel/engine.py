"""PlacementEngine: adaptive batching dispatcher for the dense kernels.

The north-star serving path (reference nomad/worker.go:81-85 — N scheduler
workers processing evals concurrently — and BASELINE.json "pmap across
evaluations in the EvalBroker queue"): scheduler workers block in
`place()`, a single dispatcher thread coalesces every request that arrived
while the previous dispatch was in flight into ONE device call
(`ops.place.place_batch_packed_jit`, a chained `lax.scan` over the eval
axis over the packed single-leaf transport), resolves the G x N-scale
tensors through a content-addressed device-resident cache (hits ship
zero bytes), ships the rest with one host->device transfer and fetches
all results with one device->host transfer.

Why chained instead of independent (vmap/pmap): evals scored against the
same usage basis all argmax onto the same best nodes, so independent
batching turns into plan-applier conflicts and retries; the chained scan
threads the proposed-usage matrix through the batch, making results
identical to sequential worker processing while paying one transfer
round-trip per *batch* instead of per *eval*.  On high-latency runtimes
(TPU behind a network tunnel: ~20-120 ms per transfer) this is the
difference between ~7 evals/s and hundreds.

Batching is adaptive with no artificial delay window: an idle engine
dispatches a lone request immediately (an E=1 variant of the packed
kernel, its own one-time XLA compile), and the in-flight device time is
the window in which the next batch accumulates.
"""
from __future__ import annotations

import os
import threading
import time as _time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu import chaos, knobs
from nomad_tpu import native as _native
from nomad_tpu import tracing
from nomad_tpu.analysis import race
from nomad_tpu.encode.matrixizer import NUM_RESOURCE_DIMS, pad_to_bucket
from nomad_tpu.ops.place import (
    SPARSE_CAP,
    PlaceInputs,
    PlaceResult,
    bulk_heavy_digest,
    heavy_digest,
    heavy_dims,
    pack_bulk_heavy,
    pack_bulk_light,
    pack_heavy,
    pack_light,
    place_batch_packed_jit,
    place_bulk_batch_donate_jit,
    place_bulk_batch_jit,
    unpack_bulk_batch,
    unpack_outputs,
)

from nomad_tpu.parallel.world import DeviceWorld, mesh_key

# transfer-purity (nomad_tpu.analysis): the dispatch loop is hot-path —
# implicit host<->device movement is a finding; the few sanctioned
# device_put sites (cache fills, per-dispatch dynamic leaf) carry
# transfer-purity suppression comments with their reason
_TRANSFER_HOT_PATH = True

# fixed sparse-delta slot count per eval: a CONSTANT so the delta axis
# never forks another XLA compile variant (every distinct D was a full
# recompile, billed mid-serving).  Evals with more deltas than this fold
# them into a pre-applied basis instead (rare: deltas are one eval's
# stops + sticky preplacements).
_DELTA_BUCKET = 64
# canonical slot-axis buckets, same rationale: per-eval slot counts vary
# (retries place the remainder), and every distinct S was a compile
_S_BUCKETS = (16, 128, 1024)


def _s_bucket(n: int) -> int:
    return next((b for b in _S_BUCKETS if b >= n), pad_to_bucket(n))


def _fold_overflow(basis: "np.ndarray", deltas):
    """Apply an oversized delta list directly into a PRIVATE basis copy
    (the fixed delta bucket cannot carry it without forking an XLA
    compile variant).  Returns the effective shipped delta list ([]) —
    consumers must use it instead of the request's own deltas or the
    fold double-counts."""
    n = basis.shape[0]
    for row, vec in deltas:
        if row < n:
            basis[row] += vec
    return []


class _DeviceCache:
    """Content-addressed device-resident array cache (LRU).

    The G x N-scale placement tensors are identical across every eval of
    the same (job version, cluster epoch, alloc set) — the common case for
    a job's worth of evals and for retries — so a content fingerprint
    dedupes them and a hit ships ZERO bytes to the device.  This is the
    SURVEY §7 prescription ("keep the node matrix resident, ship deltas")
    applied to the per-eval tensors that actually dominate transfer bytes
    (VERDICT r3: put_s was 79%% of e2e wall)."""

    def __init__(self, max_entries: int = 128):
        from collections import OrderedDict
        self.max_entries = max_entries
        self._d = OrderedDict()
        self._stacks = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def _get_or_put(self, key, build):  # analysis: allow(transfer-purity) — cache-fill upload: a miss ships once so every later hit ships zero bytes
        import jax
        return self._get_or_put_device(key, lambda: jax.device_put(build()))

    def _get_or_put_device(self, key, build_device):
        """build_device() must return the final (device-resident) value."""
        with self._lock:
            v = self._d.get(key)
            if v is not None:
                self._d.move_to_end(key)
                self.hits += 1
                return v
        arr = build_device()
        with self._lock:
            self._d[key] = arr
            self.misses += 1
            while len(self._d) > self.max_entries:
                self._d.popitem(last=False)
        return arr

    def sharded(self, tag, mesh, pytree, shardings, key=None):
        """Content-addressed sharded placement of a pytree: a hit returns
        the device-resident (already mesh-sharded) arrays with zero bytes
        shipped — the multi-chip twin of heavy()/bulk_heavy().

        `pytree` may be a zero-arg callable so a hit skips BUILDING the
        host arrays entirely (the per-dispatch np.stack of an E-chain was
        itself a hit-path cost at C2M-1M rates).  `key` carries a
        caller-computed content key (per-request digests); when omitted
        the pytree leaves are hashed, which forces materialization.

        Keyed on the mesh's (axis layout, device ids) — `id(mesh)` is not
        an identity: a re-created Mesh can reuse a dead mesh's id and
        resurrect entries with stale shardings."""
        import hashlib

        import jax
        build = pytree if callable(pytree) else None
        if key is None:
            if build is not None:
                pytree = build()
                build = None
            h = hashlib.blake2b(digest_size=16)
            for leaf in jax.tree_util.tree_leaves(pytree):
                h.update(np.ascontiguousarray(leaf).tobytes())
            key = h.digest()
        if build is None:
            tree = pytree
            build = lambda: tree                 # noqa: E731
        full_key = ("sh", tag, mesh_key(mesh), key)
        return self._get_or_put_device(
            full_key,
            lambda: jax.device_put(build(), shardings))  # analysis: allow(transfer-purity) — sharded cache fill: one sanctioned upload per content key

    def heavy(self, inputs: PlaceInputs):
        """Device-resident packed heavy block for one eval's inputs."""
        key = (heavy_dims(inputs), heavy_digest(inputs))
        return self._get_or_put(key, lambda: pack_heavy(inputs))

    def bulk_heavy(self, r, digest: bytes = None):
        """Device-resident packed node-axis block of one bulk request.
        `digest` lets dispatch reuse a digest it already computed."""
        if digest is None:
            digest = bulk_heavy_digest(r.feasible, r.affinity, r.penalty,
                                       r.coll0)
        key = ("bulk", r.feasible.shape[0], digest)
        return self._get_or_put(
            key, lambda: pack_bulk_heavy(r.feasible, r.affinity,
                                         r.penalty, r.coll0))

    def stack(self, key, build_device):
        """Device-resident STACKED per-dispatch tensor (the [E, ...]
        chain of an entire bulk dispatch).  Entries are E x the per-eval
        size, so they keep their own short LRU instead of crowding the
        main cache; a hit skips both the host stack and the device-side
        jnp.stack dispatch."""
        with self._lock:
            v = self._stacks.get(key)
            if v is not None:
                self._stacks.move_to_end(key)
                self.hits += 1
                return v
        arr = build_device()
        with self._lock:
            self._stacks[key] = arr
            self.misses += 1
            while len(self._stacks) > 4:
                self._stacks.popitem(last=False)
        return arr


@dataclass
class _Request:
    cm: object                      # ClusterMatrix the inputs were built from
    inputs: PlaceInputs             # numpy-backed; .used already has deltas applied
    deltas: List[Tuple[int, np.ndarray]]   # (row, f32[R]) sparse usage deltas
    spread_algorithm: bool
    future: Future
    trace: object = None            # (ctx, submit_ts) for sampled evals

    def shape_key(self):
        i = self.inputs
        # the slot axis pads to a canonical bucket at dispatch, so evals
        # sharing a bucket batch together regardless of raw slot count
        return (id(self.cm), self.spread_algorithm, i.feasible.shape,
                i.spread_vidx.shape, i.spread_desired.shape,
                _s_bucket(i.demand.shape[0]), i.demand.shape[1])


@dataclass
class _BulkRequest:
    """One wavefront bulk eval (many identical slots of one task group,
    spreads/distinct/ports/devices inactive) for the batched bulk kernel."""
    cm: object
    feasible: np.ndarray            # bool[N]
    affinity: np.ndarray            # f32[N]
    has_affinity: bool
    desired: int
    penalty: np.ndarray             # bool[N]
    coll0: np.ndarray               # i32[N] existing co-placements
    demand: np.ndarray              # f32[R]
    count: int
    deltas: List[Tuple[int, np.ndarray]]
    spread_algorithm: bool
    future: Future
    trace: object = None            # (ctx, submit_ts) for sampled evals
    # lane affinity on the 2-D mesh: requests sharing a wave_key (the
    # eval's namespace) chain in ONE lane; distinct keys spread across
    # the mesh's 'wave' columns and score concurrently
    wave_key: str = ""

    def shape_key(self):
        return ("bulk", id(self.cm), self.spread_algorithm,
                self.feasible.shape[0])


@dataclass
class _PendingBulk:
    """One in-flight bulk dispatch (donated-carry pipeline): the device
    computes while the engine preps + dispatches the next part against
    the adopted carry; _drain_record fetches and resolves it."""
    reqs: List
    out: object                     # device outputs (packed or tuple)
    world: object                   # DeviceWorld the dispatch scored on
    deltas_per: List
    mapping: object                 # sharded lane mapping or None
    donated: bool
    t_dispatch: float


class PlacementEngine:
    """One per process.  Thread-safe; callers block in `place()`.

    In-flight usage overlay: the basis each dispatch starts from is
    `cm.used + overlay`, where the overlay sums the placements (and
    sticky pre-placement adds) of every eval whose plan has not yet
    committed.  Without it, batch N+1 would score against state that
    misses batch N's still-uncommitted plans and pile onto the same
    best-fit nodes (the reference pays for this optimism with plan-applier
    partial commits + scheduler retries, worker.go:81-85 /
    plan_apply.go:400).  Callers release their contribution via
    `complete(ticket)` once their plan has been applied (or abandoned) —
    the scheduler does this right after Planner.SubmitPlan returns."""

    # happens-before (nomad_tpu.analysis): the in-flight overlay table is
    # written by scheduler workers (register_external*), the plan applier
    # (complete_many) and the engine thread (_register/_basis_for)
    # concurrently; every access must hold _overlay_lock.  The runtime
    # race detector (NOMAD_TPU_RACE=1) traces it through these hooks.
    _RACE_TRACED = {"_overlays": "_overlay_lock"}

    # eval-axis compile buckets: lax.scan compile cost is E-independent
    # (one While body), so buckets only bound padding waste — scan-path
    # pad evals still run their S slot steps, bulk pads exit immediately.
    # Bulk chains run longer (pads are free and each dispatch pays a
    # runtime-link round trip, so more evals per trip wins at C2M-1M
    # rates); scan chains stay shorter (pad evals still scan S slots).
    E_BUCKETS = (1, 8, 16, 48)
    BULK_E_BUCKETS = (1, 8, 16, 48, 128, 512)

    def __init__(self, max_batch: int = 512,
                 shard_min_nodes: Optional[int] = None):
        # batches are sliced at max_batch before grouping; scan-path
        # groups re-chunk to their largest compile bucket below
        self.max_batch = min(max_batch, self.BULK_E_BUCKETS[-1])
        self.scan_max_batch = self.E_BUCKETS[-1]
        # multi-chip serving: when >1 device is visible, dispatches whose
        # node axis reaches shard_min_nodes (and divides the device
        # count) route through the ('nodes',)-mesh kernels — the
        # "pmap across the EvalBroker queue" north star, with the eval
        # axis kept chained for single-device-identical placements.
        # Sharding is the DEFAULT on multi-device meshes: the floor only
        # excludes toy worlds where per-wave collective latency exceeds
        # the scoring work (>=16 rows/shard on an 8-device mesh).
        # NOMAD_TPU_SHARD=0 disables; NOMAD_TPU_SHARD_MIN tunes.
        if shard_min_nodes is None:
            shard_min_nodes = knobs.get_int("NOMAD_TPU_SHARD_MIN")
        self.shard_min_nodes = shard_min_nodes
        # per-eval bulk heavy block is f32[4N]: cap the eval-axis chain
        # so one dispatch's stacked tensors stay under this byte budget
        # (100K-node worlds at the 512-eval bucket would be ~1 GB)
        self.bulk_bytes_budget = knobs.get_int("NOMAD_TPU_BULK_BYTES")
        # fused wave dispatch (NOMAD_TPU_FUSE=0 restores the 3-way
        # sparse/delta/dense format split): one device call per bulk
        # wave — the format split paid ~1.5-2 dispatch+D2H round trips
        # per wave on mixed serving traffic for transfer savings that
        # stopped mattering once the heavy blocks went device-resident
        self.fuse = knobs.get_bool("NOMAD_TPU_FUSE")
        # donated-carry bulk dispatch (NOMAD_TPU_DONATE=0 restores the
        # copy-on-dispatch carry): the usage-basis buffer is donated to
        # the kernel and its carry output adopted as the new resident
        # basis (world.loan_basis/adopt_basis) — the put_basis re-upload
        # per wave (BENCH_r05: 0.37 s) drops to zero bytes
        self.donate = knobs.get_bool("NOMAD_TPU_DONATE")
        # upload/compute overlap (NOMAD_TPU_OVERLAP=0 disables): hold
        # ONE bulk dispatch in flight and prep + dispatch the next part
        # against the adopted carry while the device computes — requires
        # donation (the carry is what makes the in-flight placements
        # visible to the chained dispatch without a resolve barrier)
        self.overlap = self.donate and \
            knobs.get_bool("NOMAD_TPU_OVERLAP")
        self._pending: Optional[_PendingBulk] = None
        # (t0, t1) wall windows of in-flight device compute (bulk:
        # dispatch -> fetch complete) — intersected with upload_windows
        # (host-side stack/update/dispatch prep) for the bench's
        # pipeline_overlap_s, and with the applier's commit-fsync
        # windows for commit_overlap_s, in the device_stages block
        from collections import deque
        self.device_windows = deque(maxlen=8192)
        self.upload_windows = deque(maxlen=8192)
        self._serving_mesh = None
        self._mesh_checked = False
        self._queue: List[_Request] = []
        self._cv = threading.Condition()
        self._stop = False
        self._overlay_lock = threading.Lock()
        # serializes bulk-path basis-read -> kernel -> register windows so
        # concurrent bulk evals cannot pile onto the same nodes
        self.bulk_gate = threading.RLock()
        self._overlays: Dict[int, np.ndarray] = {}   # id(cm) -> f32[N, R]
        # id(cm) -> {device gid -> i32[N] in-flight instance counts}
        self._dev_overlays: Dict[int, Dict[str, np.ndarray]] = {}
        self._tickets: Dict[int, Tuple[int, List[Tuple[int, np.ndarray]]]] = {}
        self._dev_tickets: Dict[int, Tuple[int, List[Tuple[str, int, int]]]] = {}
        self._next_ticket = 1
        # called (outside locks) whenever the in-flight overlay fully
        # drains: transient over-reservation may have failed placements
        # that would now succeed, so the server re-queues blocked evals
        self.on_drain = None
        self.stats = {"dispatches": 0, "batched_evals": 0, "single_evals": 0,
                      "max_batch_seen": 0, "tickets_open": 0,
                      "stack_s": 0.0, "put_s": 0.0, "device_s": 0.0,
                      "resolve_s": 0.0, "cache_hits": 0, "cache_misses": 0,
                      "bulk_evals": 0, "waves": 0, "max_waves_seen": 0,
                      # fused-path health: bulk_groups counts bulk wave
                      # groups, bulk_parts the device calls they took —
                      # fused steady state holds parts == groups, and
                      # bench --smoke gates on the ratio
                      "bulk_groups": 0, "bulk_parts": 0,
                      # donated-carry / 2-D-mesh health: donated_carries
                      # counts dispatches whose basis was donated (the
                      # steady state holds this == bulk_parts when
                      # NOMAD_TPU_DONATE=1), wave_lanes the peak count
                      # of concurrently-scoring mesh lanes, lane_evals /
                      # lane_slots the laned occupancy (evals shipped vs
                      # W x E slots compiled), overlap_chained the bulk
                      # dispatches issued while the previous one was
                      # still in flight on device
                      "donated_carries": 0, "wave_lanes": 0,
                      "lane_evals": 0, "lane_slots": 0,
                      "overlap_chained": 0}
        self._cache = _DeviceCache()
        # device-resident worlds: (id(cm), N, mesh identity) ->
        # DeviceWorld (epoch-uploaded capacity/basis, scatter deltas);
        # LRU over stale cm epochs
        from collections import OrderedDict
        self._worlds: "OrderedDict[tuple, DeviceWorld]" = OrderedDict()
        self._worlds_lock = threading.Lock()
        # serving readiness: compiled variants persist across processes
        # (utils.enable_compile_cache docstring) — must be set before the
        # first jit call of this process
        from nomad_tpu.utils import enable_compile_cache
        enable_compile_cache()
        self._thread = threading.Thread(
            target=self._run, name="placement-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- public

    def place(self, cm, inputs: PlaceInputs,
              deltas: Optional[Sequence[Tuple[int, np.ndarray]]] = None,
              spread_algorithm: bool = False) -> Tuple[PlaceResult, int]:
        """Returns (result, ticket).  The caller MUST call
        `complete(ticket)` once the resulting plan has been submitted (or
        will never be), releasing its in-flight usage contribution."""
        req = _Request(cm=cm, inputs=inputs, deltas=list(deltas or ()),
                       spread_algorithm=spread_algorithm, future=Future())
        if tracing.active is not None:
            ctx = tracing.current()
            if ctx is not None:
                req.trace = (ctx, _time.time())
        with self._cv:
            if self._stop:
                raise RuntimeError("placement engine stopped")
            self._queue.append(req)
            self._cv.notify()
        return req.future.result()

    def place_bulk_begin(self, cm, *, feasible, affinity, has_affinity,
                         desired, penalty, coll0, demand, count,
                         deltas: Optional[Sequence[Tuple[int, np.ndarray]]]
                         = None,
                         spread_algorithm: bool = False,
                         wave_key: str = "") -> Future:
        """Enqueue a bulk wavefront placement and return its Future
        (result tuple = place_bulk's).  Lets a multi-group eval submit
        EVERY eligible group before waiting: the engine chains them (and
        other workers' evals) into one device dispatch instead of one
        blocking round trip per group — the C2M-1M path, where jobs are
        many small groups.  FIFO order + the engine thread's resolve-
        before-next-dispatch discipline preserve exact chained
        semantics.  `wave_key` (the eval's namespace) steers 2-D-mesh
        lane binning: requests sharing a key chain in one lane, distinct
        keys score concurrently across the mesh's wave columns."""
        req = _BulkRequest(
            cm=cm, feasible=np.asarray(feasible, bool),
            affinity=np.asarray(affinity, np.float32),
            has_affinity=bool(has_affinity), desired=int(desired),
            penalty=np.asarray(penalty, bool),
            coll0=np.asarray(coll0, np.int32),
            demand=np.asarray(demand, np.float32), count=int(count),
            deltas=list(deltas or ()), spread_algorithm=spread_algorithm,
            future=Future(), wave_key=str(wave_key))
        if tracing.active is not None:
            ctx = tracing.current()
            if ctx is not None:
                req.trace = (ctx, _time.time())
        with self._cv:
            if self._stop:
                raise RuntimeError("placement engine stopped")
            self._queue.append(req)
            self._cv.notify()
        return req.future

    def place_bulk(self, cm, *, feasible, affinity, has_affinity, desired,
                   penalty, coll0, demand, count,
                   deltas: Optional[Sequence[Tuple[int, np.ndarray]]] = None,
                   spread_algorithm: bool = False, wave_key: str = ""):
        """Wavefront bulk placement of `count` identical slots, batched
        with concurrent bulk evals into one chained device dispatch
        (ops.place.place_bulk_batch_jit).  Blocks; returns (assign i32[N],
        placed, nodes_evaluated, nodes_exhausted, scores f32[N], ticket).
        Callers derive usage from `assign` (sparse) — the engine returns
        no usage matrix.  The caller MUST `complete(ticket)` once the
        plan is submitted (ticket may be None if nothing placed)."""
        return self.place_bulk_begin(
            cm, feasible=feasible, affinity=affinity,
            has_affinity=has_affinity, desired=desired, penalty=penalty,
            coll0=coll0, demand=demand, count=count, deltas=deltas,
            spread_algorithm=spread_algorithm,
            wave_key=wave_key).result()

    def warmup(self, cm, inputs: Optional[PlaceInputs] = None,
               bulk: Optional[dict] = None) -> None:
        """Compile every E-bucket variant of the dispatch kernels for the
        given input shapes, so a serving or measurement window never pays
        a mid-run XLA compile (queue timing makes organically warmed
        bucket coverage nondeterministic).  `inputs`: a representative
        scan-path PlaceInputs; `bulk`: place_bulk-style field dict
        (feasible/affinity/has_affinity/desired/penalty/coll0/demand/
        count).  Results are discarded; nothing registers in the
        in-flight overlay.  Timing/cache stats are restored afterwards so
        one-time compile cost never skews serving diagnostics."""
        import jax

        import dataclasses

        stats_before = dict(self.stats)
        cache_before = (self._cache.hits, self._cache.misses)
        mesh = self._mesh_for(cm.n_rows)
        # every S bucket up to the sample's own (retry evals place the
        # remainder with fewer slots, hitting the smaller buckets)
        input_variants = []
        if inputs is not None:
            S_in = inputs.demand.shape[0]
            # every bucket below the sample's slot count, then the sample
            # itself (covering its own bucket even beyond _S_BUCKETS[-1])
            for cut in [b for b in _S_BUCKETS if b < S_in] + [S_in]:
                input_variants.append(dataclasses.replace(
                    inputs, demand=inputs.demand[:cut],
                    slot_tg=inputs.slot_tg[:cut],
                    slot_active=inputs.slot_active[:cut]))
        def scan_variant(E, inp_v):
            reqs = [_Request(cm=cm, inputs=inp_v, deltas=[],
                             spread_algorithm=False, future=Future())
                    for _ in range(E)]
            if mesh is not None:
                jax.block_until_ready(
                    self._dispatch_group_sharded(reqs, mesh))
            else:
                packed = self._dispatch_packed(
                    reqs, E=E,
                    basis=np.asarray(inp_v.used, np.float32),
                    deltas_per_req=[[] for _ in reqs],
                    capacity=np.asarray(inp_v.capacity))
                jax.block_until_ready(packed)

        def bulk_variant(E):
            # separate compiles serving mixes: sparse vs dense output
            # (count <=/> SPARSE_CAP) x delta-free (D=0) vs delta-
            # carrying (D=_DELTA_BUCKET) light blocks x the fill-grid
            # buckets (the dispatch derives fill_grid from the part's
            # max count, so the three warm counts induce the reachable
            # static combos: sparse x {16, 64} and dense x {64} —
            # retry evals place shrinking remainders, so a small-grid
            # sparse variant is reachable whatever the job's count)
            from nomad_tpu.ops.place import FILL_GRID_BUCKETS
            dummy_delta = [(0, np.zeros(NUM_RESOURCE_DIMS, np.float32))]
            for count in {min(bulk["count"], FILL_GRID_BUCKETS[0]),
                          SPARSE_CAP,
                          max(bulk["count"], SPARSE_CAP + 1)}:
                for deltas in ([], dummy_delta):
                    spec = dict(bulk, count=count)
                    breqs = [_BulkRequest(cm=cm, deltas=list(deltas),
                                          spread_algorithm=False,
                                          future=Future(), **spec)
                             for _ in range(E)]
                    # THROWAWAY world per thunk: the warmed variants
                    # include the donated-carry kernels, and donating /
                    # adopting against the real resident world would
                    # install a basis holding warmup placements the
                    # host snapshot never saw
                    if mesh is not None:
                        out = self._dispatch_bulk_group_sharded(
                            breqs, mesh, world=DeviceWorld(mesh))[0]
                        jax.block_until_ready(out)
                    else:
                        packed = self._dispatch_bulk_group(
                            breqs, world=DeviceWorld())[0]
                        jax.block_until_ready(packed)

        # XLA compiles release the GIL and run concurrently per variant,
        # cutting the grid from the sum of compile times toward the max.
        # Each thunk also EXECUTES its variant (block_until_ready), so
        # worker count bounds peak device memory: NOMAD_TPU_WARM_THREADS
        # tunes it down to 1 (sequential) for memory-tight configs.
        # (jit dispatch and the device cache are safe here: warmup thunks
        # never write overlays, and stats are restored below.)
        thunks = [(scan_variant, (E, v))
                  for E in self.E_BUCKETS for v in input_variants]
        if bulk is not None:
            # buckets above the byte-budget chunk can never be dispatched
            # for this world size — warming them would only stage the
            # oversized stacks the budget exists to avoid
            chunk = self._bulk_chunk(cm.n_rows)
            thunks += [(bulk_variant, (E,))
                       for E in self.BULK_E_BUCKETS if E <= chunk]
        workers = knobs.get_int("NOMAD_TPU_WARM_THREADS")
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(
                max_workers=max(1, min(workers, len(thunks)))) as ex:
            futs = [ex.submit(fn, *a) for fn, a in thunks]
            for f in futs:
                f.result()
        # world scatter pair: the measured window's first dirty-row
        # update must not pay its bucket's compile (shape-keyed on the
        # world size; the bulk path runs an unsharded world even when a
        # mesh exists)
        from nomad_tpu.parallel.world import warm_scatter
        cap = np.asarray(cm.capacity)
        warm_scatter(cap.shape, mesh)
        if mesh is not None:
            warm_scatter(cap.shape)
        if bulk is not None:
            # bulk warmup ran against throwaway worlds: pre-upload the
            # REAL world's epoch so the measured window's first dispatch
            # pays a dirty-row diff, not the epoch's full upload
            N = cm.n_rows
            self._world(cm, N, mesh).update(
                np.asarray(cm.capacity)[:N], self._basis_for(cm)[:N])
        self.stats.update(stats_before)
        self._cache.hits, self._cache.misses = cache_before

    def register_external(self, cm, contributions) -> int:
        """Record usage scheduled OUTSIDE the engine (the bulk wavefront
        path) in the in-flight overlay so engine dispatches see it before
        the plan commits.  `contributions`: [(row, f32[R])].  Returns a
        ticket for complete()."""
        with self._overlay_lock:
            race.write("PlacementEngine._overlays", self)
            key = id(cm)
            overlay = self._overlays.get(key)
            n = cm.used.shape[0]
            if overlay is None or overlay.shape[0] < n:
                grown = np.zeros((n, NUM_RESOURCE_DIMS), np.float32)
                if overlay is not None:
                    grown[:overlay.shape[0]] = overlay
                overlay = self._overlays[key] = grown
            contribs = []
            for row, vec in contributions:
                if row < overlay.shape[0]:
                    vec = np.asarray(vec, np.float32)
                    overlay[row] += vec
                    contribs.append((row, vec))
            ticket = self._next_ticket
            self._next_ticket += 1
            self._tickets[ticket] = (key, contribs)
            self.stats["tickets_open"] = len(self._tickets)
        return ticket

    def register_external_sparse(self, cm, rows: np.ndarray,
                                 counts: np.ndarray,
                                 demand: np.ndarray) -> int:
        """register_external for a resolved bulk eval without the
        per-row Python loop: overlay[rows[k]] += counts[k] * demand in
        one native scatter.  Ticket contribs stay in sparse form so
        complete() reverses them with the same rank-1 scatter."""
        rows = np.ascontiguousarray(rows, np.int32)
        counts = np.ascontiguousarray(counts, np.int32)
        with self._overlay_lock:
            race.write("PlacementEngine._overlays", self)
            key = id(cm)
            overlay = self._overlays.get(key)
            n = cm.used.shape[0]
            if overlay is None or overlay.shape[0] < n:
                grown = np.zeros((n, NUM_RESOURCE_DIMS), np.float32)
                if overlay is not None:
                    grown[:overlay.shape[0]] = overlay
                overlay = self._overlays[key] = grown
            keep = rows < overlay.shape[0]
            if not keep.all():
                rows, counts = rows[keep], counts[keep]
            d = np.zeros(overlay.shape[1], np.float32)
            d[:min(len(demand), len(d))] = \
                np.asarray(demand, np.float32)[:len(d)]
            _native.scatter_add_rank1(overlay, rows, counts, d)
            ticket = self._next_ticket
            self._next_ticket += 1
            self._tickets[ticket] = (key, ("rank1", rows, counts, d))
            self.stats["tickets_open"] = len(self._tickets)
        return ticket

    def basis_for(self, cm) -> np.ndarray:
        """Public view of committed usage + in-flight overlay."""
        return self._basis_for(cm)

    def register_devices(self, cm, contributions) -> int:
        """In-flight device instance counts: [(gid, row, count)].
        Steers concurrent evals away from nodes whose instances are
        claimed by not-yet-committed plans."""
        with self._overlay_lock:
            key = id(cm)
            per = self._dev_overlays.setdefault(key, {})
            n = cm.n_rows
            kept = []
            for gid, row, count in contributions:
                col = per.get(gid)
                if col is None or col.shape[0] < n:
                    grown = np.zeros(n, np.int32)
                    if col is not None:
                        grown[:col.shape[0]] = col
                    col = per[gid] = grown
                if row < col.shape[0]:
                    col[row] += count
                    kept.append((gid, row, count))
            ticket = self._next_ticket
            self._next_ticket += 1
            self._dev_tickets[ticket] = (key, kept)
        return ticket

    def device_overlay(self, cm, gid: str):
        """i32[N] in-flight instance counts for a device group, or None."""
        with self._overlay_lock:
            per = self._dev_overlays.get(id(cm))
            if not per:
                return None
            col = per.get(gid)
            return None if col is None else col.copy()

    def complete(self, ticket) -> None:
        """Release a placement's in-flight usage (its plan is now either
        committed into cm.used or abandoned)."""
        if ticket is not None:
            self.complete_many((ticket,))

    def complete_many(self, tickets) -> None:
        """complete() for a whole batch of tickets under ONE overlay-lock
        acquisition — the plan applier's commit->overlay hand-off
        releases every ticket of a coalesced plan batch at once, instead
        of bouncing the lock against concurrent dispatches per ticket."""
        chaos.maybe_delay("engine.complete_delay")
        drained = False
        with self._overlay_lock:
            race.write("PlacementEngine._overlays", self)
            for ticket in tickets:
                if ticket is None:
                    continue
                dev_entry = self._dev_tickets.pop(ticket, None)
                if dev_entry is not None:
                    key, contribs = dev_entry
                    per = self._dev_overlays.get(key, {})
                    for gid, row, count in contribs:
                        col = per.get(gid)
                        if col is not None and row < col.shape[0]:
                            col[row] -= count
                    if not self._dev_tickets:
                        self._dev_overlays.clear()
                        drained = drained or not self._tickets
                else:
                    entry = self._tickets.pop(ticket, None)
                    if entry is not None:
                        cm_key, contrib = entry
                        overlay = self._overlays.get(cm_key)
                        if overlay is not None:
                            if isinstance(contrib, tuple) \
                                    and contrib[0] == "rank1":
                                _, rows, counts, d = contrib
                                keep = rows < overlay.shape[0]
                                _native.scatter_add_rank1(
                                    overlay, rows[keep], -counts[keep],
                                    d[:overlay.shape[1]])
                            else:
                                for row, vec in contrib:
                                    if row < overlay.shape[0]:
                                        overlay[row] -= vec
                        self.stats["tickets_open"] = len(self._tickets)
                        if not self._tickets:
                            # nothing in flight: drop overlays entirely
                            # so numerical residue never accumulates
                            self._overlays.clear()
                            drained = drained or not self._dev_tickets
        if drained and self.on_drain is not None:
            try:
                self.on_drain()
            except Exception:                   # noqa: BLE001
                pass

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5.0)

    # ------------------------------------------------------------- overlay

    def _world(self, cm, N: int, mesh=None) -> DeviceWorld:
        """The device-resident world for (matrix, padded node axis, mesh).

        The world's capacity/basis pair is uploaded ONCE per cluster
        epoch (the key changes when the matrix re-buckets its node axis)
        and lives on device — sharded over the ('nodes',) serving mesh
        when one is active — with subsequent dispatches scatter-applying
        row deltas (world.update / world.apply_rank1) instead of
        re-shipping the [N, R] matrices."""
        key = (id(cm), N, mesh_key(mesh))
        with self._worlds_lock:
            w = self._worlds.get(key)
            if w is None:
                w = self._worlds[key] = DeviceWorld(mesh)
            self._worlds.move_to_end(key)
            while len(self._worlds) > 4:         # stale cm epochs (LRU)
                self._worlds.popitem(last=False)
            return w

    def world_stats(self) -> Dict[str, int]:
        """Aggregate DeviceWorld.stats over every resident world.  The
        bench steady-state gate reads full_uploads / steady_reuploads
        here: after warmup a healthy run scatters rows and never
        re-ships a full matrix."""
        agg: Dict[str, int] = {}
        with self._worlds_lock:
            worlds = list(self._worlds.values())
        for w in worlds:
            with w.lock:
                for k, v in w.stats.items():
                    agg[k] = agg.get(k, 0) + int(v)
        return agg

    def _basis_for(self, cm) -> np.ndarray:
        """cm.used + in-flight overlay (copy).  The committed matrix is
        copied under ITS owner's lock: a copy taken mid-commit would see
        a plan half in the matrix while the overlay still counts it fully
        — phantom usage that silently shrinks placements."""
        import contextlib
        cm_lock = getattr(cm, "lock", None) or contextlib.nullcontext()
        with self._overlay_lock:
            race.read("PlacementEngine._overlays", self)
            with cm_lock:
                used = np.array(cm.used, dtype=np.float32)
            overlay = self._overlays.get(id(cm))
            if overlay is not None:
                n = min(overlay.shape[0], used.shape[0])
                used[:n] += overlay[:n]
            return used

    def _register(self, req: _Request, result: PlaceResult) -> int:
        """Record an eval's in-flight usage contribution; returns ticket."""
        contrib: List[Tuple[int, np.ndarray]] = []
        S = req.inputs.demand.shape[0]
        for si in range(S):
            row = int(result.node[si])
            if row >= 0:
                contrib.append((row, req.inputs.demand[si]))
        for row, vec in req.deltas:
            if vec.max(initial=0.0) > 0.0 and (vec >= 0.0).all():
                contrib.append((row, vec))    # sticky pre-placement adds
        if not contrib:
            # nothing placed: no overlay entry, no ticket — otherwise a
            # permanently-unplaceable eval would drain the overlay on
            # every retry and busy-loop the blocked-eval wakeups
            return None
        with self._overlay_lock:
            race.write("PlacementEngine._overlays", self)
            key = id(req.cm)
            overlay = self._overlays.get(key)
            n = req.cm.used.shape[0]
            if overlay is None or overlay.shape[0] < n:
                grown = np.zeros((n, NUM_RESOURCE_DIMS), np.float32)
                if overlay is not None:
                    grown[:overlay.shape[0]] = overlay
                overlay = self._overlays[key] = grown
            for row, vec in contrib:
                if row < overlay.shape[0]:
                    overlay[row] += vec
            ticket = self._next_ticket
            self._next_ticket += 1
            self._tickets[ticket] = (key, contrib)
            self.stats["tickets_open"] = len(self._tickets)
        return ticket

    # ------------------------------------------------------------- loop

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop \
                        and self._pending is None:
                    self._cv.wait()
                if self._stop and not self._queue:
                    break
                batch, self._queue = (self._queue[:self.max_batch],
                                      self._queue[self.max_batch:])
            if not batch:
                # idle with a bulk dispatch in flight: nothing arrived
                # to chain behind it, so fetch + resolve it now
                self._drain_pending()
                continue
            try:
                self._dispatch(batch)
            except Exception as e:              # noqa: BLE001
                self._drain_pending()
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)
        # stop: settle any in-flight dispatch so its futures resolve
        self._drain_pending()

    # ------------------------------------------------------------- dispatch

    def _dispatch(self, batch: List[_Request]) -> None:
        groups: Dict[tuple, List] = {}
        for r in batch:
            groups.setdefault(r.shape_key(), []).append(r)
        self.stats["dispatches"] += 1
        self.stats["max_batch_seen"] = max(self.stats["max_batch_seen"],
                                           len(batch))

        # groups resolve SEQUENTIALLY: each group's results register in
        # the in-flight overlay before the next group's basis is read, so
        # two groups in one cycle (a service scan group + a batch bulk
        # group on the same matrix is the C2M steady state) never score
        # against a basis blind to each other's placements — that
        # blindness showed up as plan-applier conflicts and eval retries.
        # Cost: one D2H round trip per group instead of one per cycle.
        for reqs in groups.values():
            try:
                self._dispatch_one_group(reqs)
            except Exception as e:              # noqa: BLE001
                for r in reqs:
                    if not r.future.done():
                        r.future.set_exception(e)

    def _dispatch_one_group(self, reqs: List) -> None:
        if isinstance(reqs[0], _BulkRequest):
            cm = reqs[0].cm
            N = reqs[0].feasible.shape[0]
            mesh = self._mesh_for(N)
            world = self._world(cm, N, mesh)
            lanes = mesh.shape.get("wave", 1) if mesh is not None else 1
            expected_shape = ((N, cm.capacity.shape[1]),
                              (N, cm.used.shape[1]))
            parts = 0
            for part in self._split_bulk(reqs, sharded=mesh is not None,
                                         lanes=lanes):
                parts += 1
                # upload/compute overlap: the previous bulk dispatch may
                # still be computing.  Chaining behind it is sound ONLY
                # when this part scores against the same world via the
                # adopted donated carry (which already holds the
                # in-flight placements) and update() can proceed by
                # dirty-row scatter — a full upload from the host
                # snapshot would erase those placements, and chaos
                # injection may force exactly that, so both bail to a
                # drain-first barrier.
                chained = (self.overlap and self.donate
                           and chaos.active is None
                           and self._pending is not None
                           and self._pending.world is world
                           and self._pending.donated
                           and world.shape == expected_shape)
                if self._pending is not None and not chained:
                    self._drain_pending()
                tp0 = _time.time()
                if mesh is not None:
                    out, _w, dper, mapping, donated = \
                        self._dispatch_bulk_group_sharded(
                            part, mesh, world=world,
                            force_scatter=chained)
                else:
                    out, _w, dper, donated = self._dispatch_bulk_group(
                        part, world=world, force_scatter=chained)
                    mapping = None
                tp1 = _time.time()
                self.upload_windows.append((tp0, tp1))
                if chained:
                    self.stats["overlap_chained"] += 1
                prev, self._pending = self._pending, _PendingBulk(
                    reqs=part, out=out, world=world, deltas_per=dper,
                    mapping=mapping, donated=donated, t_dispatch=tp1)
                if prev is not None:
                    self._drain_record(prev)
                if not (self.overlap and donated):
                    self._drain_pending()
            self.stats["bulk_groups"] += 1
            self.stats["bulk_parts"] += parts
            self.stats["bulk_evals"] += len(reqs)
            return

        # scan-path groups resolve against the overlay basis: an
        # in-flight bulk dispatch's placements are not registered yet,
        # so a pending dispatch must land before this group's basis read
        self._drain_pending()
        rebucketed = (reqs[0].cm.capacity.shape[0]
                      != reqs[0].inputs.capacity.shape[0])
        mesh = None if rebucketed else \
            self._mesh_for(reqs[0].inputs.capacity.shape[0])
        # evals whose delta list exceeds the fixed slot bucket run alone
        # with the deltas folded into a private basis (no new compile
        # variant); on a mesh they stay SHARDED (an E=1 sharded dispatch
        # is a warmed bucket) rather than regressing to one device
        overflow = [r for r in reqs if len(r.deltas) > _DELTA_BUCKET]
        if overflow:
            reqs = [r for r in reqs if len(r.deltas) <= _DELTA_BUCKET]
            for r in overflow:
                if mesh is not None:
                    packed = self._dispatch_group_sharded(
                        [r], mesh, fold_deltas=True)
                    self._fetch_resolve_scan([r], packed)
                else:
                    self._run_single(r)
            self.stats["single_evals"] += len(overflow)
            if not reqs:
                return
        if mesh is None and (len(reqs) == 1 or rebucketed):
            # single path also when the matrix has grown (re-bucketed)
            # since these inputs were built: the dispatch-time basis no
            # longer matches the padded node axis
            for r in reqs:
                self._run_single(r)
            self.stats["single_evals"] += len(reqs)
            return
        # scan chains cap at their own bucket (queue slices can exceed it
        # now that bulk chains run longer); chunks chain through the
        # overlay between dispatches
        for i in range(0, len(reqs), self.scan_max_batch):
            chunk = reqs[i:i + self.scan_max_batch]
            if mesh is not None:
                packed = self._dispatch_group_sharded(chunk, mesh)
            else:
                packed = self._dispatch_group(chunk)
            self.stats["batched_evals"] += len(chunk)
            self._fetch_resolve_scan(chunk, packed)

    def _drain_pending(self) -> None:
        """Fetch + resolve the in-flight bulk dispatch, if any.  Called
        wherever the overlap pipeline must barrier: before any dispatch
        that cannot chain (different world, scan path, chaos active),
        when the queue idles with work in flight, and at stop."""
        p, self._pending = self._pending, None
        if p is not None:
            self._drain_record(p)

    def _drain_record(self, p: _PendingBulk) -> None:
        import jax

        t0 = _time.time()
        try:
            fetched = jax.device_get(p.out)
        except Exception as e:                  # noqa: BLE001
            if p.donated and p.world is not None:
                # the adopted carry is suspect (failed dispatch): the
                # next update() re-uploads from the host snapshot
                p.world.invalidate_basis()
            for r in p.reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        t1 = _time.time()
        dev_s = t1 - t0
        self.stats["device_s"] += dev_s
        self.device_windows.append((p.t_dispatch, t1))
        t0 = _time.time()
        try:
            self._resolve_bulk(p.reqs, fetched, p.world, p.deltas_per,
                               mapping=p.mapping, donated=p.donated)
        except Exception as e:                  # noqa: BLE001
            for r in p.reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        self.stats["resolve_s"] += _time.time() - t0
        self._emit_dispatch_spans(p.reqs, dev_s, "bulk")
        if len(p.reqs) > 1:
            self.stats["batched_evals"] += len(p.reqs)
        else:
            self.stats["single_evals"] += 1

    def _fetch_resolve_scan(self, reqs: List[_Request], packed) -> None:
        import jax

        t0 = _time.time()
        fetched = jax.device_get(packed)
        t1 = _time.time()
        dev_s = t1 - t0
        self.stats["device_s"] += dev_s
        self.device_windows.append((t0, t1))
        t0 = _time.time()
        node, score, fit_s, n_eval, n_exh, top_n, top_s = \
            unpack_outputs(np.asarray(fetched))
        for i, r in enumerate(reqs):
            res = PlaceResult(
                node=node[i], score=score[i], fit_score=fit_s[i],
                nodes_evaluated=n_eval[i], nodes_exhausted=n_exh[i],
                top_nodes=top_n[i], top_scores=top_s[i], used=None)
            ticket = self._register(r, res)
            r.future.set_result((res, ticket))
        self.stats["resolve_s"] += _time.time() - t0
        self._emit_dispatch_spans(reqs, dev_s, "scan")

    @staticmethod
    def _emit_dispatch_spans(reqs: List, dev_s: float, kind: str) -> None:
        """Per-request device-dispatch spans for sampled evals: the span
        covers submit -> resolve on the engine thread, with the shared
        device_get window carried as an attribute (the whole group rides
        one chained device dispatch)."""
        tracer = tracing.active
        if tracer is None:
            return
        now = _time.time()
        for r in reqs:
            if r.trace is not None:
                tracer.emit(r.trace[0], "engine.dispatch", r.trace[1],
                            now, kind=kind, batch=len(reqs),
                            device_get_s=round(dev_s, 6))

    # ------------------------------------------------------- sharded path

    def _mesh_for(self, N: int):
        """The ('node_shard','wave') serving mesh when sharding applies
        to this node axis, else None."""
        if not knobs.get_bool("NOMAD_TPU_SHARD"):
            return None
        if not self._mesh_checked:
            import jax

            from nomad_tpu.parallel.sharded import make_serving_mesh
            if len(jax.devices()) > 1:
                self._serving_mesh = make_serving_mesh()
            self._mesh_checked = True
        mesh = self._serving_mesh
        if mesh is None or N < self.shard_min_nodes:
            return None
        # the node axis splits over 'node_shard' only (wave columns hold
        # replicas); shards need >= 2 local rows (the wave's top-2
        # reduction)
        n_shard = mesh.shape.get("node_shard", mesh.devices.size)
        if N % n_shard != 0 or N < 2 * n_shard:
            return None
        return mesh

    # per-eval PlaceInputs fields shipped to the sharded scan kernel
    _SHARD_FIELDS = (
        "feasible", "affinity", "has_affinity", "desired_count",
        "penalty", "tg_count", "spread_vidx", "spread_desired",
        "spread_targeted", "spread_wfrac", "spread_counts",
        "spread_active", "place_cap", "demand", "slot_tg", "slot_active")

    def _stack_deltas(self, deltas_per_req, E: int, N: int):
        R = NUM_RESOURCE_DIMS
        D = _DELTA_BUCKET
        drows = np.full((E, D), N, np.int32)
        dvals = np.zeros((E, D, R), np.float32)
        for i, ds in enumerate(deltas_per_req):
            for d, (row, vec) in enumerate(ds[:D]):
                drows[i, d] = row
                dvals[i, d] = vec
        return drows, dvals

    def _dispatch_group_sharded(self, reqs: List[_Request], mesh,
                                fold_deltas: bool = False):
        """Scan-path dispatch over the node-sharded serving mesh.  Pads
        the eval axis to a compile bucket with inert evals (slot_active
        all False).  `fold_deltas` (overflow singletons only) folds the
        request's oversized delta list into the shipped basis copy."""
        from nomad_tpu.parallel.sharded import place_batch_sharded

        cm = reqs[0].cm
        N = reqs[0].inputs.capacity.shape[0]
        E = next(b for b in self.E_BUCKETS if b >= len(reqs))
        S = _s_bucket(reqs[0].inputs.demand.shape[0])
        t0 = _time.time()
        fields = {}
        for f in self._SHARD_FIELDS:
            arrs = [np.asarray(getattr(r.inputs, f)) for r in reqs]
            if f in ("demand", "slot_tg", "slot_active"):
                # slot axis padded to the canonical bucket (pads inactive)
                arrs = [np.concatenate(
                    [a, np.zeros((S - a.shape[0],) + a.shape[1:],
                                 a.dtype)]) if a.shape[0] < S else a
                        for a in arrs]
            if E > len(reqs):
                pad = (np.zeros_like(arrs[0])
                       if f == "slot_active" else arrs[0])
                arrs += [pad] * (E - len(reqs))
            fields[f] = np.stack(arrs)
        basis = self._basis_for(cm)
        deltas_per = [r.deltas for r in reqs]
        if fold_deltas:
            assert len(reqs) == 1
            deltas_per = [_fold_overflow(basis, reqs[0].deltas)]
        drows, dvals = self._stack_deltas(
            deltas_per + [[]] * (E - len(reqs)), E, N)
        self.stats["stack_s"] += _time.time() - t0
        t0 = _time.time()
        # content-addressed sharded placement: identical job-state
        # batches (the common case) ship zero bytes; basis/deltas always
        # ship (they change every dispatch and are small)
        from jax.sharding import NamedSharding
        from nomad_tpu.parallel.sharded import _field_specs_batched
        fshard = {k: NamedSharding(mesh, s)
                  for k, s in _field_specs_batched().items()}
        fields_dev = self._cache.sharded("scan", mesh, fields, fshard)
        t1 = _time.time()
        # device-resident world: capacity/basis live sharded across the
        # mesh; update() ships only the rows that changed since the last
        # dispatch (the overlay contributions of the previous cycle)
        cap_dev, basis_dev = self._world(cm, N, mesh).update(
            cm.capacity, basis)
        self.stats["put_basis_s"] = self.stats.get("put_basis_s", 0.0) \
            + (_time.time() - t1)
        packed, _used = place_batch_sharded(
            mesh, cap_dev, basis_dev, fields_dev,
            drows, dvals, spread_algorithm=reqs[0].spread_algorithm)
        self.stats["put_s"] += _time.time() - t0
        self.stats["sharded_evals"] = (
            self.stats.get("sharded_evals", 0) + len(reqs))
        return packed

    @staticmethod
    def _lane_bins(reqs: List[_BulkRequest], W: int):
        """Deterministic wave-lane binning: distinct wave_keys (eval
        namespaces) spread round-robin over the mesh's W wave columns in
        sorted-key order; requests sharing a key stay in ONE lane so
        their chained semantics are untouched.  Returns (bins — per-lane
        request lists, ALWAYS W of them so the stacks match the mesh's
        wave extent — and mapping[i] = (lane, slot) per input order).
        A single distinct key (or W == 1) degenerates to one active lane
        (padded lanes carry count=0 evals that exit immediately) —
        placement-identical to the pre-laned dispatch."""
        keys = sorted({r.wave_key for r in reqs})
        if W <= 1 or len(keys) <= 1:
            bins = [list(reqs)] + [[] for _ in range(max(0, W - 1))]
            return bins, [(0, i) for i in range(len(reqs))]
        lane_of = {k: i % W for i, k in enumerate(keys)}
        bins: List[List[_BulkRequest]] = [[] for _ in range(W)]
        mapping = []
        for r in reqs:
            lane = lane_of[r.wave_key]
            mapping.append((lane, len(bins[lane])))
            bins[lane].append(r)
        return bins, mapping

    def _dispatch_bulk_group_sharded(self, reqs: List[_BulkRequest],
                                     mesh, world=None, donate=None,
                                     force_scatter: bool = False):
        from nomad_tpu.parallel.sharded import (
            NODE_AXIS_NAME,
            WAVE_AXIS_NAME,
            place_bulk_batch_sharded,
        )

        cm = reqs[0].cm
        N = reqs[0].feasible.shape[0]
        donate = self.donate if donate is None else donate
        W = mesh.shape.get(WAVE_AXIS_NAME, 1)
        capacity = cm.capacity[:N]
        basis = self._basis_for(cm)[:N]
        deltas_per = [r.deltas for r in reqs]
        if len(reqs) == 1 and len(reqs[0].deltas) > _DELTA_BUCKET:
            deltas_per = [_fold_overflow(basis, reqs[0].deltas)]
            reqs = list(reqs)
            bins = [[reqs[0]]] + [[] for _ in range(max(0, W - 1))]
            mapping = [(0, 0)]
            deltas_bins = [deltas_per] + [[] for _ in range(max(0, W - 1))]
        else:
            bins, mapping = self._lane_bins(reqs, W)
            dp = {id(r): d for r, d in zip(reqs, deltas_per)}
            deltas_bins = [[dp[id(r)] for r in b] for b in bins]
        # lane eval extent: one compile bucket covering the fullest lane
        fullest = max(len(b) for b in bins)
        E = next(b for b in self.BULK_E_BUCKETS if b >= fullest)
        self.stats["wave_lanes"] = max(
            self.stats["wave_lanes"], sum(1 for b in bins if b))
        self.stats["lane_evals"] += len(reqs)
        self.stats["lane_slots"] += W * E

        t0 = _time.time()
        # content key from per-request digests (packbits + zero-marker
        # fast paths) — cheaper than hashing the stacked [W, E, N]
        # tensors, and a hit skips even BUILDING the host stacks.  The
        # per-lane tuples make the key sensitive to lane layout.
        r00 = reqs[0]
        digs = tuple(tuple(
            bulk_heavy_digest(r.feasible, r.affinity, r.penalty, r.coll0)
            for r in b) for b in bins)
        meta = tuple(tuple(
            (np.asarray(r.demand, np.float32).tobytes(),
             bool(r.has_affinity), int(r.desired)) for r in b)
            for b in bins)

        def build_stacks():
            def lane_stack(get, dt, pad_val=None):
                rows = []
                for b in bins:
                    fill = b[0] if b else r00
                    lane = [np.asarray(get(r), dt) for r in b]
                    pad_a = np.asarray(get(fill), dt) \
                        if pad_val is None else pad_val
                    lane += [pad_a] * (E - len(b))
                    rows.append(np.stack(lane) if lane[0].ndim
                                else np.array(lane, dt))
                return np.stack(rows)
            feas = lane_stack(lambda r: r.feasible, bool)
            aff = lane_stack(lambda r: r.affinity, np.float32)
            pen = lane_stack(lambda r: r.penalty, bool)
            coll = lane_stack(lambda r: r.coll0, np.int32)
            dem = lane_stack(lambda r: r.demand, np.float32)
            hasa = np.stack([np.array(
                [r.has_affinity for r in b] + [False] * (E - len(b)),
                bool) for b in bins])
            des = np.stack([np.array(
                [r.desired for r in b] + [1] * (E - len(b)), np.int32)
                for b in bins])
            return feas, aff, pen, coll, dem, hasa, des

        # padded evals have count=0: the wavefront exits immediately
        cnt = np.stack([np.array(
            [r.count for r in b] + [0] * (E - len(b)), np.int32)
            for b in bins])
        lane_drows, lane_dvals = [], []
        for db in deltas_bins:
            dr, dv = self._stack_deltas(
                list(db) + [[]] * (E - len(db)), E, N)
            lane_drows.append(dr)
            lane_dvals.append(dv)
        drows = np.stack(lane_drows)
        dvals = np.stack(lane_dvals)
        self.stats["stack_s"] += _time.time() - t0
        t0 = _time.time()
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as _P
        lane3 = NamedSharding(
            mesh, _P(WAVE_AXIS_NAME, None, NODE_AXIS_NAME))
        lane2 = NamedSharding(mesh, _P(WAVE_AXIS_NAME, None))
        lane2r = NamedSharding(mesh, _P(WAVE_AXIS_NAME, None, None))
        feas, aff, pen, coll, dem, hasa, des = self._cache.sharded(
            "bulk", mesh, build_stacks,
            (lane3, lane3, lane3, lane3, lane2r, lane2, lane2),
            key=("bulkstack", N, W, E, digs, meta))
        self.stats["put_heavy_s"] = self.stats.get("put_heavy_s", 0.0) \
            + (_time.time() - t0)
        t1 = _time.time()
        # device-resident world: one full upload per cluster epoch, then
        # dirty-row scatters; steady state ships zero basis bytes because
        # _resolve_bulk pre-applied the placements (apply_rank1, or the
        # donated carry + apply_rank1_host)
        world = world if world is not None else self._world(cm, N, mesh)
        cap_dev, basis_dev = world.update(capacity, basis,
                                          force_scatter=force_scatter)
        if donate:
            loaned = world.loan_basis()
            if loaned is not None:
                basis_dev = loaned
            else:
                donate = False
        self.stats["put_basis_s"] = self.stats.get("put_basis_s", 0.0) \
            + (_time.time() - t1)
        t1 = _time.time()
        from nomad_tpu.ops.place import fill_grid_for
        out = place_bulk_batch_sharded(
            mesh, cap_dev, basis_dev,
            feas, aff, hasa, des, pen, coll, dem, cnt,
            drows, dvals, spread_algorithm=reqs[0].spread_algorithm,
            fill_grid=fill_grid_for(max(r.count for r in reqs)),
            donate=donate)
        assign, scores, placed, n_eval, n_exh, waves, used_tot = out
        if donate:
            world.adopt_basis(used_tot)
            self.stats["donated_carries"] += 1
        self.stats["put_kernel_s"] = self.stats.get("put_kernel_s", 0.0) \
            + (_time.time() - t1)
        self.stats["put_s"] += _time.time() - t0
        self.stats["sharded_evals"] = (
            self.stats.get("sharded_evals", 0) + len(reqs))
        return (assign, scores, placed, n_eval, n_exh, waves), \
            world, deltas_per, mapping, donate

    # ---------------------------------------------------------- bulk path

    def _split_bulk(self, reqs: List[_BulkRequest], sharded: bool = False,
                    lanes: int = 1):
        # oversized-delta requests always go alone so their deltas can
        # fold into the part's private basis copy (fixed delta bucket,
        # no compile variant forked)
        overflow = [r for r in reqs if len(r.deltas) > _DELTA_BUCKET]
        rest = [r for r in reqs if len(r.deltas) <= _DELTA_BUCKET]
        for r in overflow:
            yield [r]
        chunk = self._bulk_chunk(reqs[0].feasible.shape[0], lanes)
        if self.fuse or sharded:
            # FUSED wave dispatch: the whole wave is ONE device call
            # (modulo the byte-budget chunk).  The dispatch picks the
            # output format (sparse iff every count fits) and delta
            # bucket (D=0 iff nothing ships deltas) for the mixed part —
            # all combinations are warmed compile variants.  The old
            # 3-way sparse/delta/dense split bought smaller D2H rows at
            # the price of ~1.5-2 dispatch round trips per wave; with
            # device-resident heavy blocks the extra round trips
            # dominate.  The sharded kernel has ONE (dense, fixed-D)
            # format, so it always dispatched fused.
            for i in range(0, len(rest), chunk):
                yield rest[i:i + chunk]
            return
        # NOMAD_TPU_FUSE=0: the pre-fusion format split — small-count
        # (sparse-output) and large-count (dense) requests split so a
        # part compiles one output format and small evals never pay the
        # dense [2N] D2H row; delta-free requests (the fresh-placement
        # common case) split from delta-carrying ones (their D=0 light
        # block is ~50x smaller, which mattered on slow links)
        fits_s0, fits_s, fits_d = [], [], []
        for r in rest:
            if r.count <= SPARSE_CAP:
                (fits_s0 if not r.deltas else fits_s).append(r)
            else:
                fits_d.append(r)
        for fits in (fits_s0, fits_s, fits_d):
            for i in range(0, len(fits), chunk):
                yield fits[i:i + chunk]

    def _bulk_chunk(self, N: int, lanes: int = 1) -> int:
        """Largest bulk E bucket whose stacked per-eval heavy blocks
        (f32[4N] each) fit the NOMAD_TPU_BULK_BYTES budget — 100K-node
        worlds cap their chains instead of staging ~1 GB stacks.  On a
        laned mesh the stacks carry [W, E, ...] so the budget divides by
        the wave extent."""
        cap = max(1, self.bulk_bytes_budget
                  // (4 * N * 4 * max(1, lanes)))
        allowed = [b for b in self.BULK_E_BUCKETS if b <= cap]
        return min(self.max_batch, allowed[-1] if allowed else 1)

    def _dispatch_bulk_group(self, reqs: List[_BulkRequest], world=None,
                             donate=None, force_scatter: bool = False):
        import jax

        cm = reqs[0].cm
        N = reqs[0].feasible.shape[0]
        donate = self.donate if donate is None else donate
        E = next(b for b in self.BULK_E_BUCKETS if b >= len(reqs))
        # rows are stable across matrix re-bucketing (growth only pads
        # the node axis), so the enqueue-time world is the prefix slice
        capacity = cm.capacity[:N]
        basis = self._basis_for(cm)[:N]
        deltas_per = [r.deltas for r in reqs]
        if len(reqs) == 1 and len(reqs[0].deltas) > _DELTA_BUCKET:
            # singleton overflow part (_split_bulk): fold into the
            # private basis copy instead of forking a compile variant
            deltas_per = [_fold_overflow(basis, reqs[0].deltas)]
        # D=0 when nothing ships deltas (the fresh-placement common
        # case; _split_bulk separates delta-free parts)
        D = _DELTA_BUCKET if any(deltas_per) else 0

        t0 = _time.time()
        lights = [pack_bulk_light(r.has_affinity, r.desired, r.count,
                                  r.demand, ds, N, D)
                  for r, ds in zip(reqs, deltas_per)]
        Ll = lights[0].shape[0]
        if E > len(reqs):
            # padded evals have count=0: the wavefront loop exits at once
            lights += [np.zeros(Ll, np.float32)] * (E - len(reqs))
        dyn = np.concatenate(lights)
        self.stats["stack_s"] += _time.time() - t0
        t0 = _time.time()
        # device-resident world: epoch upload once, dirty-row scatters
        # after; steady state ships zero basis bytes (apply_rank1 in
        # _resolve_bulk keeps device and host snapshot in lockstep; on
        # the donated path the kernel's exact carry IS the new resident
        # basis and only the host snapshot catches up)
        world = world if world is not None else self._world(cm, N)
        cap_dev, used_dev = world.update(capacity, basis,
                                         force_scatter=force_scatter)
        if donate:
            loaned = world.loan_basis()
            if loaned is not None:
                used_dev = loaned
            else:
                donate = False
        self.stats["put_basis_s"] = self.stats.get("put_basis_s", 0.0) \
            + (_time.time() - t0)
        t1 = _time.time()
        digs = tuple(bulk_heavy_digest(r.feasible, r.affinity, r.penalty,
                                       r.coll0) for r in reqs)
        heavy = [self._cache.bulk_heavy(r, dig)
                 for r, dig in zip(reqs, digs)]
        heavy += [heavy[0]] * (E - len(reqs))
        # the stacked [E, 4N] chain is itself content-addressed: C2M's
        # identical-content evals re-dispatch the same stack every wave,
        # and the jnp.stack launch was the dominant put_kernel_s cost
        import jax.numpy as jnp
        hstack = self._cache.stack(("hstack", N, E, digs),
                                   lambda: jnp.stack(heavy))
        self.stats["put_heavy_s"] = self.stats.get("put_heavy_s", 0.0) \
            + (_time.time() - t1)
        self.stats["cache_hits"] = self._cache.hits
        self.stats["cache_misses"] = self._cache.misses
        t1 = _time.time()
        dyn_dev = jax.device_put(dyn)  # analysis: allow(transfer-purity) — per-dispatch dynamic leaf, shipped explicitly
        sparse = all(r.count <= SPARSE_CAP for r in reqs)
        from nomad_tpu.ops.place import fill_grid_for
        fill_grid = fill_grid_for(max(r.count for r in reqs))
        if donate:
            # exact_out: the adopted basis is the rank-1 reconstruction
            # (bitwise what apply_rank1 would have scattered), while the
            # scan's own carry keeps chain-scoring parity
            packed, _used_final, used_exact = place_bulk_batch_donate_jit(
                cap_dev, used_dev, hstack, dyn_dev, D,
                sparse_out=sparse,
                spread_algorithm=reqs[0].spread_algorithm,
                fill_grid=fill_grid, exact_out=True)
            world.adopt_basis(used_exact)
            self.stats["donated_carries"] += 1
        else:
            packed, _used_final = place_bulk_batch_jit(
                cap_dev, used_dev, hstack, dyn_dev, D,
                sparse_out=sparse,
                spread_algorithm=reqs[0].spread_algorithm,
                fill_grid=fill_grid)
        self.stats["put_kernel_s"] = self.stats.get("put_kernel_s", 0.0) \
            + (_time.time() - t1)
        self.stats["put_s"] += _time.time() - t0
        return packed, world, deltas_per, donate

    def _resolve_bulk(self, reqs: List[_BulkRequest], packed: np.ndarray,
                      world, deltas_per, mapping=None,
                      donated: bool = False) -> None:
        """Mirror the kernel's chained usage host-side so every caller
        gets the exact used matrix its placements produced: each eval
        sees basis + prior evals' PLACEMENTS + its own private deltas;
        deltas never chain forward (uncommitted stops of one eval are
        invisible to others, exactly like the in-flight overlay).
        `deltas_per` is what the dispatch actually SHIPPED per eval —
        empty for an overflow singleton whose deltas were folded into
        the shipped basis (re-applying r.deltas would double-count).
        `world` is the DeviceWorld this dispatch scored against: each
        eval's placements scatter onto it (host snapshot + device in
        lockstep) so the NEXT dispatch's update() diff is already clean
        and ships zero basis rows in steady state.  `mapping` (laned
        sharded dispatches) gives each request's (lane, slot) in the
        [W, E, ...] outputs; `donated` routes the world hand-off through
        apply_rank1_host — the adopted carry already holds the
        placements on device, only the host snapshot catches up."""
        import jax

        N = reqs[0].feasible.shape[0]
        # one EXPLICIT device->host fetch per resolve: np.asarray on the
        # device outputs would sync implicitly, invisible to profiles and
        # to the steady-state transfer discipline
        if isinstance(packed, tuple):       # sharded path: raw field tuple
            assign, scores, placed, n_eval, n_exh, waves = \
                [np.asarray(x) for x in jax.device_get(packed)]
            assign = assign.astype(np.int32)
            if mapping is not None:
                idx = (np.array([ln for ln, _ in mapping]),
                       np.array([s for _, s in mapping]))
                assign, scores, placed, n_eval, n_exh, waves = (
                    assign[idx], scores[idx], placed[idx], n_eval[idx],
                    n_exh[idx], waves[idx])
        else:
            sparse = all(r.count <= SPARSE_CAP for r in reqs)
            assign, scores, placed, n_eval, n_exh, waves = \
                unpack_bulk_batch(np.asarray(jax.device_get(packed)), N,
                                  sparse=sparse)
        # wave-count visibility: a workload that degrades toward one
        # placement per wave shows up here instead of as mystery latency
        self.stats["waves"] += int(np.sum(waves))
        self.stats["max_waves_seen"] = max(self.stats["max_waves_seen"],
                                           int(np.max(waves, initial=0)))
        for i, r in enumerate(reqs):
            # sparse contributions only — no per-request [N, R] copies:
            # at 512-eval chains those copies dominated resolve, and the
            # scheduler reconstructs its cumulative usage from assigns.
            # One rank-1 scatter per eval instead of a per-row loop.
            rows = np.flatnonzero(assign[i])
            ticket = self.register_external_sparse(
                r.cm, rows, assign[i][rows], r.demand) \
                if rows.size else None
            if ticket is not None and world is not None:
                if donated:
                    world.apply_rank1_host(rows, assign[i][rows],
                                           r.demand)
                else:
                    world.apply_rank1(rows, assign[i][rows], r.demand)
            r.future.set_result(
                (assign[i], int(placed[i]), int(n_eval[i]),
                 int(n_exh[i]), scores[i], ticket))

    def _run_single(self, r: _Request) -> None:
        """Lone request: packed E=1 dispatch through the same device
        cache.  Still scores against the in-flight overlay basis so
        concurrent-but-unbatched evals don't collide."""
        import jax
        try:
            if r.cm.used.shape[0] == r.inputs.used.shape[0]:
                basis = self._basis_for(r.cm)
                deltas = r.deltas
                cap_src = r.cm.capacity
                if len(deltas) > _DELTA_BUCKET:
                    # basis is a fresh copy; no compile variant forked
                    deltas = _fold_overflow(basis, deltas)
            else:
                # matrix re-bucketed since inputs were built: inputs.used
                # already carries the deltas, score against it verbatim
                basis = np.asarray(r.inputs.used, np.float32)
                deltas = []
                cap_src = r.inputs.capacity
            packed = self._dispatch_packed(
                [r], E=1, basis=basis, deltas_per_req=[deltas],
                capacity=cap_src)
            node, score, fit_s, n_eval, n_exh, top_n, top_s = \
                unpack_outputs(np.asarray(jax.device_get(packed)))
            res = PlaceResult(
                node=node[0], score=score[0], fit_score=fit_s[0],
                nodes_evaluated=n_eval[0], nodes_exhausted=n_exh[0],
                top_nodes=top_n[0], top_scores=top_s[0], used=None)
            ticket = self._register(r, res)
            r.future.set_result((res, ticket))
        except Exception as e:                  # noqa: BLE001
            r.future.set_exception(e)

    def _dispatch_group(self, reqs: List[_Request]):
        """One shape-group -> one packed dispatch: heavy blocks resolve
        through the device cache (hits ship nothing), light blocks + the
        usage basis concatenate into ONE device_put leaf.  Returns the
        device-side output array (fetch happens batched in _dispatch)."""
        cm = reqs[0].cm
        basis = self._basis_for(cm)
        E = next(b for b in self.E_BUCKETS if b >= len(reqs))
        return self._dispatch_packed(
            reqs, E=E, basis=basis,
            deltas_per_req=[r.deltas for r in reqs], capacity=cm.capacity)

    def _dispatch_packed(self, reqs: List[_Request], E: int,
                         basis: np.ndarray, deltas_per_req,
                         capacity: np.ndarray):
        import jax

        i0 = reqs[0].inputs
        G, N, K, Vp1 = heavy_dims(i0)
        S = _s_bucket(i0.demand.shape[0])
        R = NUM_RESOURCE_DIMS
        D = _DELTA_BUCKET

        t0 = _time.time()
        lights = [pack_light(r.inputs, d, D, S)
                  for r, d in zip(reqs, deltas_per_req)]
        Ll = lights[0].shape[0]
        if E > len(reqs):
            lights += [np.zeros(Ll, np.float32)] * (E - len(reqs))
        basis = np.ascontiguousarray(basis, dtype=np.float32)
        dyn = np.concatenate(lights)
        self.stats["stack_s"] += _time.time() - t0
        # cache resolution inside the put window: misses device_put the
        # heavy bytes, and that transfer cost belongs in put_s
        t0 = _time.time()
        cap_dev, used_dev = self._world(
            reqs[0].cm, basis.shape[0]).update(capacity, basis)
        heavy = [self._cache.heavy(r.inputs) for r in reqs]
        heavy += [heavy[0]] * (E - len(reqs))   # pads place nothing
        self.stats["cache_hits"] = self._cache.hits
        self.stats["cache_misses"] = self._cache.misses
        dyn_dev = jax.device_put(dyn)  # analysis: allow(transfer-purity) — per-dispatch dynamic leaf (basis deltas + light blocks): payload that must ship, sent explicitly so the runtime guard stays armed
        packed, _used_final = place_batch_packed_jit(
            cap_dev, used_dev, tuple(heavy), dyn_dev, (G, N, K, Vp1, S, D),
            spread_algorithm=reqs[0].spread_algorithm)
        self.stats["put_s"] += _time.time() - t0
        return packed


_engine: Optional[PlacementEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> Optional[PlacementEngine]:
    """Process-wide engine; disable with NOMAD_TPU_ENGINE=0."""
    global _engine
    if not knobs.get_bool("NOMAD_TPU_ENGINE"):
        return None
    with _engine_lock:
        if _engine is None:
            _engine = PlacementEngine()
        return _engine
