"""Device-resident world state: the node x resource matrices live on
device across dispatches, and changes scatter in as row deltas.

The capacity / usage-basis matrices are the only per-dispatch inputs
whose CONTENT survives from wave to wave: a plan cycle touches a few
hundred rows of a 10K-100K row world.  Re-shipping the full [N, R]
matrices host->device every dispatch (and, on the sharded path,
re-sharding them across the mesh) was the dominant transfer cost at
C2M-1M rates (BENCH_r05: put_basis_s/put_heavy_s ~0.35 s,
put_kernel_s ~14.7 s per run).

`DeviceWorld` keeps one (capacity, basis) pair resident per cluster
epoch — an epoch is a (matrix identity, padded row count) pair, so the
matrix growing (ClusterMatrix._grow re-buckets the node axis) starts a
new epoch with one full upload, while routine node churn (join/drain
mutates PADDED rows in place) and plan commits flow in as bucketed
dirty-row scatters:

- `update(capacity, basis)` diffs both matrices against the host
  snapshot shipped last time and scatters only the changed rows
  (bucketed pad so the row count never forks an XLA compile variant;
  >25% churn or a shape change falls back to one full device_put).
- `apply_rank1(rows, counts, demand)` is the commit/overlay hand-off
  twin of the native `scatter_add_rank1` export: the same rank-1
  update lands in the host snapshot (native scatter) and in the device
  basis (jitted scatter) in one call, so a resolved bulk eval's
  placements are already device-resident before the next dispatch
  diffs — the steady-state diff is empty and ships zero rows.

On a multi-device mesh the buffers live sharded over the serving
mesh's 'node_shard' axis (`NamedSharding(mesh, P('node_shard', None))`,
replicated across 'wave' columns) and the scatters run through
`sharded.serving_update_fns` — a shard_map twin that translates global
rows to shard-local ones so each device only writes rows it owns (no
cross-device gather of the operand).

Updates are functional (`at[...].set` under jit): in-flight consumers
(a dispatched kernel, a concurrent warmup thread) keep the old buffer
alive until they finish, then it frees — replacing the buffer under
the lock while readers hold references is safe.  Explicit buffer
donation IS safe, but only through the loan/adopt lifecycle below:
`loan_basis()` transfers exclusive ownership of the resident basis to
a donating kernel (the world forgets it, so no later scatter can touch
a donated-away buffer), and `adopt_basis(used_final)` installs the
kernel's carry output as the new resident basis.  Because the donated
carry already contains the wave's placements, the resolve path pairs
it with `apply_rank1_host` — the host-snapshot-only rank-1 twin —
keeping host and device in lockstep with zero basis bytes shipped.
"""
from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

from nomad_tpu import chaos
from nomad_tpu import native as _native
from nomad_tpu.analysis import race, recompile

# transfer-purity (nomad_tpu.analysis): this module is on the dispatch
# hot path AND is the one place sanctioned to jax.device_put world bytes
_TRANSFER_HOT_PATH = True
_TRANSFER_UPLOAD_SITE = True
# recompile-budget: every jit site here must be registered by name
_RECOMPILE_TRACKED = True

# dirty-row buckets: each size is one small compile of the row scatter
ROW_BUCKETS = (64, 512, 4096)

# canonical mesh identity lives with the kernel caches it keys
from nomad_tpu.parallel.sharded import mesh_key  # noqa: E402,F401


_set_rows_fn = None
_add_rank1_fn = None


def _single_device_fns():
    """Jitted (set_rows, add_rank1) scatter pair for the unsharded world
    (rows == N pad slots drop)."""
    global _set_rows_fn, _add_rank1_fn
    if _set_rows_fn is None:
        import jax
        import jax.numpy as jnp
        _set_rows_fn = jax.jit(
            lambda d, r, v: d.at[r].set(v, mode="drop"))
        _add_rank1_fn = jax.jit(
            lambda d, r, c, dem: d.at[r].add(
                c[:, None].astype(jnp.float32) * dem, mode="drop"))
        recompile.register("world.set_rows", _set_rows_fn)
        recompile.register("world.add_rank1", _add_rank1_fn)
    return _set_rows_fn, _add_rank1_fn


def warm_scatter(shape: tuple, mesh=None) -> None:
    """Compile the row-scatter kernel for a world of `shape` (N, R) and
    every ROW_BUCKET before a measured window.  The first dirty-row
    update of an epoch otherwise pays its bucket's XLA compile inside
    the steady state (the recompile gate flags it).  Dispatches are
    pad-only no-ops — every row index is N, so `mode="drop"` discards
    them — against a throwaway zero world, never a resident one."""
    import jax

    N, R = shape
    w = DeviceWorld(mesh)
    dev = w._put_full(np.zeros((N, R), np.float32))
    if mesh is None:
        set_fn, _ = _single_device_fns()
    else:
        from nomad_tpu.parallel.sharded import serving_update_fns
        set_fn, _ = serving_update_fns(mesh)
    for b in ROW_BUCKETS:
        rows = np.full(b, N, np.int32)
        vals = np.zeros((b, R), np.float32)
        rows_dev, vals_dev = w._put_operands(rows, vals)
        jax.block_until_ready(set_fn(dev, rows_dev, vals_dev))


class DeviceWorld:
    """One epoch's device-resident (capacity, basis) pair.

    Thread-safe: every read-modify-write of the resident pair happens
    under `self.lock` (warmup dispatches run concurrently with the
    engine thread)."""

    # happens-before (nomad_tpu.analysis): the host snapshot is written
    # by the plan applier (apply_rank1) and the engine thread (update)
    # concurrently; both must hold `lock`.  The race detector traces it.
    _RACE_TRACED = {"_basis_last": "lock"}

    def __init__(self, mesh=None):
        self.mesh = mesh
        self.lock = threading.Lock()
        self.shape: Optional[tuple] = None       # (N, R) of current epoch
        self._cap_last: Optional[np.ndarray] = None
        self._cap_dev = None
        self._basis_last: Optional[np.ndarray] = None
        self._basis_dev = None
        self.stats = {"full_uploads": 0, "rows_scattered": 0,
                      "clean_hits": 0, "rank1_applies": 0,
                      # full uploads AFTER the epoch's first (churn
                      # fallback or injected device loss): the bench's
                      # steady-state gate asserts this stays 0
                      "steady_reuploads": 0,
                      # donated-carry lifecycle (loan_basis/adopt_basis)
                      "basis_loans": 0, "basis_adopts": 0}

    # ------------------------------------------------------------ helpers

    def _sharding(self):
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        axis = "node_shard" if "node_shard" in self.mesh.axis_names \
            else self.mesh.axis_names[0]
        return NamedSharding(self.mesh, P(axis, None))

    def _put_full(self, host: np.ndarray):
        import jax
        sh = self._sharding()
        # ALWAYS ship a private copy: on the CPU backend device_put
        # zero-copy aliases the numpy buffer, so uploading
        # _basis_last/_cap_last directly would let apply_rank1's native
        # host scatter mutate the "device" array in place behind jit
        arr = np.array(host, dtype=np.float32)
        return jax.device_put(arr) if sh is None \
            else jax.device_put(arr, sh)

    def _put_operands(self, *arrays):
        """Explicit upload of scatter operands (rows/counts/values).
        These are the per-update payload — they must ship — but shipping
        them IMPLICITLY (numpy straight into jit) is exactly what the
        steady-state transfer guard forbids; on a mesh the operands are
        replicated to match the serving kernels' P(None) in_specs."""
        import jax
        if self.mesh is None:
            return tuple(jax.device_put(a) for a in arrays)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return tuple(
            jax.device_put(a, NamedSharding(self.mesh,
                                            P(*([None] * a.ndim))))
            for a in arrays)

    def _set_rows(self, dev, rows: np.ndarray, vals: np.ndarray):
        rows_dev, vals_dev = self._put_operands(rows, vals)
        if self.mesh is None:
            fn, _ = _single_device_fns()
            return fn(dev, rows_dev, vals_dev)
        from nomad_tpu.parallel.sharded import serving_update_fns
        fn, _ = serving_update_fns(self.mesh)
        return fn(dev, rows_dev, vals_dev)

    def _update_one(self, host: np.ndarray, last: Optional[np.ndarray],
                    dev, force_scatter: bool = False
                    ) -> Tuple[np.ndarray, object, bool]:
        """Sync one matrix; returns (new snapshot, new device array,
        full-upload?).  Caller holds self.lock.  `force_scatter` is the
        chained-dispatch (donated-carry pipeline) discipline: the device
        array holds in-flight placements the host snapshot lacks, so a
        full upload would silently erase them — large churn scatters in
        bucket-sized chunks instead of falling back."""
        if chaos.active is not None and \
                chaos.active.should("world.scatter_fail"):
            # injected device loss: forget what shipped so this update
            # falls through to one full re-upload (deterministic
            # recovery, nothing raises mid-dispatch)
            last, dev = None, None
        N = host.shape[0]
        B = None
        changed = None
        if last is not None and last.shape == host.shape and \
                dev is not None:
            changed = np.nonzero(np.any(last != host, axis=1))[0]
            if changed.size == 0:
                self.stats["clean_hits"] += 1
                return last, dev, False
            if changed.size <= N // 4 or force_scatter:
                B = next((b for b in ROW_BUCKETS if b >= changed.size),
                         None)
            if B is None and force_scatter:
                # churn beyond the largest bucket: chunked bucket
                # scatters (every chunk a warmed compile variant)
                Bmax = ROW_BUCKETS[-1]
                changed_vals = np.array(host[changed], dtype=np.float32)
                snap = last.copy()
                snap[changed] = changed_vals
                for off in range(0, changed.size, Bmax):
                    cr = changed[off:off + Bmax]
                    cv = changed_vals[off:off + Bmax]
                    b = next(b for b in ROW_BUCKETS if b >= cr.size)
                    rows = np.full(b, N, np.int32)
                    rows[:cr.size] = cr
                    vals = np.zeros((b, host.shape[1]), np.float32)
                    vals[:cr.size] = cv
                    dev = self._set_rows(dev, rows, vals)
                self.stats["rows_scattered"] += int(changed.size)
                return snap, dev, False
        if B is None:
            snap = np.array(host, dtype=np.float32)
            return snap, self._put_full(snap), True
        # read the dirty rows ONCE: `host` may be live (node churn mutates
        # it concurrently) and the snapshot must equal what shipped, not
        # what the row holds a moment later
        changed_vals = np.array(host[changed], dtype=np.float32)
        rows = np.full(B, N, np.int32)           # pad slots drop
        rows[:changed.size] = changed
        vals = np.zeros((B, host.shape[1]), np.float32)
        vals[:changed.size] = changed_vals
        snap = last.copy()
        snap[changed] = changed_vals
        self.stats["rows_scattered"] += int(changed.size)
        return snap, self._set_rows(dev, rows, vals), False

    # ------------------------------------------------------------- public

    def update(self, capacity: np.ndarray, basis: np.ndarray,
               force_scatter: bool = False):
        """Bring the resident pair up to date with the host truth;
        returns (capacity_dev, basis_dev).  `capacity` may be the LIVE
        cm.capacity (it is snapshot-copied before any caching decision);
        `basis` must already be a private copy (engine._basis_for).
        `force_scatter` (chained donated-carry dispatches only) forbids
        the basis full-upload fallback: the resident basis carries
        in-flight placements a host-snapshot upload would erase."""
        with self.lock:
            shape = (capacity.shape, basis.shape)
            if shape != self.shape:              # new cluster epoch
                self.shape = shape
                self._cap_last = np.array(capacity, dtype=np.float32)
                self._cap_dev = self._put_full(self._cap_last)
                race.write("DeviceWorld._basis_last", self)
                self._basis_last = np.array(basis, dtype=np.float32)
                self._basis_dev = self._put_full(self._basis_last)
                self.stats["full_uploads"] += 1
                return self._cap_dev, self._basis_dev
            self._cap_last, self._cap_dev, full_c = self._update_one(
                capacity, self._cap_last, self._cap_dev)
            race.write("DeviceWorld._basis_last", self)
            self._basis_last, self._basis_dev, full_b = self._update_one(
                basis, self._basis_last, self._basis_dev,
                force_scatter=force_scatter)
            if full_c or full_b:
                self.stats["full_uploads"] += 1
                # a full ship after the epoch's first upload means the
                # steady state leaked world bytes (churn fallback or an
                # injected device loss) — the bench gate watches this
                self.stats["steady_reuploads"] += 1
            return self._cap_dev, self._basis_dev

    def loan_basis(self):
        """Transfer exclusive ownership of the resident basis buffer to
        a donating kernel.  The world forgets the buffer (no later
        scatter or update can alias a donated-away array); the caller
        MUST follow the dispatch with `adopt_basis(used_final)` — or, on
        a failed dispatch, leave the world invalidated so the next
        update() re-uploads from the host snapshot.  Returns None if no
        basis is resident."""
        with self.lock:
            dev, self._basis_dev = self._basis_dev, None
            if dev is not None:
                self.stats["basis_loans"] += 1
            return dev

    def adopt_basis(self, dev) -> None:
        """Install a kernel's donated-carry output as the resident
        basis.  The caller pairs this with `apply_rank1_host` at resolve
        time: the adopted carry already holds the wave's placements on
        device, so only the host snapshot needs the rank-1 update."""
        with self.lock:
            self._basis_dev = dev
            if dev is not None:
                self.stats["basis_adopts"] += 1

    def invalidate_basis(self) -> None:
        """Forget the resident basis (failed donated dispatch / poisoned
        carry): the next update() ships a full upload from the host
        snapshot instead of serving a suspect buffer."""
        with self.lock:
            self._basis_dev = None

    def _rank1_host_locked(self, rows: np.ndarray, counts: np.ndarray,
                           demand: np.ndarray) -> Optional[tuple]:
        """Rank-1 update of the HOST snapshot (native scatter); caller
        holds self.lock.  Returns the clipped (rows, counts, d) for the
        device twin, or None if there is nothing to scatter."""
        race.write("DeviceWorld._basis_last", self)
        if self._basis_last is None:
            return None                          # next update ships full
        n, r = self._basis_last.shape
        rows = np.ascontiguousarray(rows, np.int32)
        counts = np.ascontiguousarray(counts, np.int32)
        keep = rows < n
        if not keep.all():
            rows, counts = rows[keep], counts[keep]
        if rows.size == 0:
            return None
        d = np.zeros(r, np.float32)
        d[:min(len(demand), r)] = np.asarray(
            demand, np.float32)[:r]
        _native.scatter_add_rank1(self._basis_last, rows, counts, d)
        return rows, counts, d

    def apply_rank1(self, rows: np.ndarray, counts: np.ndarray,
                    demand: np.ndarray) -> None:
        """Scatter `counts[k] * demand` into basis row `rows[k]` on BOTH
        copies (host snapshot via the native export, device via the
        jitted twin), keeping them in lockstep so the next update()'s
        diff sees those rows clean."""
        with self.lock:
            clipped = self._rank1_host_locked(rows, counts, demand)
            if clipped is None:
                return
            rows, counts, d = clipped
            if chaos.active is not None and \
                    chaos.active.should("world.scatter_fail"):
                # injected device loss of the scatter: the host snapshot
                # above is authoritative; drop the resident basis so the
                # next update() re-uploads it rather than serving a
                # basis missing this commit
                self._basis_dev = None
                self.stats["chaos_invalidations"] = \
                    self.stats.get("chaos_invalidations", 0) + 1
                return
            if self._basis_dev is None:
                return                   # loaned out: next update ships
            if self.mesh is None:
                _, fn = _single_device_fns()
            else:
                from nomad_tpu.parallel.sharded import serving_update_fns
                _, fn = serving_update_fns(self.mesh)
            rows_dev, counts_dev, d_dev = self._put_operands(
                rows, counts, d)
            self._basis_dev = fn(self._basis_dev, rows_dev, counts_dev,
                                 d_dev)
            self.stats["rank1_applies"] += 1

    def apply_rank1_host(self, rows: np.ndarray, counts: np.ndarray,
                         demand: np.ndarray) -> None:
        """Host-snapshot-only rank-1 twin for the donated-carry path:
        the adopted device basis ALREADY contains these placements (the
        kernel's carry output), so scattering them on device would
        double-count — only the host snapshot catches up, restoring
        lockstep.  The chaos hook mirrors apply_rank1: an injected
        device loss drops the adopted carry and the next update()
        re-uploads from the (authoritative) host snapshot."""
        with self.lock:
            if self._rank1_host_locked(rows, counts, demand) is None:
                return
            if chaos.active is not None and \
                    chaos.active.should("world.scatter_fail"):
                self._basis_dev = None
                self.stats["chaos_invalidations"] = \
                    self.stats.get("chaos_invalidations", 0) + 1
                return
            self.stats["rank1_applies"] += 1

    def host_basis(self) -> Optional[np.ndarray]:
        """Copy of the host-side basis snapshot (tests / debugging)."""
        with self.lock:
            race.read("DeviceWorld._basis_last", self)
            return None if self._basis_last is None \
                else self._basis_last.copy()

    def device_arrays(self):
        """(capacity_dev, basis_dev) as currently resident (no sync)."""
        with self.lock:
            return self._cap_dev, self._basis_dev
