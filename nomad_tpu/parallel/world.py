"""Device-resident world state: the node x resource matrices live on
device across dispatches, and changes scatter in as row deltas.

The capacity / usage-basis matrices are the only per-dispatch inputs
whose CONTENT survives from wave to wave: a plan cycle touches a few
hundred rows of a 10K-100K row world.  Re-shipping the full [N, R]
matrices host->device every dispatch (and, on the sharded path,
re-sharding them across the mesh) was the dominant transfer cost at
C2M-1M rates (BENCH_r05: put_basis_s/put_heavy_s ~0.35 s,
put_kernel_s ~14.7 s per run).

`DeviceWorld` keeps one (capacity, basis) pair resident per cluster
epoch — an epoch is a (matrix identity, padded row count) pair, so the
matrix growing (ClusterMatrix._grow re-buckets the node axis) starts a
new epoch with one full upload, while routine node churn (join/drain
mutates PADDED rows in place) and plan commits flow in as bucketed
dirty-row scatters:

- `update(capacity, basis)` diffs both matrices against the host
  snapshot shipped last time and scatters only the changed rows
  (bucketed pad so the row count never forks an XLA compile variant;
  >25% churn or a shape change falls back to one full device_put).
- `apply_rank1(rows, counts, demand)` is the commit/overlay hand-off
  twin of the native `scatter_add_rank1` export: the same rank-1
  update lands in the host snapshot (native scatter) and in the device
  basis (jitted scatter) in one call, so a resolved bulk eval's
  placements are already device-resident before the next dispatch
  diffs — the steady-state diff is empty and ships zero rows.

On a multi-device mesh the buffers live sharded over the ('nodes',)
serving mesh (`NamedSharding(mesh, P('nodes', None))`) and the scatters
run through `sharded.serving_update_fns` — a shard_map twin that
translates global rows to shard-local ones so each device only writes
rows it owns (no cross-device gather of the operand).

Updates are functional (`at[...].set` under jit): in-flight consumers
(a dispatched kernel, a concurrent warmup thread) keep the old buffer
alive until they finish, then it frees — replacing the buffer under
the lock while readers hold references is safe, which explicit buffer
donation is not.  The transient second [N, R] buffer is ~2 MB at 100K
nodes, noise next to the per-eval stacks.
"""
from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

from nomad_tpu import chaos
from nomad_tpu import native as _native
from nomad_tpu.analysis import race, recompile

# transfer-purity (nomad_tpu.analysis): this module is on the dispatch
# hot path AND is the one place sanctioned to jax.device_put world bytes
_TRANSFER_HOT_PATH = True
_TRANSFER_UPLOAD_SITE = True
# recompile-budget: every jit site here must be registered by name
_RECOMPILE_TRACKED = True

# dirty-row buckets: each size is one small compile of the row scatter
ROW_BUCKETS = (64, 512, 4096)


def mesh_key(mesh) -> Optional[tuple]:
    """Stable identity of a device mesh: axis layout + device ids.

    `id(mesh)` is NOT a mesh identity — a re-created Mesh object can
    reuse the id of a dead one and resurrect its cache entries with
    stale shardings.  Two meshes with the same axes over the same
    devices are interchangeable for sharding purposes."""
    if mesh is None:
        return None
    return (tuple(mesh.shape.items()),
            tuple(d.id for d in mesh.devices.flat))


_set_rows_fn = None
_add_rank1_fn = None


def _single_device_fns():
    """Jitted (set_rows, add_rank1) scatter pair for the unsharded world
    (rows == N pad slots drop)."""
    global _set_rows_fn, _add_rank1_fn
    if _set_rows_fn is None:
        import jax
        import jax.numpy as jnp
        _set_rows_fn = jax.jit(
            lambda d, r, v: d.at[r].set(v, mode="drop"))
        _add_rank1_fn = jax.jit(
            lambda d, r, c, dem: d.at[r].add(
                c[:, None].astype(jnp.float32) * dem, mode="drop"))
        recompile.register("world.set_rows", _set_rows_fn)
        recompile.register("world.add_rank1", _add_rank1_fn)
    return _set_rows_fn, _add_rank1_fn


def warm_scatter(shape: tuple, mesh=None) -> None:
    """Compile the row-scatter kernel for a world of `shape` (N, R) and
    every ROW_BUCKET before a measured window.  The first dirty-row
    update of an epoch otherwise pays its bucket's XLA compile inside
    the steady state (the recompile gate flags it).  Dispatches are
    pad-only no-ops — every row index is N, so `mode="drop"` discards
    them — against a throwaway zero world, never a resident one."""
    import jax

    N, R = shape
    w = DeviceWorld(mesh)
    dev = w._put_full(np.zeros((N, R), np.float32))
    if mesh is None:
        set_fn, _ = _single_device_fns()
    else:
        from nomad_tpu.parallel.sharded import serving_update_fns
        set_fn, _ = serving_update_fns(mesh)
    for b in ROW_BUCKETS:
        rows = np.full(b, N, np.int32)
        vals = np.zeros((b, R), np.float32)
        rows_dev, vals_dev = w._put_operands(rows, vals)
        jax.block_until_ready(set_fn(dev, rows_dev, vals_dev))


class DeviceWorld:
    """One epoch's device-resident (capacity, basis) pair.

    Thread-safe: every read-modify-write of the resident pair happens
    under `self.lock` (warmup dispatches run concurrently with the
    engine thread)."""

    # happens-before (nomad_tpu.analysis): the host snapshot is written
    # by the plan applier (apply_rank1) and the engine thread (update)
    # concurrently; both must hold `lock`.  The race detector traces it.
    _RACE_TRACED = {"_basis_last": "lock"}

    def __init__(self, mesh=None):
        self.mesh = mesh
        self.lock = threading.Lock()
        self.shape: Optional[tuple] = None       # (N, R) of current epoch
        self._cap_last: Optional[np.ndarray] = None
        self._cap_dev = None
        self._basis_last: Optional[np.ndarray] = None
        self._basis_dev = None
        self.stats = {"full_uploads": 0, "rows_scattered": 0,
                      "clean_hits": 0, "rank1_applies": 0,
                      # full uploads AFTER the epoch's first (churn
                      # fallback or injected device loss): the bench's
                      # steady-state gate asserts this stays 0
                      "steady_reuploads": 0}

    # ------------------------------------------------------------ helpers

    def _sharding(self):
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P("nodes", None))

    def _put_full(self, host: np.ndarray):
        import jax
        sh = self._sharding()
        # ALWAYS ship a private copy: on the CPU backend device_put
        # zero-copy aliases the numpy buffer, so uploading
        # _basis_last/_cap_last directly would let apply_rank1's native
        # host scatter mutate the "device" array in place behind jit
        arr = np.array(host, dtype=np.float32)
        return jax.device_put(arr) if sh is None \
            else jax.device_put(arr, sh)

    def _put_operands(self, *arrays):
        """Explicit upload of scatter operands (rows/counts/values).
        These are the per-update payload — they must ship — but shipping
        them IMPLICITLY (numpy straight into jit) is exactly what the
        steady-state transfer guard forbids; on a mesh the operands are
        replicated to match the serving kernels' P(None) in_specs."""
        import jax
        if self.mesh is None:
            return tuple(jax.device_put(a) for a in arrays)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return tuple(
            jax.device_put(a, NamedSharding(self.mesh,
                                            P(*([None] * a.ndim))))
            for a in arrays)

    def _set_rows(self, dev, rows: np.ndarray, vals: np.ndarray):
        rows_dev, vals_dev = self._put_operands(rows, vals)
        if self.mesh is None:
            fn, _ = _single_device_fns()
            return fn(dev, rows_dev, vals_dev)
        from nomad_tpu.parallel.sharded import serving_update_fns
        fn, _ = serving_update_fns(self.mesh)
        return fn(dev, rows_dev, vals_dev)

    def _update_one(self, host: np.ndarray, last: Optional[np.ndarray],
                    dev) -> Tuple[np.ndarray, object, bool]:
        """Sync one matrix; returns (new snapshot, new device array,
        full-upload?).  Caller holds self.lock."""
        if chaos.active is not None and \
                chaos.active.should("world.scatter_fail"):
            # injected device loss: forget what shipped so this update
            # falls through to one full re-upload (deterministic
            # recovery, nothing raises mid-dispatch)
            last, dev = None, None
        N = host.shape[0]
        B = None
        changed = None
        if last is not None and last.shape == host.shape and \
                dev is not None:
            changed = np.nonzero(np.any(last != host, axis=1))[0]
            if changed.size == 0:
                self.stats["clean_hits"] += 1
                return last, dev, False
            if changed.size <= N // 4:
                B = next((b for b in ROW_BUCKETS if b >= changed.size),
                         None)
        if B is None:
            snap = np.array(host, dtype=np.float32)
            return snap, self._put_full(snap), True
        # read the dirty rows ONCE: `host` may be live (node churn mutates
        # it concurrently) and the snapshot must equal what shipped, not
        # what the row holds a moment later
        changed_vals = np.array(host[changed], dtype=np.float32)
        rows = np.full(B, N, np.int32)           # pad slots drop
        rows[:changed.size] = changed
        vals = np.zeros((B, host.shape[1]), np.float32)
        vals[:changed.size] = changed_vals
        snap = last.copy()
        snap[changed] = changed_vals
        self.stats["rows_scattered"] += int(changed.size)
        return snap, self._set_rows(dev, rows, vals), False

    # ------------------------------------------------------------- public

    def update(self, capacity: np.ndarray, basis: np.ndarray):
        """Bring the resident pair up to date with the host truth;
        returns (capacity_dev, basis_dev).  `capacity` may be the LIVE
        cm.capacity (it is snapshot-copied before any caching decision);
        `basis` must already be a private copy (engine._basis_for)."""
        with self.lock:
            shape = (capacity.shape, basis.shape)
            if shape != self.shape:              # new cluster epoch
                self.shape = shape
                self._cap_last = np.array(capacity, dtype=np.float32)
                self._cap_dev = self._put_full(self._cap_last)
                race.write("DeviceWorld._basis_last", self)
                self._basis_last = np.array(basis, dtype=np.float32)
                self._basis_dev = self._put_full(self._basis_last)
                self.stats["full_uploads"] += 1
                return self._cap_dev, self._basis_dev
            self._cap_last, self._cap_dev, full_c = self._update_one(
                capacity, self._cap_last, self._cap_dev)
            race.write("DeviceWorld._basis_last", self)
            self._basis_last, self._basis_dev, full_b = self._update_one(
                basis, self._basis_last, self._basis_dev)
            if full_c or full_b:
                self.stats["full_uploads"] += 1
                # a full ship after the epoch's first upload means the
                # steady state leaked world bytes (churn fallback or an
                # injected device loss) — the bench gate watches this
                self.stats["steady_reuploads"] += 1
            return self._cap_dev, self._basis_dev

    def apply_rank1(self, rows: np.ndarray, counts: np.ndarray,
                    demand: np.ndarray) -> None:
        """Scatter `counts[k] * demand` into basis row `rows[k]` on BOTH
        copies (host snapshot via the native export, device via the
        jitted twin), keeping them in lockstep so the next update()'s
        diff sees those rows clean."""
        with self.lock:
            race.write("DeviceWorld._basis_last", self)
            if self._basis_last is None:
                return                           # next update ships full
            n, r = self._basis_last.shape
            rows = np.ascontiguousarray(rows, np.int32)
            counts = np.ascontiguousarray(counts, np.int32)
            keep = rows < n
            if not keep.all():
                rows, counts = rows[keep], counts[keep]
            if rows.size == 0:
                return
            d = np.zeros(r, np.float32)
            d[:min(len(demand), r)] = np.asarray(
                demand, np.float32)[:r]
            _native.scatter_add_rank1(self._basis_last, rows, counts, d)
            if chaos.active is not None and \
                    chaos.active.should("world.scatter_fail"):
                # injected device loss of the scatter: the host snapshot
                # above is authoritative; drop the resident basis so the
                # next update() re-uploads it rather than serving a
                # basis missing this commit
                self._basis_dev = None
                self.stats["chaos_invalidations"] = \
                    self.stats.get("chaos_invalidations", 0) + 1
                return
            if self.mesh is None:
                _, fn = _single_device_fns()
            else:
                from nomad_tpu.parallel.sharded import serving_update_fns
                _, fn = serving_update_fns(self.mesh)
            rows_dev, counts_dev, d_dev = self._put_operands(
                rows, counts, d)
            self._basis_dev = fn(self._basis_dev, rows_dev, counts_dev,
                                 d_dev)
            self.stats["rank1_applies"] += 1

    def host_basis(self) -> Optional[np.ndarray]:
        """Copy of the host-side basis snapshot (tests / debugging)."""
        with self.lock:
            race.read("DeviceWorld._basis_last", self)
            return None if self._basis_last is None \
                else self._basis_last.copy()

    def device_arrays(self):
        """(capacity_dev, basis_dev) as currently resident (no sync)."""
        with self.lock:
            return self._cap_dev, self._basis_dev
