"""Multi-chip scale-out (reference analog: SURVEY.md sections 2.5/2.6 —
eval parallelism via scheduler workers and EvaluatePool fan-out).

On TPU the two parallel axes are:
- the **wave batch**: independent ready waves (distinct namespaces from
  the broker's wave dequeue) scored concurrently (Nomad's optimistic
  worker concurrency) -> sharded over the 'wave' mesh axis,
- the **node axis**: the 10K-100K node matrix of one eval -> sharded
  over the 'node_shard' mesh axis with pmax/pmin collectives for the
  global argmax (the ICI all-gather top-k of SURVEY.md section 5).

`wave_mesh_shape` factors a device count into the (node_shard, wave)
grid; NOMAD_TPU_WAVE_SHARDS pins the wave extent.
"""

from nomad_tpu.parallel.sharded import (
    make_mesh,
    place_eval_batch_sharded,
    stack_inputs,
    wave_mesh_shape,
)

__all__ = ["make_mesh", "place_eval_batch_sharded", "stack_inputs",
           "wave_mesh_shape"]
