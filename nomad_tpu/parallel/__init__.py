"""Multi-chip scale-out (reference analog: SURVEY.md sections 2.5/2.6 —
eval parallelism via scheduler workers and EvaluatePool fan-out).

On TPU the two parallel axes are:
- the **eval batch**: independent evaluations scheduled concurrently
  (Nomad's optimistic worker concurrency) -> sharded over the 'evals'
  mesh axis,
- the **node axis**: the 10K-100K node matrix of one eval -> sharded over
  the 'nodes' mesh axis with pmax/pmin collectives for the global argmax
  (the ICI all-gather top-k of SURVEY.md section 5).
"""

from nomad_tpu.parallel.sharded import (
    make_mesh,
    place_eval_batch_sharded,
    stack_inputs,
)

__all__ = ["make_mesh", "place_eval_batch_sharded", "stack_inputs"]
