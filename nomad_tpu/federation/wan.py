"""WAN gossip pool (reference: nomad/serf.go — the serf WAN cluster
every server joins, carrying ``region`` and leader-ness in its tags).

A second `Membership` instance over the same transport, on channel
"wan" so its handler names (``wan:server-1``) never collide with the
LAN pool's (``gossip:server-1``).  Only *servers* join; clients never
see the WAN pool.  Tags carry the member's region and whether it is
currently its region's raft leader; leadership changes propagate by
re-tagging (`set_leader`), which bumps the member's incarnation so the
new claim outranks every stale entry cluster-wide.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from nomad_tpu.core.membership import ALIVE, LEFT, SUSPECT, Membership


class WanPool(Membership):
    """Server-only federation gossip: `Membership` on channel "wan" with
    region/leader tags and region-indexed lookups."""

    def __init__(self, transport, name: str, addr: Tuple[str, int],
                 region: str, is_leader: bool = False, **kw):
        self.region = region
        kw.setdefault("channel", "wan")
        # the WAN pool is bigger than a LAN pool (every server of every
        # region) and SWIM-lite refreshes heard_at mostly on DIRECT
        # contact, so the expected gap between contacts with any given
        # peer grows with pool size — laxer timers keep a healthy pool
        # from flapping into SUSPECT
        kw.setdefault("suspect_after", 2.5)
        kw.setdefault("fail_after", 6.0)
        super().__init__(transport, name, addr,
                         tags={"region": region,
                               "leader": bool(is_leader)}, **kw)

    # ----------------------------------------------------------- tagging

    def set_leader(self, is_leader: bool) -> None:
        """Re-tag this server's leader-ness (no-op if unchanged)."""
        self.set_tags({"region": self.region, "leader": bool(is_leader)})

    # ----------------------------------------------------------- lookups

    def _entries(self) -> List[dict]:
        # member_list() already snapshots the table under the lock with
        # the race hooks; every read below goes through it
        return self.member_list()

    def regions(self) -> List[str]:
        """Sorted, deduped regions with at least one non-LEFT member,
        always including our own."""
        regs = {self.region}
        for m in self._entries():
            r = (m.get("tags") or {}).get("region")
            if r and m["status"] != LEFT:
                regs.add(r)
        return sorted(regs)

    def region_servers(self, region: str) -> List[str]:
        """Reachable-looking server names in `region`: ALIVE first, then
        SUSPECT (a big pool suspects healthy members now and then, and a
        forward attempt is the cheapest way to find out), each tier
        sorted for determinism."""
        alive, suspect = [], []
        for m in self._entries():
            if (m.get("tags") or {}).get("region") != region:
                continue
            if m["status"] == ALIVE:
                alive.append(m["name"])
            elif m["status"] == SUSPECT:
                suspect.append(m["name"])
        return sorted(alive) + sorted(suspect)

    def region_leader(self, region: str) -> Optional[str]:
        """The non-dead server currently tagged leader of `region`, or
        None (elections in flight / region dark)."""
        best = None
        for m in self._entries():
            tags = m.get("tags") or {}
            if m["status"] in (ALIVE, SUSPECT) \
                    and tags.get("region") == region and tags.get("leader"):
                if m["status"] == ALIVE:
                    return m["name"]
                best = best or m["name"]
        return best

    def server_region(self, name: str) -> Optional[str]:
        for m in self._entries():
            if m["name"] == name:
                return (m.get("tags") or {}).get("region")
        return None

    def members_by_region(self) -> Dict[str, List[dict]]:
        out: Dict[str, List[dict]] = {}
        for m in self._entries():
            r = (m.get("tags") or {}).get("region")
            if r:
                out.setdefault(r, []).append(m)
        return out
