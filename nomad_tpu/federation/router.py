"""Cross-region RPC forwarding (reference: nomad/rpc.go forwardRegion —
pick a server in the remote region, preferring its known leader, and
retry around leader churn).

The router replaces the hand-wired ``Server._region_peers`` dict as the
routing brain: candidates come from the WAN gossip pool (leader-tagged
member first), known-leader hints learned from ``not_leader`` redirects
(the ``X-Nomad-KnownLeader`` analog), and finally any statically
federated peer.  Retry is bounded: leader churn in the remote region is
ridden out with short waits up to a deadline, but a *dark* region —
every candidate `Unreachable` — fails fast so ``?consistent`` reads
into a partitioned region return promptly instead of timing out.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from nomad_tpu import chaos
from nomad_tpu import deadline as request_deadline
from nomad_tpu.raft.transport import Unreachable

# forwarded requests carry a hop counter; a routing loop (two regions
# each believing the other owns a region) trips this instead of
# recursing until the stack dies
MAX_FORWARD_HOPS = 4


class RegionRouter:
    """Routes an RPC to a remote region's current leader."""

    def __init__(self, server):
        self.server = server
        # region -> server name that last answered for it (the
        # known-leader hint; dropped on Unreachable)
        self._hints: Dict[str, str] = {}
        self._hint_lock = threading.Lock()

    # -------------------------------------------------------- candidates

    def _hint(self, region: str) -> Optional[str]:
        with self._hint_lock:
            return self._hints.get(region)

    def _remember(self, region: str, name: str) -> None:
        with self._hint_lock:
            self._hints[region] = name

    def _forget(self, region: str, name: Optional[str] = None) -> None:
        with self._hint_lock:
            if name is None or self._hints.get(region) == name:
                self._hints.pop(region, None)

    def _candidates(self, region: str) -> List[object]:
        """Ordered forwarding candidates: known-leader hint, the WAN
        pool's leader-tagged member, every other alive WAN member of the
        region, then statically federated peers (in-process `Server`
        handles or names).  Names are strings; in-process peers are
        `Server` objects."""
        s = self.server
        out: List[object] = []
        seen = set()

        def add(c):
            key = c if isinstance(c, str) else id(c)
            if key not in seen:
                seen.add(key)
                out.append(c)

        hint = self._hint(region)
        if hint is not None:
            add(hint)
        wan = getattr(s, "wan_pool", None)
        if wan is not None:
            leader = wan.region_leader(region)
            if leader is not None:
                add(leader)
            for name in wan.region_servers(region):
                add(name)
        static = s._region_peers.get(region)
        if static is not None:
            add(static)
        return out

    def known_regions(self) -> List[str]:
        return self.server.regions()

    # ------------------------------------------------------------- route

    def route(self, region: str, method: str, args: dict,
              timeout: float = 3.0):
        """Forward `method` to `region`'s current leader.  Bounded retry
        across remote leader churn; `Unreachable` fail-fast when every
        candidate is dark."""
        from nomad_tpu.rpc.endpoints import RpcError
        s = self.server
        if not region or region == s.region:
            return s.rpc_leader(method, args)
        # region-partition chaos: the WAN link to the remote region is
        # cut before any candidate is tried (linter-pinned site)
        if chaos.active is not None and chaos.should("region.partition"):
            raise Unreachable(
                f"{s.name}->{region}: chaos region.partition")
        # the caller's end-to-end budget bounds the churn retry: no
        # point riding out a remote election longer than the request
        # has left to live
        budget = request_deadline.remaining()
        if budget is not None:
            timeout = min(timeout, budget)
        deadline = time.monotonic() + timeout
        hinted: Optional[str] = None        # not_leader redirect target
        last_unreachable: Optional[Unreachable] = None
        while True:
            if request_deadline.check("federation"):
                raise RpcError(
                    "deadline_exceeded",
                    f"{method}->{region}: budget exhausted in transit")
            if request_deadline.DEADLINE_KEY in args and \
                    request_deadline.current() is not None:
                # re-encode the remaining budget each retry round so
                # time burnt riding out remote churn is decremented
                # before the next hop sees the stamp
                args = dict(args)
                args[request_deadline.DEADLINE_KEY] = \
                    request_deadline.to_wire()
            candidates = self._candidates(region)
            if hinted is not None:
                # try the redirect target first, then everyone else
                candidates = [hinted] + [c for c in candidates
                                         if c != hinted]
                hinted = None
            if not candidates:
                known = ", ".join(self.known_regions())
                raise RpcError("no_region_path",
                               f"{region} (known regions: {known})")
            all_dark = True
            for target in candidates:
                try:
                    result = self._call(target, method, args)
                except Unreachable as e:
                    if isinstance(target, str):
                        self._forget(region, target)
                    last_unreachable = e
                    continue
                except RpcError as e:
                    if e.kind == "not_leader":
                        # known-leader redirect: retry against the hint
                        all_dark = False
                        if e.leader and isinstance(target, str) \
                                and e.leader != target:
                            hinted = e.leader
                            break
                        continue
                    if e.kind == "no_leader":
                        # remote election in flight: try the next
                        # candidate, then wait the churn out
                        all_dark = False
                        continue
                    raise         # an application error from the remote
                if isinstance(target, str):
                    self._remember(region, target)
                return result
            if all_dark:
                # every known path into the region is down: fail fast
                # (the serving gate re-raises this for ?consistent)
                raise last_unreachable or Unreachable(
                    f"{s.name}->{region}: region dark")
            if time.monotonic() >= deadline:
                raise RpcError(
                    "no_region_leader",
                    f"{region}: no leader within {timeout:g}s")
            if hinted is None:
                time.sleep(0.05)

    def _call(self, target, method: str, args: dict):
        s = self.server
        if not isinstance(target, str):
            # in-process federated Server handle (dev mode)
            return target.rpc_leader(method, args)
        if s._transport is None:
            raise Unreachable(f"{s.name}->{target}: no transport")
        return s._transport.call(s.name, f"rpc:{target}", method, args)
