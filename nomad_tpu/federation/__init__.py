"""Multi-region federation plane (reference: nomad/serf.go WAN pool +
nomad/rpc.go region forwarding).

Each region runs its own raft commit spine; regions discover each other
over a second SWIM gossip instance joining only servers (`WanPool`,
channel "wan" so it coexists with the LAN pool on one transport), with
members tagged region + leader-ness.  `RegionRouter` forwards RPCs to a
remote region's current leader using those tags plus known-leader hints,
with bounded retry across remote leader churn and `Unreachable`
fail-fast when the region is dark.
"""
from nomad_tpu.federation.router import MAX_FORWARD_HOPS, RegionRouter
from nomad_tpu.federation.wan import WanPool

__all__ = ["MAX_FORWARD_HOPS", "RegionRouter", "WanPool"]
