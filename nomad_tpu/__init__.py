"""nomad_tpu — a TPU-native cluster-scheduling framework.

A from-scratch rebuild of the capabilities of HashiCorp Nomad (reference:
hollowsunsets/nomad, surveyed in SURVEY.md) designed TPU-first:

- The control plane (state store, evaluation broker, plan queue, serialized
  optimistic-concurrency plan applier, blocked evals, deployment watcher,
  node drainer, heartbeats) lives on the host in `nomad_tpu.core` /
  `nomad_tpu.state`.
- The scheduler hot path (feasibility -> bin-pack/spread scoring -> ranking ->
  selection -> preemption; Nomad's RankIterator stack and structs.AllocsFit,
  reference scheduler/rank.go:193-551, structs/funcs.go:166-297) is a dense
  batched engine in `nomad_tpu.ops`: cluster state is encoded as fixed-shape
  node x resource matrices (`nomad_tpu.encode`), and a single jitted
  `lax.scan` places every task-group instance of an evaluation while vmapping
  feasibility + scoring across all candidate nodes at once.
- Multi-chip scale-out shards the node axis and the evaluation batch over a
  `jax.sharding.Mesh` (`nomad_tpu.parallel`).
"""

__version__ = "0.1.0"

SCHEDULER_VERSION = 1  # parity: reference scheduler/scheduler.go:19
