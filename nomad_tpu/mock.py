"""Canonical test fixtures (reference: nomad/mock/mock.go — mock.Node:15,
mock.Job:233, mock.Alloc:1540, mock.Eval:1479 and variants).
"""
from __future__ import annotations

import itertools
import uuid

from nomad_tpu.utils import generate_uuid

from nomad_tpu.structs import (
    Allocation,
    AllocClientStatus,
    AllocDesiredStatus,
    Evaluation,
    Job,
    JobStatus,
    JobType,
    Node,
    NodeStatus,
    ReschedulePolicy,
    Task,
    TaskGroup,
    UpdateStrategy,
)
from nomad_tpu.structs.alloc import AllocatedResources, AllocatedTaskResources, alloc_name
from nomad_tpu.structs.job import Constraint, Operand
from nomad_tpu.structs.resources import NetworkResource
from nomad_tpu.structs.node import NodeCpuResources, NodeResources, compute_node_class
from nomad_tpu.structs.resources import Resources

_seq = itertools.count(1)


def _uuid() -> str:
    return generate_uuid()


def node(**overrides) -> Node:
    i = next(_seq)
    n = Node(
        id=_uuid(),
        name=f"node-{i}",
        datacenter="dc1",
        node_class="",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "nomad.version": "0.5.0",
            "driver.exec": "1",
            "driver.mock_driver": "1",
            "unique.hostname": f"node-{i}",
        },
        node_resources=NodeResources(
            cpu=NodeCpuResources(cpu_shares=4000, total_core_count=4,
                                 reservable_cores=[0, 1, 2, 3]),
            memory_mb=8192,
            disk_mb=100 * 1024,
            # reference mock.Node: one eth0 device with 1000 MBits
            networks=[NetworkResource(device="eth0", cidr="192.168.0.100/32",
                                      mbits=1000)],
        ),
        drivers={"exec": {"detected": True, "healthy": True},
                 "mock_driver": {"detected": True, "healthy": True}},
        status=NodeStatus.READY,
    )
    for k, v in overrides.items():
        setattr(n, k, v)
    n.computed_class = compute_node_class(n)
    return n


def job(**overrides) -> Job:
    j = Job(
        id=f"mock-service-{_uuid()}",
        name="my-job",
        type=JobType.SERVICE,
        priority=50,
        all_at_once=False,
        datacenters=["dc1"],
        constraints=[Constraint("${attr.kernel.name}", "linux", Operand.EQ)],
        task_groups=[TaskGroup(
            name="web",
            count=10,
            tasks=[Task(
                name="web",
                driver="exec",
                config={"command": "/bin/date"},
                resources=Resources(cpu=500, memory_mb=256),
            )],
            reschedule_policy=ReschedulePolicy.default_service(),
        )],
        update=UpdateStrategy(max_parallel=1, health_check="checks"),
        status=JobStatus.PENDING,
        version=0,
        create_index=42,
        modify_index=99,
        job_modify_index=99,
    )
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def batch_job(**overrides) -> Job:
    j = job(**overrides)
    j.type = JobType.BATCH
    if "id" not in overrides:
        j.id = f"mock-batch-{_uuid()}"
    for tg in j.task_groups:
        if tg.reschedule_policy is not None:
            tg.reschedule_policy = ReschedulePolicy.default_batch()
    return j


def csi_node(plugin_id: str = "ebs-plugin", healthy: bool = True,
             max_volumes: int = 3, controller: bool = False, **overrides):
    """Node fingerprinting a CSI node plugin (reference mock.Node +
    CSINodePlugins fixtures in csi_endpoint_test.go)."""
    n = node(**overrides)
    n.csi_node_plugins = {plugin_id: {
        "healthy": healthy, "max_volumes": max_volumes,
        "provider": "com.test.csi"}}
    if controller:
        n.csi_controller_plugins = {plugin_id: {"healthy": healthy}}
    return n


def csi_volume(vol_id: str = "", plugin_id: str = "ebs-plugin",
               access_mode: str = "", **overrides):
    from nomad_tpu.structs.csi import CSIVolume
    v = CSIVolume(id=vol_id or f"vol-{_uuid()[:8]}", namespace="default",
                  name="test-volume", plugin_id=plugin_id,
                  access_mode=access_mode)
    for k, val in overrides.items():
        setattr(v, k, val)
    return v


def system_job(**overrides) -> Job:
    j = job(**overrides)
    j.type = JobType.SYSTEM
    j.priority = 100
    if "id" not in overrides:
        j.id = f"mock-system-{_uuid()}"
    j.task_groups[0].count = 1
    return j


def sysbatch_job(**overrides) -> Job:
    j = system_job(**overrides)
    j.type = JobType.SYSBATCH
    j.priority = 50
    if "id" not in overrides:
        j.id = f"mock-sysbatch-{_uuid()}"
    return j


def eval(**overrides) -> Evaluation:
    e = Evaluation(
        id=_uuid(),
        namespace="default",
        priority=50,
        type=JobType.SERVICE,
        job_id=_uuid(),
        status="pending",
    )
    for k, v in overrides.items():
        setattr(e, k, v)
    return e


def alloc_for(j: Job, node_id: str, index: int = 0, **overrides) -> Allocation:
    tg = j.task_groups[0]
    tasks = {}
    for t in tg.tasks:
        tasks[t.name] = AllocatedTaskResources(
            cpu_shares=t.resources.cpu,
            memory_mb=t.resources.memory_mb,
        )
    a = Allocation(
        id=_uuid(),
        eval_id=_uuid(),
        node_id=node_id,
        name=alloc_name(j.id, tg.name, index),
        job_id=j.id,
        job=j,
        task_group=tg.name,
        allocated_resources=AllocatedResources(
            tasks=tasks, shared_disk_mb=tg.ephemeral_disk.size_mb),
        desired_status=AllocDesiredStatus.RUN,
        client_status=AllocClientStatus.PENDING,
    )
    for k, v in overrides.items():
        setattr(a, k, v)
    return a


def alloc(**overrides) -> Allocation:
    j = job()
    a = alloc_for(j, node_id=_uuid())
    for k, v in overrides.items():
        setattr(a, k, v)
    return a
