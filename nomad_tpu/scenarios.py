"""Scenario matrix: workload shapes x phased chaos schedules, run as
convergence soaks on a real 3-server cluster.

Each matrix cell boots a data_dir-backed in-process `Cluster`, registers
mock client nodes that heartbeat on short TTLs, drives one workload
shape (batch spine, spread services, device-constrained, preemption,
serving plane, rolling deploy, autoscaling ramp, multi-tenant
fair-share, multi-region federation), and runs a *phased* chaos
schedule against it: the `NOMAD_TPU_CHAOS` grammar's
`phase=<name>:<a>-<b>` windows interleave calm -> storm -> calm, with
server hard_kill/restart and partition bursts riding the storm phases.
The `server_replace` schedule runs the elastic-membership drill instead:
the leader is permanently destroyed mid-storm and a blank replacement
joins, catches up, and is promoted to voter by autopilot — the cell's
invariants (including FSM byte-identity) then run against the NEW
voter set.
After chaos lifts the cell must CONVERGE, and the runner asserts the
production invariants the reconcilers promise:

    evals_drained        every eval terminal (BLOCKED allowed only for
                         capacity-starved shapes), no broker leases, no
                         queued plans
    allocs_consistent    every group at its final desired count, no
                         duplicate names among live allocs, every live
                         alloc on a live ready node
    fsm_identical        canonical FSM snapshots byte-equal across all
                         members (survivors AND restarted crashers)
    deployments_settled  no active deployments; a FAILED auto-revert
                         deployment implies the job version moved past it
    drained_nodes_empty  drained nodes hold no live allocs and their
                         strategy is cleared

Cells emit `BENCH_matrix_<shape>_<schedule>.json` trajectory files
(allocs/s, plan.submit p50/p99, convergence time, invariant verdicts);
`bench.py --matrix` runs the full matrix and `--matrix --smoke` the
curated CI subset.

The three chaos points this plane owns:

    node.churn_kill     injected in HeartbeatTracker.heartbeat (a client
                        heartbeat is swallowed, the node expires through
                        the real TTL-miss path)
    deploy.health_flap  injected in HealthReporter.tick below (a healthy
                        alloc reports unhealthy, driving the deployment
                        watcher into failure/auto-revert)
    scale.burst         injected in AutoscaleDriver.tick below (a scale
                        wave is amplified to the policy max bound)
"""
from __future__ import annotations

import contextlib
import json
import os
import random
import shutil
import tempfile
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from nomad_tpu import chaos, knobs, mock
from nomad_tpu import deadline as request_deadline
from nomad_tpu.chaos import ChaosRegistry
from nomad_tpu.rpc import RpcError
from nomad_tpu.state import digest as state_digest
from nomad_tpu.core.cluster import Cluster
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.core.worker import TRANSIENT_ERRORS
from nomad_tpu.raft import RaftConfig
from nomad_tpu.structs import (
    AllocClientStatus,
    DeploymentStatus,
    EvalStatus,
)
from nomad_tpu.structs.job import (
    ReschedulePolicy,
    ScalingPolicy,
    UpdateStrategy,
)
from nomad_tpu.structs.resources import DeviceRequest, NodeDevice
from nomad_tpu.telemetry import global_metrics


# ------------------------------------------------------------- utilities


def _wait(cond, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _on_leader(cluster, fn, timeout=15.0):
    """Run fn(leader), retrying across leadership churn / chaos drops."""
    deadline = time.time() + timeout
    while True:
        try:
            return fn(cluster.leader(timeout=5.0))
        except TRANSIENT_ERRORS + (TimeoutError,):
            if time.time() >= deadline:
                raise
            time.sleep(0.05)


# Canonicalize an FSM snapshot for equality (pickle memoizes shared
# references, so byte-different blobs can encode identical state).  The
# SAME canonical form backs the runtime integrity plane's per-table
# digests, so the battery's byte-identity verdict and the online
# divergence votes can never disagree about what "identical" means.
_canon = state_digest.canon


def _tune(server: Server) -> None:
    """Fast redelivery so injected nacks/lease expiries resolve inside a
    cell; applied to every incarnation (restart() builds fresh Servers
    that would otherwise revert to the 60s production defaults)."""
    server.broker.nack_timeout = 1.0
    server.broker.initial_nack_delay = 0.05
    server.broker.subsequent_nack_delay = 0.1


def _live(allocs):
    return [a for a in allocs if not a.terminal_status()]


# ------------------------------------------------------------- schedules


@dataclass(frozen=True)
class Schedule:
    """One phased chaos schedule: a NOMAD_TPU_CHAOS-grammar spec with a
    `{seed}` placeholder, the total chaos window, whether seeded server
    churn (hard_kill/restart + partition flaps) rides the open phases,
    and whether the server-loss drill (permanently destroy the leader,
    join a blank replacement) fires mid-storm."""
    name: str
    spec: str
    duration_s: float
    server_churn: bool
    server_replace: bool = False


SCHEDULES: Dict[str, Schedule] = {
    # calm -> node-churn storm -> calm: heartbeats swallowed, leases
    # shed, raft traffic dropped/partitioned, servers hard-killed and
    # restarted from their WALs mid-flight
    "storm": Schedule(
        name="storm",
        spec=("seed={seed};delay_ms=1;phase=storm:0.6-3.0;"
              "rpc.drop=0.03@storm;rpc.delay=0.08@storm;"
              "raft.partition=0.02@storm;broker.lease_expire=0.25@storm;"
              "node.churn_kill=0.5@storm;deploy.health_flap=0.12@storm;"
              "scale.burst=0.25@storm"),
        duration_s=3.8,
        server_churn=True,
    ),
    # two lease-shedding windows with a calm gap: every broker dequeue
    # hands out near-expired leases, read leases void, deployment health
    # reports flap — no servers die, the control loops must absorb pure
    # redelivery pressure
    "lease_flap": Schedule(
        name="lease_flap",
        spec=("seed={seed};delay_ms=1;"
              "phase=flap1:0.3-1.6;phase=flap2:2.3-3.6;"
              "broker.lease_expire=0.5@flap1;broker.lease_expire=0.5@flap2;"
              "read.lease_expire=0.4@flap1;read.lease_expire=0.4@flap2;"
              "deploy.health_flap=0.2@flap1;deploy.health_flap=0.2@flap2;"
              "scale.burst=0.35@flap1;scale.burst=0.35@flap2;"
              "rpc.delay=0.1@flap1;rpc.delay=0.1@flap2"),
        duration_s=4.2,
        server_churn=False,
    ),
    # the elastic-membership drill: mid-storm the CURRENT LEADER is
    # permanently destroyed (power loss, disk gone — it never comes
    # back) and a blank server joins under a new name, catches up via
    # snapshot, and is promoted to voter by autopilot.  The membership
    # chaos points ride the same phase: joins stall, config appends hit
    # the one-in-flight gate, leadership transfers time out.  Every
    # invariant then runs against the NEW voter set.
    "server_replace": Schedule(
        name="server_replace",
        spec=("seed={seed};delay_ms=1;phase=storm:0.5-3.2;"
              "rpc.drop=0.02@storm;rpc.delay=0.05@storm;"
              "broker.lease_expire=0.2@storm;node.churn_kill=0.3@storm;"
              "member.join_stall=0.15@storm;"
              "raft.config_conflict=0.05@storm;"
              "transfer.timeout=0.2@storm"),
        duration_s=4.0,
        server_churn=False,
        server_replace=True,
    ),
    # the WAN cable cut: during the dark phase the multi_region shape
    # severs every cross-region link to the secondary region (and the
    # `region.partition` point drops a slice of whatever forwards still
    # get attempted).  The deterministic gates: `?stale` keeps serving
    # locally on both sides, `?consistent` reads into the dark region
    # fail fast with Unreachable, the sequential multiregion rollout
    # HALTS at the partitioned region without corrupting either spine,
    # and resumes to completion after the heal.  Only the multi_region
    # shape runs this schedule (it is excluded from the core product in
    # ALL_CELLS).
    "region_partition": Schedule(
        name="region_partition",
        spec=("seed={seed};delay_ms=1;phase=dark:0.8-2.8;"
              "region.partition=0.25@dark;rpc.delay=0.05@dark"),
        duration_s=3.6,
        server_churn=False,
    ),
}


# --------------------------------------------------------- shape context


@dataclass
class CellCtx:
    """Mutable per-cell state shared between the runner, the drivers,
    and the invariant checker."""
    namespace: str = "default"
    # job ids whose groups must sit exactly at their (final) tg.count
    exact_jobs: List[str] = field(default_factory=list)
    # job ids allowed below count (capacity-starved fillers)
    at_most_jobs: List[str] = field(default_factory=list)
    # multi-tenant shapes track jobs across namespaces; absent entries
    # fall back to ctx.namespace
    job_ns: Dict[str, str] = field(default_factory=dict)
    allow_blocked: bool = False
    drain_candidates: List[str] = field(default_factory=list)
    drained: List[str] = field(default_factory=list)
    node_ids: List[str] = field(default_factory=list)
    notes: Dict[str, object] = field(default_factory=dict)

    def tracked_jobs(self) -> List[str]:
        return self.exact_jobs + self.at_most_jobs

    def ns_of(self, job_id: str) -> str:
        return self.job_ns.get(job_id, self.namespace)


# ---------------------------------------------------------- background


class NodeKeeper(threading.Thread):
    """The mock client fleet: heartbeats every node through the leader.
    chaos `node.churn_kill` swallows re-arms inside HeartbeatTracker, so
    under a storm nodes expire through the REAL ttl-miss path and come
    back ready once their heartbeats land again."""

    def __init__(self, cluster: Cluster, node_ids: List[str],
                 interval: float = 0.3):
        super().__init__(name="matrix-keeper", daemon=True)
        self.cluster = cluster
        self.node_ids = node_ids
        self.interval = interval
        self.stop_flag = threading.Event()

    def run(self):
        while not self.stop_flag.is_set():
            try:
                ld = self.cluster.leader(timeout=1.0)
                for nid in self.node_ids:
                    ld.node_heartbeat(nid)
            except Exception:           # noqa: BLE001 — chaos/no-leader
                pass
            self.stop_flag.wait(self.interval)


class FleetDriver(threading.Thread):
    """The 10K-agent client fleet: each driver thread owns a shard of
    the registered nodes and heartbeats it through the BATCHED liveness
    RPC path (Node.BatchHeartbeat -> Server.node_heartbeats), which
    still runs every node through the real per-node heartbeat path —
    TTL-wheel re-arm, down/disconnected revival, rate-limited liveness
    stamp — so chaos `node.churn_kill` swallows individual re-arms and
    storm expiry waves flow through the genuine TTL-miss path at fleet
    scale.  `busy_s` accumulates wall time spent heartbeating, the
    steady-state heartbeat cost the fleet cells gate on."""

    def __init__(self, cluster: Cluster, node_ids: List[str],
                 interval: float = 0.5, chunk: int = 1000,
                 lock: Optional[threading.Lock] = None):
        super().__init__(name="fleet-driver", daemon=True)
        self.cluster = cluster
        self.node_ids = node_ids
        self.interval = interval
        self.chunk = chunk
        # the cold boot shares its (still-growing) id list so agents
        # heartbeat from the moment they register — at fleet size the
        # boot outlasts the TTL, and without early coverage the early
        # registrants mass-expire into a down-status wavefront that
        # races every plan apply
        self._lock = lock
        self.stop_flag = threading.Event()
        self.busy_s = 0.0
        self.rounds = 0

    def reset_stats(self):
        self.busy_s = 0.0
        self.rounds = 0

    def run(self):
        while not self.stop_flag.is_set():
            t0 = time.monotonic()
            try:
                if self._lock is not None:
                    with self._lock:
                        ids = list(self.node_ids)
                else:
                    ids = self.node_ids
                ld = self.cluster.leader(timeout=1.0)
                for i in range(0, len(ids), self.chunk):
                    ld.node_heartbeats(ids[i:i + self.chunk])
            except Exception:       # noqa: BLE001 — chaos/no-leader
                pass
            self.busy_s += time.monotonic() - t0
            self.rounds += 1
            self.stop_flag.wait(self.interval)


class HealthReporter(threading.Thread):
    """The client health plane: marks live allocs running+healthy via
    the real Node.UpdateAlloc RPC (raft-replicated, never a direct store
    write — FSM parity is one of the invariants under test).  Carries
    the `deploy.health_flap` chaos point: a firing flips one report to
    unhealthy, which is exactly what drives the deployment watcher into
    failure and auto-revert."""

    def __init__(self, cluster: Cluster, ctx: CellCtx,
                 interval: float = 0.15):
        super().__init__(name="matrix-health", daemon=True)
        self.cluster = cluster
        self.ctx = ctx
        self.interval = interval
        self.stop_flag = threading.Event()
        self.flaps = 0

    def tick(self):
        try:
            ld = self.cluster.leader(timeout=1.0)
        except TimeoutError:
            return
        updates = []
        for job_id in list(self.ctx.tracked_jobs()):
            for a in ld.store.allocs_by_job(self.ctx.ns_of(job_id),
                                            job_id):
                if a.terminal_status():
                    continue
                healthy = True
                if a.deployment_id and chaos.active is not None \
                        and chaos.should("deploy.health_flap"):
                    healthy = False
                    self.flaps += 1
                current = (a.deployment_status or {}).get("healthy")
                if a.client_status == AllocClientStatus.RUNNING \
                        and current is healthy:
                    continue
                u = a.copy()
                u.client_status = AllocClientStatus.RUNNING
                u.deployment_status = {"healthy": healthy}
                updates.append(u)
        if updates:
            ld.endpoints.handle("Node.UpdateAlloc", {"allocs": updates})

    def run(self):
        while not self.stop_flag.is_set():
            try:
                self.tick()
            except Exception:           # noqa: BLE001 — chaos/no-leader
                pass
            self.stop_flag.wait(self.interval)


class AutoscaleDriver:
    """Scale-up/down waves through the services-scaling path (Job.Scale
    with ScalingPolicy bounds).  Carries the `scale.burst` chaos point: a
    firing amplifies the wave's target to the policy max, stacking a
    burst registration on top of whatever the broker is redelivering."""

    def __init__(self, cluster: Cluster, ctx: CellCtx, job_id: str,
                 group: str, waves: List[int], policy_max: int,
                 interval: float = 0.6):
        self.cluster = cluster
        self.ctx = ctx
        self.job_id = job_id
        self.group = group
        self.waves = list(waves)
        self.policy_max = policy_max
        self.interval = interval
        self._next_at = 0.0
        self._wave = 0
        self.applied: List[int] = []
        self.bursts = 0

    def tick(self, now: Optional[float] = None):
        now = time.time() if now is None else now
        if now < self._next_at or self._wave >= len(self.waves):
            return
        self._next_at = now + self.interval
        target = self.waves[self._wave]
        self._wave += 1
        if chaos.active is not None and chaos.should("scale.burst"):
            target = self.policy_max
            self.bursts += 1
        try:
            _on_leader(self.cluster, lambda ld: ld.scale_job(
                self.ctx.namespace, self.job_id, self.group, count=target,
                message=f"matrix wave -> {target}"), timeout=5.0)
            self.applied.append(target)
        except TRANSIENT_ERRORS + (TimeoutError,):
            self._wave -= 1             # wave lost to chaos: retry it


class ChurnDriver:
    """Seeded server churn riding the schedule's open phases: at most
    one impaired member at a time (quorum must survive), alternating
    power-loss hard_kill -> WAL restart with isolate -> heal partition
    flaps."""

    def __init__(self, cluster: Cluster, reg: ChaosRegistry,
                 rng: random.Random):
        self.cluster = cluster
        self.reg = reg
        self.rng = rng
        self.dead = None                # (server, revive_at)
        self.isolated = None            # (server, heal_at)
        self._next_op = 0.0
        self.kills = 0
        self.restarts = 0
        self.partitions = 0

    def tick(self, now: Optional[float] = None):
        now = time.time() if now is None else now
        if self.dead is not None and now >= self.dead[1]:
            replacement = self.cluster.restart(self.dead[0])
            _tune(replacement)
            self.dead = None
            self.restarts += 1
        if self.isolated is not None and now >= self.isolated[1]:
            self.cluster.heal(self.isolated[0])
            self.isolated = None
        if not self.reg.phase_now():
            return
        if now < self._next_op or self.dead or self.isolated:
            return
        self._next_op = now + 0.45
        victim = self.cluster.servers[
            self.rng.randrange(len(self.cluster.servers))]
        if self.rng.random() < 0.5:
            self.cluster.hard_kill(victim)
            self.dead = (victim, now + 0.7)
            self.kills += 1
        else:
            self.cluster.isolate(victim)
            self.isolated = (victim, now + 0.4)
            self.partitions += 1

    def restore(self):
        if self.isolated is not None:
            self.cluster.heal(self.isolated[0])
            self.isolated = None
        if self.dead is not None:
            _tune(self.cluster.restart(self.dead[0]))
            self.dead = None
            self.restarts += 1

    def events(self) -> Dict[str, int]:
        return {"hard_kills": self.kills, "restarts": self.restarts,
                "partitions": self.partitions}


class ReplaceDriver:
    """The server-loss drill riding the storm phase: permanently destroy
    the CURRENT LEADER (hard_kill, never restarted — its data_dir is
    abandoned), remove it from the raft configuration, join a blank
    replacement under a new name, and wait for autopilot to promote it
    to voter.  Runs once, in a background thread (the drill spans
    elections and catch-up, and the cell loop must keep pumping the
    workload shape while it happens).  The invariant battery then runs
    against the post-replacement voter set."""

    def __init__(self, cluster: Cluster, reg: ChaosRegistry, ctx: CellCtx):
        self.cluster = cluster
        self.reg = reg
        self.ctx = ctx
        self.thread: Optional[threading.Thread] = None
        self.replaced = None            # (old_name, new_name)
        self.error: Optional[str] = None

    def tick(self, now: Optional[float] = None):
        if self.thread is not None or not self.reg.phase_now():
            return
        self.thread = threading.Thread(
            target=self._run, name="matrix-replace", daemon=True)
        self.thread.start()

    def _run(self):
        try:
            victim = self.cluster.leader(timeout=5.0)
            replacement = self.cluster.replace_server(victim, timeout=30.0)
            _tune(replacement)
            self.replaced = (victim.name, replacement.name)
        except Exception as e:          # noqa: BLE001 — reported below
            self.error = repr(e)

    def finish(self, timeout: float = 30.0):
        """Join the drill thread (it may outlive the chaos window: once
        chaos lifts its retries land quickly), then assert the
        configuration actually moved to the new voter set."""
        if self.thread is not None:
            self.thread.join(timeout)
            if self.thread.is_alive():
                self.error = self.error or "replace drill still running"
        elif self.replaced is None:
            self.error = self.error or "storm phase never opened"
        self.ctx.notes["server_replace"] = self.events()
        if self.error is not None or self.replaced is None:
            raise RuntimeError(
                f"server replace did not complete: {self.error}")
        old, new = self.replaced
        voters = _on_leader(
            self.cluster, lambda ld: ld.raft.configuration()["voters"])
        self.ctx.notes["voters_after_replace"] = voters
        if old in voters or new not in voters:
            raise RuntimeError(
                f"voter set did not converge after replace: {voters} "
                f"(destroyed {old}, joined {new})")

    def events(self) -> Dict[str, object]:
        return {"replaced": self.replaced, "error": self.error}


# --------------------------------------------------------------- shapes


def _wait_live(cluster, ctx, job_id, want, timeout=120.0):
    def placed():
        try:
            ld = cluster.leader(timeout=2.0)
        except TimeoutError:
            return False
        return len(_live(ld.store.allocs_by_job(ctx.ns_of(job_id),
                                                job_id))) >= want
    if not _wait(placed, timeout):
        raise TimeoutError(
            f"initial placement for {job_id} did not reach {want}")


def _service_job(count, cpu=500, mem=256, spread=False, priority=None):
    from nomad_tpu.structs.job import Affinity, Spread
    j = mock.job()
    tg = j.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = cpu
    tg.tasks[0].resources.memory_mb = mem
    tg.ephemeral_disk.size_mb = 0
    j.update = None
    tg.update = None
    if spread:
        tg.spreads = [Spread("${attr.rack}", 50, ())]
        tg.affinities = [Affinity("${node.datacenter}", "dc1", "=", 50)]
    if priority is not None:
        j.priority = priority
    return j


def _batch_job(count, cpu=300, mem=128):
    j = mock.batch_job()
    tg = j.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = cpu
    tg.tasks[0].resources.memory_mb = mem
    tg.ephemeral_disk.size_mb = 0
    # the matrix asserts exact post-chaos counts, so a storm must never
    # exhaust the default batch policy's single reschedule attempt into
    # a stable live-0 state
    tg.reschedule_policy = ReschedulePolicy(
        delay_s=0.2, delay_function="constant", unlimited=True)
    return j


class Shape:
    """One workload shape.  setup() builds pre-chaos steady state (and
    declares expectations in ctx), during() is pumped ~20x/s inside the
    chaos window, finish() runs after chaos lifts, before invariants.
    make_cluster()/check() let a shape swap the cluster topology (the
    multi_region shape boots a FederatedCluster and runs the invariant
    battery per region)."""

    name = "shape"
    n_nodes = 8

    def tune_config(self, cfg: ServerConfig) -> None:
        """Adjust the cell ServerConfig before the cluster is built
        (the fleet shape stretches heartbeat_ttl so a 10K-node expiry
        wave is a storm, not an extinction)."""

    def amend_spec(self, spec: str) -> str:
        """Append shape-specific chaos points to the schedule spec
        (only for curated schedules — an explicit NOMAD_TPU_CHAOS
        override is never amended)."""
        return spec

    def make_cluster(self, cfg: ServerConfig, raft_config: RaftConfig,
                     data_dir: str):
        return Cluster(3, config=cfg, raft_config=raft_config,
                       data_dir=data_dir)

    def make_nodes(self, rng: random.Random):
        nodes = []
        for i in range(self.n_nodes):
            n = mock.node()
            n.attributes["rack"] = f"r{i % 4}"
            nodes.append(n)
        return nodes

    def setup(self, cluster: Cluster, rng: random.Random, ctx: CellCtx):
        raise NotImplementedError

    def during(self, cluster: Cluster, rng: random.Random, ctx: CellCtx,
               reg: ChaosRegistry):
        pass

    def finish(self, cluster: Cluster, ctx: CellCtx):
        pass

    def check(self, cluster, ctx: CellCtx, timeout: float = 60.0) -> dict:
        return check_convergence(cluster, ctx, timeout=timeout)


class E2ESpineShape(Shape):
    """Batch spine: steady batch jobs placed pre-chaos, more registered
    mid-storm; every group must sit at count afterwards."""

    name = "e2e_spine"

    def setup(self, cluster, rng, ctx):
        self._extra_registered = False
        for _ in range(3):
            j = _batch_job(6)
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
            ctx.exact_jobs.append(j.id)
            _wait_live(cluster, ctx, j.id, 6)
        ctx.drain_candidates = list(ctx.node_ids)

    def during(self, cluster, rng, ctx, reg):
        if self._extra_registered or not reg.phase_now():
            return
        self._extra_registered = True
        for _ in range(2):
            j = _batch_job(4)
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
            ctx.exact_jobs.append(j.id)


class ScanSpreadShape(Shape):
    """Spread+affinity service jobs (the chained-scan placement path):
    the spread constraints must re-solve every time churn moves allocs."""

    name = "scan_spread"

    def setup(self, cluster, rng, ctx):
        self._extra_registered = False
        for _ in range(3):
            j = _service_job(4, spread=True)
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
            ctx.exact_jobs.append(j.id)
            _wait_live(cluster, ctx, j.id, 4)
        ctx.drain_candidates = list(ctx.node_ids)

    def during(self, cluster, rng, ctx, reg):
        if self._extra_registered or not reg.phase_now():
            return
        self._extra_registered = True
        j = _service_job(4, spread=True)
        _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
        ctx.exact_jobs.append(j.id)


class DeviceConstrainedShape(Shape):
    """Half the fleet carries GPU device groups; jobs pin DeviceRequest,
    so lost-node replacement must re-find device capacity, not just cpu."""

    name = "device_constrained"

    def make_nodes(self, rng):
        nodes = super().make_nodes(rng)
        self._device_nodes = []
        for i, n in enumerate(nodes):
            if i % 2 == 0:
                n.node_resources.devices = [NodeDevice(
                    vendor="nvidia", type="gpu", name="a100",
                    instance_ids=[f"gpu-{n.id[:8]}-0", f"gpu-{n.id[:8]}-1"])]
                self._device_nodes.append(n.id)
        return nodes

    def setup(self, cluster, rng, ctx):
        self._mid_registered = False
        for _ in range(2):
            j = _batch_job(3)
            j.task_groups[0].tasks[0].resources.devices = [
                DeviceRequest(name="gpu", count=1)]
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
            ctx.exact_jobs.append(j.id)
            _wait_live(cluster, ctx, j.id, 3)
        # draining a device node could starve instances: drain cpu-only
        ctx.drain_candidates = [nid for nid in ctx.node_ids
                                if nid not in self._device_nodes]

    def during(self, cluster, rng, ctx, reg):
        # a device job landing mid-chaos: the feasibility walk must find
        # gpu instances while heartbeats are being swallowed
        if self._mid_registered or not reg.phase_now():
            return
        self._mid_registered = True
        j = _batch_job(2)
        j.task_groups[0].tasks[0].resources.devices = [
            DeviceRequest(name="gpu", count=1)]
        _on_leader(cluster, lambda ld: ld.register_job(j))
        ctx.exact_jobs.append(j.id)


class PreemptionHeavyShape(Shape):
    """The fleet packed with low-priority fillers; a priority-90 service
    job lands mid-chaos and must preempt its way in.  Displaced fillers
    legitimately block on capacity, so BLOCKED evals are allowed."""

    name = "preemption_heavy"
    n_nodes = 6

    def setup(self, cluster, rng, ctx):
        self._service_registered = False
        import copy as _copy

        def enable_preemption(ld):
            from nomad_tpu.raft import MessageType
            cfg = _copy.deepcopy(ld.store.scheduler_config)
            cfg.preemption_config.service_scheduler_enabled = True
            cfg.preemption_config.batch_scheduler_enabled = True
            ld.apply(MessageType.SCHEDULER_CONFIG, {"config": cfg})
        _on_leader(cluster, enable_preemption)
        # 4 slots per node (900cpu/1800mb on 4000/8192) -> 24 slots, all
        # taken by fillers
        self.filler = _batch_job(24, cpu=900, mem=1800)
        self.filler.priority = 20
        _on_leader(cluster, lambda ld: ld.register_job(self.filler))
        ctx.at_most_jobs.append(self.filler.id)
        ctx.allow_blocked = True
        _wait_live(cluster, ctx, self.filler.id, 24)

    def during(self, cluster, rng, ctx, reg):
        if self._service_registered or not reg.phase_now():
            return
        self._service_registered = True
        j = _service_job(4, cpu=900, mem=1800, priority=90)
        _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
        ctx.exact_jobs.append(j.id)


class ServingPlaneShape(Shape):
    """The read path under chaos: event subscriptions plus follower
    lease reads keep running while the spine registers jobs; reads may
    fail during churn but must resume, and the write-side invariants
    still hold."""

    name = "serving_plane"

    def setup(self, cluster, rng, ctx):
        self._extra_registered = False
        for _ in range(2):
            j = _service_job(4)
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
            ctx.exact_jobs.append(j.id)
            _wait_live(cluster, ctx, j.id, 4)
        ctx.drain_candidates = list(ctx.node_ids)
        self._stop = threading.Event()
        self._reads_ok = [0]
        self._reads_err = [0]
        self._events = [0]
        follower = cluster.followers()[0]
        self._subs = []
        try:
            self._subs = [follower.event_broker.subscribe(
                {"*": ["*"]}, max_queue=64) for _ in range(32)]
        except Exception:               # noqa: BLE001
            pass

        def reader():
            while not self._stop.is_set():
                srv = cluster.servers[rng.randrange(len(cluster.servers))]
                try:
                    srv.read("Job.List", {}, consistency="default",
                             timeout=1.0)
                    self._reads_ok[0] += 1
                except Exception:       # noqa: BLE001
                    self._reads_err[0] += 1
                for sub in self._subs[:8]:
                    try:
                        while sub.next(timeout=0.0) is not None:
                            self._events[0] += 1
                    except Exception:   # noqa: BLE001
                        pass
                time.sleep(0.01)

        self._threads = [threading.Thread(target=reader, daemon=True)
                         for _ in range(2)]
        for t in self._threads:
            t.start()

    def during(self, cluster, rng, ctx, reg):
        if self._extra_registered or not reg.phase_now():
            return
        self._extra_registered = True
        j = _service_job(4)
        _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
        ctx.exact_jobs.append(j.id)

    def finish(self, cluster, ctx):
        self._stop.set()
        for t in self._threads:
            t.join(2.0)
        for sub in self._subs:
            try:
                sub.close()
            except Exception:           # noqa: BLE001
                pass
        ctx.notes["reads_ok"] = self._reads_ok[0]
        ctx.notes["reads_err"] = self._reads_err[0]
        ctx.notes["events_consumed"] = self._events[0]


class RollingDeployShape(Shape):
    """Rolling deploy under churn: v0 stable and healthy, then a canary
    + auto-revert destructive update lands mid-chaos while nodes die.
    The deployment must settle — promoted to SUCCESSFUL, or FAILED with
    the job auto-reverted to the stable version."""

    name = "rolling_deploy"
    n_nodes = 6

    def setup(self, cluster, rng, ctx):
        self._v1_registered = False
        j = _service_job(4)
        j.update = UpdateStrategy(max_parallel=2, auto_revert=True,
                                  canary=1, auto_promote=True,
                                  health_check="checks")
        self.job = j
        _on_leader(cluster, lambda ld: ld.register_job(j))
        ctx.exact_jobs.append(j.id)
        _wait_live(cluster, ctx, j.id, 4)
        # v0 healthy (the HealthReporter isn't running yet in setup)
        def mark_healthy(ld):
            updates = []
            for a in ld.store.allocs_by_job(ctx.namespace, j.id):
                if a.terminal_status():
                    continue
                u = a.copy()
                u.client_status = AllocClientStatus.RUNNING
                u.deployment_status = {"healthy": True}
                updates.append(u)
            ld.endpoints.handle("Node.UpdateAlloc", {"allocs": updates})
        _on_leader(cluster, mark_healthy)
        # v0 is the stable rollback target
        _on_leader(cluster, lambda ld: ld.set_job_stability(
            ctx.namespace, j.id, 0, True))
        ctx.drain_candidates = list(ctx.node_ids)
        ctx.notes["v0_config"] = dict(
            j.task_groups[0].tasks[0].config)

    def during(self, cluster, rng, ctx, reg):
        if self._v1_registered or not reg.phase_now():
            return
        self._v1_registered = True
        v1 = self.job.copy()
        v1.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        _on_leader(cluster, lambda ld: ld.register_job(v1))
        ctx.notes["v1_config"] = {"command": "/bin/new"}

    def finish(self, cluster, ctx):
        def outcome(ld):
            job = ld.store.job_by_id(ctx.namespace, self.job.id)
            deps = [d for d in ld.store.deployments()
                    if d.job_id == self.job.id]
            return {"job_version": job.version if job else None,
                    "config": dict(job.task_groups[0].tasks[0].config)
                    if job else None,
                    "deployments": [(d.job_version, d.status)
                                    for d in deps]}
        try:
            ctx.notes["deploy_outcome"] = _on_leader(cluster, outcome,
                                                     timeout=5.0)
        except TRANSIENT_ERRORS + (TimeoutError,):
            pass


class AutoscaleRampShape(Shape):
    """Autoscaling ramp: Job.Scale waves walk a ScalingPolicy-bounded
    group up and down while the broker sheds leases; a final post-chaos
    wave sets the count every invariant is measured against."""

    name = "autoscale_ramp"
    n_nodes = 6
    FINAL = 5

    def setup(self, cluster, rng, ctx):
        j = _service_job(2)
        j.task_groups[0].scaling = ScalingPolicy(min=1, max=10,
                                                 enabled=True)
        self.job = j
        _on_leader(cluster, lambda ld: ld.register_job(j))
        ctx.exact_jobs.append(j.id)
        _wait_live(cluster, ctx, j.id, 2)
        self.driver = AutoscaleDriver(
            cluster, ctx, j.id, j.task_groups[0].name,
            waves=[6, 3, 8, 4, 7, 3, 8, 5], policy_max=10,
            interval=0.45)

    def during(self, cluster, rng, ctx, reg):
        self.driver.tick()

    def finish(self, cluster, ctx):
        # the settling wave: whatever the chaos window left behind, the
        # group must converge to FINAL
        _on_leader(cluster, lambda ld: ld.scale_job(
            ctx.namespace, self.job.id, self.job.task_groups[0].name,
            count=self.FINAL, message="matrix settle"), timeout=20.0)
        ctx.notes["scale_waves_applied"] = self.driver.applied
        ctx.notes["scale_bursts"] = self.driver.bursts


class MultiTenantShape(Shape):
    """1K+ registered tenants behind replicated namespaces, a small
    active set, one abusive: the abuser floods ABUSE_JOBS submissions
    (100x the single job each victim lands mid-window) into a 4-alloc
    quota while the victims keep submitting.  Gated: weighted fair
    dequeue keeps every victim's plan.submit p99 under 2x its solo
    baseline (plus a fixed allowance for leader elections, which stall
    a submit whether or not the abuser exists), per-namespace quota
    usage converges to exactly the live-alloc sums on every survivor
    (byte-identity of the usage tables rides the fsm_identical check),
    the abuser never holds more than its quota admits, and no alloc or
    eval ever crosses a namespace boundary."""

    name = "multi_tenant"
    TENANTS = 1024                      # registered namespaces (1K+ floor)
    VICTIMS = 3
    ABUSE_JOBS = 100                    # 100x each victim's one submit
    P99_FLOOR_MS = 300.0                # one election's worth of stall

    def setup(self, cluster, rng, ctx):
        from nomad_tpu.structs import QuotaSpec
        from nomad_tpu.telemetry import global_metrics
        self._victims_submitted = False
        self._abuse_sent = 0
        self.victim_ns = [f"tenant-v{i}" for i in range(1, self.VICTIMS + 1)]
        self.abuse_ns = "tenant-abuse"
        self._contended: Dict[str, str] = {}
        self._baseline: Dict[str, dict] = {}
        _on_leader(cluster, lambda ld: ld.upsert_quota_spec(QuotaSpec(
            name="tenant-std", description="steady tenant envelope",
            allocs=32)))
        _on_leader(cluster, lambda ld: ld.upsert_quota_spec(QuotaSpec(
            name="abuse-cap", description="abusive tenant clamp",
            allocs=4)))
        # the registered-tenant universe: every namespace is replicated
        # state the post-chaos FSM identity check must reproduce; the
        # pool pipelines proposals so they batch into few commit rounds
        import concurrent.futures as futures
        names = [f"tenant-{i:04d}" for i in range(self.TENANTS)]
        with futures.ThreadPoolExecutor(max_workers=8) as ex:
            list(ex.map(
                lambda nm: _on_leader(
                    cluster, lambda ld, nm=nm: ld.upsert_namespace(
                        nm, quota="tenant-std")), names))
        for ns in self.victim_ns:
            _on_leader(cluster, lambda ld, ns=ns: ld.upsert_namespace(
                ns, quota="tenant-std"))
        _on_leader(cluster, lambda ld: ld.upsert_namespace(
            self.abuse_ns, quota="abuse-cap"))
        # solo baseline: each victim lands jobs on the calm cluster and
        # its per-namespace plan.submit series drains into the baseline
        for ns in self.victim_ns:
            global_metrics.take_sample(f"nomad.plan.submit.ns.{ns}")
            for _ in range(2):
                j = _batch_job(2, cpu=200, mem=64)
                j.namespace = ns
                _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
                ctx.exact_jobs.append(j.id)
                ctx.job_ns[j.id] = ns
                _wait_live(cluster, ctx, j.id, 2)
            self._baseline[ns] = global_metrics.take_sample(
                f"nomad.plan.submit.ns.{ns}")
        ctx.allow_blocked = True        # quota-blocked abusive evals stay
        ctx.drain_candidates = list(ctx.node_ids)

    def during(self, cluster, rng, ctx, reg):
        if not reg.phase_now():
            return
        if not self._victims_submitted:
            self._victims_submitted = True
            for ns in self.victim_ns:
                j = _batch_job(2, cpu=200, mem=64)
                j.namespace = ns
                _on_leader(cluster, lambda ld, j=j: ld.register_job(j),
                           timeout=3.0)
                ctx.exact_jobs.append(j.id)
                ctx.job_ns[j.id] = ns
                self._contended[ns] = j.id
        for _ in range(5):              # ~100/s against the victims' ~1
            if self._abuse_sent >= self.ABUSE_JOBS:
                break
            j = _batch_job(1, cpu=200, mem=64)
            j.namespace = self.abuse_ns
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j),
                       timeout=3.0)
            ctx.at_most_jobs.append(j.id)
            ctx.job_ns[j.id] = self.abuse_ns
            self._abuse_sent += 1

    def finish(self, cluster, ctx):
        from nomad_tpu.telemetry import global_metrics
        for job_id in self._contended.values():
            _wait_live(cluster, ctx, job_id, 2, timeout=45.0)
        gate = {}
        for ns in self.victim_ns:
            m = global_metrics.take_sample(f"nomad.plan.submit.ns.{ns}")
            solo = float((self._baseline.get(ns) or {}).get("p99") or 0.0)
            limit = max(2.0 * solo, self.P99_FLOOR_MS)
            p99 = float(m.get("p99") or 0.0)
            gate[ns] = {"solo_p99_ms": round(solo, 2),
                        "p99_ms": round(p99, 2),
                        "count": m.get("count", 0),
                        "limit_ms": round(limit, 2),
                        "ok": p99 <= limit}
        ctx.notes["victim_p99_gate"] = gate
        ctx.notes["abuse_jobs_submitted"] = self._abuse_sent
        ctx.notes["tenants_registered"] = self.TENANTS + self.VICTIMS + 1

    @staticmethod
    def _quota_problems(ld) -> List[str]:
        from nomad_tpu.structs.namespace import alloc_quota_usage, usage_add
        expect: Dict[str, Dict[str, int]] = {}
        for a in ld.store.allocs():
            if a.terminal_status():
                continue
            u = expect.setdefault(a.namespace, {
                "cpu": 0, "memory_mb": 0, "devices": 0, "allocs": 0})
            usage_add(u, alloc_quota_usage(a), +1)
        expect = {ns: u for ns, u in expect.items() if any(u.values())}
        actual = ld.store.quota_usages()
        problems = [
            f"{ns}: tracked {actual.get(ns)} != live {expect.get(ns)}"
            for ns in sorted(set(expect) | set(actual))
            if expect.get(ns) != actual.get(ns)]
        for nso in ld.store.namespaces():
            if not nso.quota:
                continue
            spec = ld.store.quota_spec(nso.quota)
            u = actual.get(nso.name)
            if spec is not None and u and not spec.admits(u):
                problems.append(
                    f"{nso.name}: usage {u} exceeds quota {nso.quota} "
                    f"on {spec.exceeded_dims(u)}")
        return problems

    @staticmethod
    def _leak_problems(ld) -> List[str]:
        problems = []
        for a in ld.store.allocs():
            if a.terminal_status():
                continue
            job = ld.store.job_by_id(a.namespace, a.job_id)
            if job is None:
                problems.append(
                    f"alloc {a.id[:8]}: no job {a.job_id} in namespace "
                    f"{a.namespace!r}")
            elif job.namespace != a.namespace:
                problems.append(
                    f"alloc {a.id[:8]}: job namespace {job.namespace!r} "
                    f"!= alloc namespace {a.namespace!r}")
        for e in ld.store.evals():
            if EvalStatus.terminal(e.status):
                continue
            if ld.store.job_by_id(e.namespace, e.job_id) is None:
                problems.append(
                    f"eval {e.id[:8]}: no job {e.job_id} in namespace "
                    f"{e.namespace!r}")
        return problems

    def check(self, cluster, ctx, timeout: float = 60.0) -> dict:
        res = check_convergence(cluster, ctx, timeout=timeout)
        ld = cluster.leader(timeout=10.0)
        qprobs = lprobs = None
        for attempt in range(3):
            if attempt:
                time.sleep(2.0)         # reviving nodes may still drain
            qprobs = self._quota_problems(ld)
            lprobs = self._leak_problems(ld)
            if not qprobs and not lprobs:
                break
        res["invariants"]["quota_converged"] = {
            "ok": not qprobs, "detail": qprobs[:8] or "clean"}
        res["invariants"]["no_cross_ns_leakage"] = {
            "ok": not lprobs, "detail": lprobs[:8] or "clean"}
        gate = ctx.notes.get("victim_p99_gate") or {}
        bad = [f"{ns}: p99 {g['p99_ms']}ms > limit {g['limit_ms']}ms"
               for ns, g in gate.items() if not g["ok"]] \
            if gate else ["no victim gate recorded"]
        res["invariants"]["victim_p99_bounded"] = {
            "ok": not bad, "detail": bad or "clean"}
        res["converged"] = bool(res["converged"]) and not qprobs \
            and not lprobs and not bad
        return res


class MultiRegionShape(Shape):
    """Federation under a WAN cut: two 3-server regions over one shared
    transport, WAN-gossip joined, running a sequential multiregion
    rollout (primary -> remote, with a per-region count override).  When
    the chaos phase opens the shape severs every cross-region link (the
    `region.partition` point additionally drops a slice of the forwards
    that still get attempted) and only THEN releases the primary
    rollout, so the primary deployment goes SUCCESSFUL while the next
    region is dark.  Gated while dark: `?stale` keeps serving locally,
    `?consistent` reads into the dark region fail fast with Unreachable,
    and the rollout HALTS at the region boundary (the remote spine never
    hears about the job).  After the heal the rollout must resume to
    completion, and the invariant battery — including FSM byte-identity
    — runs per region."""

    name = "multi_region"
    n_nodes = 4                         # per region
    regions = ("global", "west")

    def make_cluster(self, cfg, raft_config, data_dir):
        from nomad_tpu.core.cluster import FederatedCluster
        self.fc = FederatedCluster(regions=self.regions, n=3, config=cfg,
                                   raft_config=raft_config,
                                   data_dir=data_dir)
        return self.fc

    def setup(self, cluster, rng, ctx):
        from nomad_tpu.structs import Multiregion, MultiregionRegion
        fc = self.fc
        fc.wait_federated(timeout=30.0)
        self.primary, self.remote = self.regions
        self._partitioned = self._healed = False
        self._reg = None
        # the runner's keeper/health drive only the primary region; the
        # remote region gets its own client fleet + background planes
        rc = fc.clusters[self.remote]
        self._rctx = CellCtx()
        rnodes = [mock.node() for _ in range(self.n_nodes)]
        for n in rnodes:
            _on_leader(rc, lambda ld, n=n: ld.register_node(n))
        self._rctx.node_ids = [n.id for n in rnodes]
        self._rkeeper = NodeKeeper(rc, self._rctx.node_ids)
        self._rkeeper.start()
        self._rhealth = HealthReporter(rc, self._rctx)
        self._rhealth.start()
        # sequential multiregion rollout with a per-region count override
        j = mock.job()
        tg = j.task_groups[0]
        tg.count = 3
        tg.tasks[0].resources.cpu = 300
        tg.tasks[0].resources.memory_mb = 128
        tg.ephemeral_disk.size_mb = 0
        j.multiregion = Multiregion(regions=[
            MultiregionRegion(name=self.primary, count=3),
            MultiregionRegion(name=self.remote, count=2)])
        self.job = j
        _on_leader(cluster, lambda ld: ld.register_job(j))
        _wait_live(cluster, ctx, j.id, 3)
        # NOT added to ctx.exact_jobs yet: the HealthReporter must not
        # drive the primary deployment SUCCESSFUL (and kick the remote
        # region) before the partition is in place — during() releases
        # the rollout when the dark phase opens

    def during(self, cluster, rng, ctx, reg):
        self._reg = reg
        fc = self.fc
        in_phase = bool(reg.phase_now())
        if in_phase and not self._partitioned:
            self._partitioned = True
            fc.partition_region(self.remote)
            ctx.notes["partitioned_at_s"] = round(reg.elapsed() or 0.0, 2)
            ctx.exact_jobs.append(self.job.id)      # release the rollout
        elif self._partitioned and not self._healed and not in_phase:
            self._healed = True
            fc.heal_region(self.remote)
            ctx.notes["healed_at_s"] = round(reg.elapsed() or 0.0, 2)
        if self._partitioned and not self._healed:
            self._probe_dark(fc, ctx)

    def _probe_dark(self, fc, ctx):
        """Record each dark-phase gate the first time it is observed
        (every probe is best-effort: elections may be in flight)."""
        from nomad_tpu.raft.transport import Unreachable
        try:
            gl = fc.clusters[self.primary].leader(timeout=0.5)
        except TimeoutError:
            return
        ns = ctx.namespace
        if "gate_stale_local" not in ctx.notes:
            try:
                gl.endpoints.handle("Job.List", {"consistency": "stale"})
                ctx.notes["gate_stale_local"] = True
            except Exception:           # noqa: BLE001
                pass
        if "gate_consistent_unreachable" not in ctx.notes:
            t0 = time.time()
            try:
                gl.endpoints.handle("Job.GetJob", {
                    "namespace": ns, "job_id": self.job.id,
                    "region": self.remote, "consistency": "consistent"})
            except Unreachable:
                ctx.notes["gate_consistent_unreachable"] = round(
                    time.time() - t0, 3)
            except Exception:           # noqa: BLE001
                pass
        if "gate_halt_at_boundary" not in ctx.notes:
            try:
                wl = fc.clusters[self.remote].leader(timeout=0.5)
            except TimeoutError:
                return
            d = gl.store.latest_deployment_by_job_id(ns, self.job.id)
            if (d is not None
                    and d.status == DeploymentStatus.SUCCESSFUL
                    and not d.multiregion_kicked
                    and wl.store.job_by_id(ns, self.job.id) is None):
                ctx.notes["gate_halt_at_boundary"] = True

    def finish(self, cluster, ctx):
        fc = self.fc
        ns = ctx.namespace
        if self._partitioned and not self._healed:
            fc.heal_region(self.remote)
            self._healed = True
        if self.job.id not in ctx.exact_jobs:
            ctx.exact_jobs.append(self.job.id)
        # under the deterministic region_partition schedule every gate
        # must have been observed and the rollout must complete; under
        # storm the health-flap point may legitimately FAIL the primary
        # deployment, in which case the rollout is (correctly) abandoned
        strict = self._reg is not None and (
            "region.partition" in self._reg.phased
            or self._reg.rates.get("region.partition", 0.0) > 0.0)
        pc, rc = fc.clusters[self.primary], fc.clusters[self.remote]

        def primary_settled():
            try:
                gl = pc.leader(timeout=1.0)
            except TimeoutError:
                return False
            d = gl.store.latest_deployment_by_job_id(ns, self.job.id)
            return d is not None and d.status in (
                DeploymentStatus.SUCCESSFUL, DeploymentStatus.FAILED)
        _wait(primary_settled, timeout=30.0)
        d = _on_leader(pc, lambda ld: ld.store.latest_deployment_by_job_id(
            ns, self.job.id))
        ctx.notes["primary_deployment"] = None if d is None else d.status
        if strict and (d is None
                       or d.status != DeploymentStatus.SUCCESSFUL):
            raise RuntimeError(
                f"primary deployment did not succeed: "
                f"{None if d is None else d.status}")
        if d is not None and d.status == DeploymentStatus.SUCCESSFUL:
            # resume-post-heal: the halted kick must now land
            def remote_arrived():
                try:
                    wl = rc.leader(timeout=1.0)
                except TimeoutError:
                    return False
                return wl.store.job_by_id(ns, self.job.id) is not None
            if not _wait(remote_arrived, timeout=30.0):
                raise RuntimeError(
                    "multiregion rollout did not resume after heal")
            self._rctx.exact_jobs.append(self.job.id)
            ctx.notes["gate_resume_post_heal"] = True
            rollout = _on_leader(pc, lambda ld: ld.store.job_by_id(
                ns, self.job.id).meta.get("multiregion.rollout"))
            wj = _on_leader(rc, lambda ld: ld.store.job_by_id(
                ns, self.job.id))
            if wj.meta.get("multiregion.rollout") != rollout:
                raise RuntimeError("remote job carries a different "
                                   "rollout id")
            if wj.task_groups[0].count != 2:
                raise RuntimeError(
                    f"per-region count override lost: remote count "
                    f"{wj.task_groups[0].count} != 2")
        if strict:
            missing = [g for g in ("gate_stale_local",
                                   "gate_consistent_unreachable",
                                   "gate_halt_at_boundary")
                       if g not in ctx.notes]
            if missing:
                raise RuntimeError(
                    f"dark-phase gates never observed: {missing}")

    def check(self, cluster, ctx, timeout: float = 60.0) -> dict:
        """Per-region invariant battery (each region is its own raft
        spine, so FSM byte-identity is asserted within each region)."""
        fc = self.fc
        ctxs = {self.primary: ctx, self.remote: self._rctx}
        merged = {"converged": True, "convergence_time_s": 0.0,
                  "invariants": {}}
        try:
            for rname in self.regions:
                res = check_convergence(fc.clusters[rname], ctxs[rname],
                                        timeout=timeout)
                merged["converged"] = (merged["converged"]
                                       and bool(res["converged"]))
                merged["convergence_time_s"] = max(
                    merged["convergence_time_s"],
                    res["convergence_time_s"])
                for k, v in res["invariants"].items():
                    merged["invariants"][f"{rname}.{k}"] = v
        finally:
            self._rkeeper.stop_flag.set()
            self._rhealth.stop_flag.set()
        return merged


def _counter(name: str) -> float:
    for row in global_metrics.snapshot()["Counters"]:
        if row["Name"] == name:
            return float(row["Count"])
    return 0.0


class FleetSoakShape(Shape):
    """Fleet scale on the real heartbeat path: NOMAD_TPU_FLEET_AGENTS
    (default 10000) in-process client agents register against a 3-server
    cluster and heartbeat through the batched liveness RPC, so the
    steady-state write load is O(batches) NodeHeartbeatBatch entries per
    flush tick, not O(nodes).  The cell gates the fleet-shaped numbers
    the small shapes cannot see:

        reg_ready_p99_ms   registration-to-ready p99 for the cold boot
        hb_busy_frac       steady-state fleet heartbeat cost (driver
                           wall-time fraction) + batch-flush counters
        blank_join_s       a blank server joining at FULL state catches
                           up via the chunked snapshot stream — with the
                           leader HARD-KILLED mid-transfer, the stream
                           must resume from the acked offset under the
                           new leader (same ChunkSink, no restart) and
                           the battery then proves FSM byte-identity

    The storm rides the shared schedules with the snapshot-plane chaos
    points amended in: chunked streams to restarted/replacement members
    lose frames (snapshot.chunk_drop), abort mid-flight and resume next
    tick (snapshot.stream_abort), and batch flushes stall
    (heartbeat.batch_stall) while expiry waves keep coalescing."""

    name = "fleet_soak"

    def __init__(self):
        self.n_agents = knobs.get_int("NOMAD_TPU_FLEET_AGENTS")
        self._driver: Optional[FleetDriver] = None
        self._drain_wave_done = False
        self._last_compact = 0.0
        self._compact_rr = 0
        self._counters0: Dict[str, float] = {}

    def tune_config(self, cfg: ServerConfig) -> None:
        # at 10K agents a churn_kill storm must thin the fleet, not
        # extinguish it: stretch the TTL so expiry needs several
        # consecutive swallowed heartbeats
        cfg.heartbeat_ttl = 3.0

    def amend_spec(self, spec: str) -> str:
        return (spec + ";snapshot.chunk_drop=0.1@storm"
                ";snapshot.stream_abort=0.05@storm"
                ";heartbeat.batch_stall=0.15@storm")

    def make_nodes(self, rng):
        # the runner's serial registration loop (and NodeKeeper's
        # per-node heartbeat RPC) would take minutes at fleet size; the
        # shape boots its own fleet in setup() instead
        return []

    def setup(self, cluster, rng, ctx):
        lat_ms: List[float] = []
        ids: List[str] = []
        lock = threading.Lock()
        t_boot = time.monotonic()

        # heartbeats must flow DURING the boot: each registrant arms a
        # TTL deadline immediately, and a 10K boot outlasts the TTL by
        # an order of magnitude — publish ids incrementally so the
        # already-running driver covers them within one interval
        def boot(count):
            for _ in range(count):
                n = mock.node()
                t0 = time.monotonic()
                _on_leader(cluster, lambda ld, n=n: ld.register_node(n),
                           timeout=60.0)
                ms = (time.monotonic() - t0) * 1000.0
                with lock:
                    ids.append(n.id)
                    lat_ms.append(ms)

        self._driver = FleetDriver(cluster, ids, lock=lock)
        self._driver.start()
        nthreads = 16
        share, extra = divmod(self.n_agents, nthreads)
        threads = [threading.Thread(
            target=boot, args=(share + (1 if i < extra else 0),),
            name=f"fleet-boot-{i}", daemon=True) for i in range(nthreads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300.0)
        if len(ids) < self.n_agents:
            raise RuntimeError(
                f"fleet cold boot registered {len(ids)}/{self.n_agents}")
        boot_s = time.monotonic() - t_boot
        lat_ms.sort()
        ctx.node_ids = ids              # NodeKeeper holds the old []
        ctx.drain_candidates = list(ids)
        ctx.notes["fleet_agents"] = self.n_agents
        ctx.notes["cold_boot_s"] = round(boot_s, 2)
        ctx.notes["reg_ready_p99_ms"] = round(
            lat_ms[int(0.99 * (len(lat_ms) - 1))], 2)
        ctx.notes["reg_per_sec"] = round(self.n_agents / boot_s, 1)

        for _ in range(3):
            j = _batch_job(8)
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
            ctx.exact_jobs.append(j.id)
            _wait_live(cluster, ctx, j.id, 8)

        self._counters0 = {k: _counter(k) for k in
                           ("heartbeat.batch_flush",
                            "heartbeat.batch_nodes",
                            "raft.snapshot.send_fail")}
        # hb_busy_frac gates the STEADY-STATE heartbeat cost: drop the
        # boot-era rounds (partial fleet, contended leader) from the
        # sample before the chaos window opens
        self._driver.reset_stats()

    def during(self, cluster, rng, ctx, reg):
        if not reg.phase_now():
            return
        # compact live members through the storm (not just once at the
        # open): a server that dies keeps its WAL position, so only a
        # compaction landing WHILE it is down forces its catch-up onto
        # the chunked stream — which is where snapshot.chunk_drop and
        # snapshot.stream_abort bite
        # one member per call, round-robin: serializing a fleet-sized
        # FSM three times per tick would stretch a single loop iteration
        # past the whole storm window (and the ReplaceDriver only fires
        # from an iteration that lands INSIDE the window)
        now = time.monotonic()
        if now - self._last_compact > 0.4:
            self._last_compact = now
            live = [s for s in cluster.servers
                    if s.raft is not None and not s._stop.is_set()]
            if live:
                s = live[self._compact_rr % len(live)]
                self._compact_rr += 1
                try:
                    s.raft.force_snapshot()
                except Exception:       # noqa: BLE001 — dying member
                    pass
        if self._drain_wave_done:
            return
        self._drain_wave_done = True
        # one drain STORM mid-window: a wave of alloc-bearing and
        # empty nodes drain concurrently while expiry waves coalesce
        busy = {a.node_id for a in _on_leader(
            cluster, lambda ld: _live(ld.store.allocs()))}
        victims = list(busy)[:8] + rng.sample(ctx.node_ids, k=8)
        for nid in dict.fromkeys(victims):
            try:
                _on_leader(cluster, lambda ld, nid=nid:
                           ld.drainer.drain_node(nid, deadline_s=1.0),
                           timeout=5.0)
                ctx.drained.append(nid)
            except TRANSIENT_ERRORS + (TimeoutError,):
                pass

    def finish(self, cluster, ctx):
        drv = self._driver
        if drv is not None:
            elapsed = max(1e-9, drv.rounds * drv.interval + drv.busy_s)
            ctx.notes["hb_busy_frac"] = round(drv.busy_s / elapsed, 4)
            ctx.notes["hb_rounds"] = drv.rounds
        for k, v0 in self._counters0.items():
            ctx.notes[k] = round(_counter(k) - v0, 1)
        self._quiesce(cluster, ctx)
        self._blank_join_drill(cluster, ctx)

    def _blank_join_drill(self, cluster, ctx):
        """The blank-join gate at full state: a blank server can only
        catch up via the chunked snapshot stream, the leader is
        HARD-KILLED provably mid-transfer, and the successor must drive
        the SAME stream to completion from the follower's acked offset
        (same ChunkSink, no restart from byte zero)."""
        # every member must hold the IDENTICAL snapshot record: the
        # leader snapshots once and bootstraps the followers through the
        # real monolithic install path (persist + restore + compact), so
        # whoever wins the post-kill election streams the same identity
        # and the joiner's partial sink resumes instead of discarding.
        # The quiesced control plane makes the applied-index barrier
        # below converge.
        rec = None
        for attempt in range(12):
            ld = cluster.leader(timeout=10.0)
            # wait out the post-storm write tail (followup evals, plan
            # results): the bootstrap below needs an instant where every
            # member sits at the same applied index
            _wait(lambda: ld.raft.state == "leader"
                  and cluster.wait_replication(ld.raft.log.last_index,
                                               timeout=0.5),
                  timeout=5.0, interval=0.1)
            ld.raft.force_snapshot()
            rec = ld.raft.snapshots.latest_full()
            peers = [s for s in cluster.servers
                     if s is not ld and not s._stop.is_set()]
            if not (_wait(lambda: all(p.raft.last_applied >= rec["index"]
                                      for p in peers), timeout=10.0)
                    and all(p.raft.last_applied == rec["index"]
                            for p in peers)):
                time.sleep(0.5)
                continue
            for p in peers:
                if p.raft.last_applied == rec["index"] \
                        and p.raft._last_snapshot_index < rec["index"]:
                    p.raft._on_install_snapshot({
                        "term": p.raft.term, "leader": ld.name,
                        "last_index": rec["index"],
                        "last_term": rec["term"],
                        "data": rec["data"], "config": rec.get("config")})
            live = [s for s in cluster.servers if not s._stop.is_set()]
            if all(s.raft._last_snapshot_index == rec["index"]
                   for s in live):
                break
            time.sleep(0.5)
        else:
            raise RuntimeError(
                "could not align an identical snapshot record across "
                "the cluster for the mid-stream kill drill")
        snap_bytes = len(rec["data"])
        ctx.notes["snapshot_bytes"] = snap_bytes
        # carve the stream into many frames so "mid-transfer" exists
        # even at the reduced CI fleet size
        chunk_override = contextlib.ExitStack()
        chunk_override.enter_context(knobs.override(
            "NOMAD_TPU_SNAP_CHUNK",
            min(max(4096, snap_bytes // 64), 256 * 1024)))
        joiner = None
        try:
            # hold the stream in backoff until the chunk gate is
            # installed on the joiner, then release it — on EVERY live
            # member, since leadership may move before the gate is up
            name = "fleet-joiner"
            for s in cluster.servers:
                if not s._stop.is_set():
                    with s.raft._lock:
                        s.raft._snap_backoff[name] = (
                            0, time.monotonic() + 30.0)
            t0 = time.monotonic()
            joiner = cluster.add_server(name=name, timeout=60.0)
            held = threading.Event()     # a mid-stream frame is parked
            release = threading.Event()  # kill done: let frames flow
            orig = joiner.raft._on_snapshot_chunk

            def gated(a):
                if a.get("offset", 0) > 0 and not release.is_set():
                    held.set()
                    release.wait(30.0)
                return orig(a)

            joiner.raft._on_snapshot_chunk = gated
            for s in cluster.servers:
                if s is not joiner and not s._stop.is_set():
                    with s.raft._lock:
                        s.raft._snap_backoff.pop(name, None)
            if not _wait(held.is_set, timeout=30.0, interval=0.001):
                raise RuntimeError("snapshot stream never reached the "
                                   "joiner's chunk gate")
            sink = joiner.raft._snap_rx
            kill_offset = sink.offset if sink is not None else 0
            ctx.notes["kill_offset"] = kill_offset
            victim = cluster.leader(timeout=5.0)
            cluster.hard_kill(victim)
            release.set()
            if not _wait(lambda: joiner.raft._last_snapshot_index > 0,
                         timeout=90.0, interval=0.01):
                raise RuntimeError("joiner never completed the snapshot "
                                   "stream after the mid-transfer kill")
            joiner.raft._on_snapshot_chunk = orig
            ctx.notes["blank_join_s"] = round(time.monotonic() - t0, 2)
            # resume, not restart: the sink the dead leader was filling
            # was driven to completion by the successor
            resumed = bool(sink is not None and kill_offset > 0
                           and sink.offset >= snap_bytes)
            ctx.notes["stream_resumed"] = resumed
            if not resumed:
                raise RuntimeError(
                    f"stream restarted instead of resuming "
                    f"(kill_offset={kill_offset}, "
                    f"sink={sink.offset if sink else None})")
            restored = cluster.restart(victim)
            _tune(restored)
            cluster.wait_voter(joiner.name, timeout=90.0)
        finally:
            if joiner is not None:
                joiner.raft._on_snapshot_chunk = orig
            chunk_override.close()

    def check(self, cluster, ctx, timeout: float = 60.0) -> dict:
        try:
            return check_convergence(cluster, ctx,
                                     timeout=max(timeout, 120.0))
        finally:
            if self._driver is not None:
                self._driver.stop_flag.set()

    def _quiesce(self, cluster, ctx):
        """Freeze the liveness plane before the join drill and the
        invariant audit: the batcher's steady-state stamps land
        continuously at fleet scale and would race both the identical-
        snapshot bootstrap and the battery's byte-identity captures.
        Stop the fleet driver, stretch every tracker's TTL past the
        audit, and run one final revival sweep so the whole fleet is
        ready with no further heartbeat writes due."""
        if self._driver is not None:
            self._driver.stop_flag.set()
            self._driver.join(5.0)
        for s in cluster.servers:
            s.config.heartbeat_ttl = 3600.0
            if s.heartbeats is not None:
                s.heartbeats.ttl = 3600.0
        for i in range(0, len(ctx.node_ids), 1000):
            _on_leader(cluster, lambda ld, c=ctx.node_ids[i:i + 1000]:
                       ld.node_heartbeats(c), timeout=30.0)
        # let the last revival batch flush before the quiet period
        time.sleep(0.3)


class _OverloadStats:
    """Shared flood ledger.  Every attempt ends in EXACTLY ONE bucket —
    the no-silent-drop gate is that ok + every refusal class + errors
    adds back up to attempts with nothing outstanding."""

    def __init__(self):
        self.lock = threading.Lock()
        self.attempts = 0
        self.ok_reads = 0               # reads served inside the budget
        self.accepted_jobs: List[str] = []
        self.shed_flood = 0             # ingress-flood chaos 503
        self.shed_admission = 0         # token bucket refusal
        self.shed_brownout = 0          # leader brownout refusal
        self.deadline_exceeded = 0      # honest 504
        self.transient = 0              # not_leader / churn window
        self.errors = 0                 # anything else (still resolved)
        self.outstanding = 0            # admitted, response pending
        self.lat_ms: List[float] = []   # successful-read latencies

    def resolved(self) -> int:
        return (self.ok_reads + len(self.accepted_jobs) + self.shed_flood
                + self.shed_admission + self.shed_brownout
                + self.deadline_exceeded + self.transient + self.errors)


class OverloadStormShape(Shape):
    """Overload drill for the deadline/admission/brownout plane: flood
    lanes offer >=10x the measured solo capacity against the leader's
    RPC surface through the SAME ingress sequence the HTTP tier runs
    (flood chaos -> per-namespace admission bucket -> deadline-stamped
    dispatch -> brownout/deadline checks inside handle), while the
    schedule churns servers, expires leases and stalls the applier
    underneath.  The cell gates:

        goodput_70pct       in-budget goodput during the storm stays
                            >= 70% of the measured solo capacity
        offered_10x         the storm window really offered >= 10x solo
        no_silent_drops     every attempt resolved explicitly (success,
                            503, 504, or a transport error) — nothing
                            admitted then silently dropped, and every
                            ACCEPTED Job.Register must fully place
                            (accepted jobs join ctx.exact_jobs, so the
                            convergence battery audits them)
        deadline_p99        successful-read p99 inside the request
                            budget
        leader_stable       a full-rate flood BEFORE chaos arms never
                            deposes the leader by itself (no election
                            from overload alone)
    """

    name = "overload_storm"
    READ_BUDGET_S = 1.0
    REGISTER_BUDGET_S = 10.0
    REGISTER_CAP = 32
    FLOOD_LANES = 6
    OFFERED_X = 12.0                    # paced offered load vs capacity
    # the cell's serve budget: raw in-process dispatch is GIL-fast (a
    # solo lane clears ~10^5 reads/s) but under the full cluster +
    # flood + churn an admitted op costs milliseconds of contended GIL,
    # so "capacity" is an admitted-rate budget the admission bucket
    # enforces and the lanes can actually pull through a storm — the
    # drill is the VALVES (shed 10x down to budget, honestly), not raw
    # dispatch speed
    CAPACITY_CAP = 500.0

    def __init__(self):
        self._stats = _OverloadStats()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._storm_t0 = 0.0
        self._storm_s = 0.0
        self._window = None
        self._solo_rate = 0.0
        self._seed = 0

    def amend_spec(self, spec: str) -> str:
        extra = []
        for ph in ("storm", "flap1", "flap2"):
            if f"phase={ph}:" in spec:
                extra += [f"overload.ingress_flood=0.05@{ph}",
                          f"overload.applier_stall=0.1@{ph}",
                          f"overload.deadline_skew=0.25@{ph}"]
        return spec + "".join(";" + e for e in extra)

    # ------------------------------------------------------ gate wiring

    def _arm(self, cluster):
        """Idempotent: re-applied every during() tick so servers the
        churn driver rebuilds get the cell's limits too."""
        rate = 1.2 * max(self._solo_rate, 50.0)
        for s in cluster.servers:
            adm = getattr(s, "admission", None)
            if adm is not None:
                adm.rate = rate
                adm.burst = max(1.0, rate / 2.0)
                adm.max_concurrency = 0
                adm.enabled = True
            bo = getattr(s, "brownout", None)
            if bo is not None:
                bo.depth_hi = 64
                bo.lag_hi = 128

    def _disarm(self, cluster):
        """Convergence runs unthrottled: admission off, brownout edges
        pushed out of reach."""
        for s in cluster.servers:
            adm = getattr(s, "admission", None)
            if adm is not None:
                adm.enabled = False
            bo = getattr(s, "brownout", None)
            if bo is not None:
                bo.depth_hi = 1 << 30
                bo.lag_hi = 1 << 30

    # ------------------------------------------------------- flood lane

    def _pump(self, cluster, stats: _OverloadStats, stop: threading.Event,
              rng: random.Random, target_rate: float, t0: float,
              register: bool):
        """One flood lane: the HTTP tier's ingress sequence (flood
        chaos, admission bucket, deadline stamp) in front of the real
        RPC dispatch."""
        leader = None
        while not stop.is_set():
            if leader is None:
                # short resolution slices keep the lane stop-responsive
                # and bound how long churn can stall the offered load
                try:
                    leader = cluster.leader(timeout=0.25)
                except TimeoutError:
                    stop.wait(0.05)
                    continue
            with stats.lock:
                stats.attempts += 1
            if chaos.active is not None and \
                    chaos.should("overload.ingress_flood"):
                with stats.lock:
                    stats.shed_flood += 1
                self._pace(stats, t0, target_rate, stop)
                continue
            adm = getattr(leader, "admission", None)
            if adm is not None and adm.enabled:
                retry = adm.try_acquire("default")
                if retry is not None:
                    with stats.lock:
                        stats.shed_admission += 1
                    self._pace(stats, t0, target_rate, stop)
                    continue
            do_register = register and rng.random() < 0.1 and \
                len(stats.accepted_jobs) < self.REGISTER_CAP
            if do_register:
                j = _batch_job(1, cpu=100, mem=64)
                method, args = "Job.Register", {
                    "job": j,
                    request_deadline.DEADLINE_KEY: self.REGISTER_BUDGET_S}
            else:
                method, args = "Job.List", {
                    "namespace": "default",
                    "consistency":
                        "stale" if rng.random() < 0.5 else "default",
                    request_deadline.DEADLINE_KEY: self.READ_BUDGET_S}
            t_op = time.monotonic()
            with stats.lock:
                stats.outstanding += 1
            try:
                leader.endpoints.handle(method, args)
            except RpcError as e:
                kind = getattr(e, "kind", "")
                with stats.lock:
                    stats.outstanding -= 1
                    if kind == "brownout":
                        stats.shed_brownout += 1
                    elif kind == "admission_denied":
                        stats.shed_admission += 1
                    elif kind == "deadline_exceeded":
                        stats.deadline_exceeded += 1
                    elif kind in ("not_leader", "no_leader"):
                        stats.transient += 1
                    else:
                        stats.errors += 1
                if kind in ("not_leader", "no_leader"):
                    leader = None
            except Exception:           # noqa: BLE001 — churn window
                with stats.lock:
                    stats.outstanding -= 1
                    stats.transient += 1
                leader = None
            else:
                ms = (time.monotonic() - t_op) * 1000.0
                with stats.lock:
                    stats.outstanding -= 1
                    if do_register:
                        stats.accepted_jobs.append(j.id)
                    else:
                        stats.ok_reads += 1
                        stats.lat_ms.append(ms)
            self._pace(stats, t0, target_rate, stop)

    @staticmethod
    def _pace(stats, t0, target_rate, stop):
        elapsed = max(1e-6, time.monotonic() - t0)
        with stats.lock:
            over = stats.attempts / elapsed > target_rate
        if over:
            stop.wait(0.002)

    def _flood(self, cluster, stats, stop, duration_s, register):
        t0 = time.monotonic()
        # registers ride a single dedicated lane: a registration stuck
        # behind a stalled applier burns its own (long) budget, and one
        # blocked lane must never sink the read lanes' offered rate
        threads = [threading.Thread(
            target=self._pump,
            args=(cluster, stats, stop, random.Random(self._seed ^ i),
                  self.OFFERED_X * max(self._solo_rate, 50.0), t0,
                  register and i == 0),
            name=f"overload-lane-{i}", daemon=True)
            for i in range(self.FLOOD_LANES)]
        for t in threads:
            t.start()
        if duration_s is not None:
            stop.wait(duration_s)
            stop.set()
            for t in threads:
                t.join(5.0)
        return threads, t0

    # ------------------------------------------------------------ shape

    def setup(self, cluster, rng, ctx):
        self._seed = rng.randrange(1 << 30)
        for _ in range(2):
            j = _batch_job(6)
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
            ctx.exact_jobs.append(j.id)
            _wait_live(cluster, ctx, j.id, 6)
        ctx.drain_candidates = list(ctx.node_ids)

        # solo capacity: one closed-loop lane, gates off, no chaos
        ld = cluster.leader(timeout=10.0)
        t0 = time.monotonic()
        n = 0
        while time.monotonic() - t0 < 0.5:
            ld.endpoints.handle("Job.List", {
                "namespace": "default",
                "consistency": "stale" if n % 2 else "default",
                request_deadline.DEADLINE_KEY: self.READ_BUDGET_S})
            n += 1
        raw = n / (time.monotonic() - t0)
        self._solo_rate = min(raw, self.CAPACITY_CAP)
        ctx.notes["solo_raw_per_s"] = round(raw, 1)
        ctx.notes["solo_per_s"] = round(self._solo_rate, 1)

        # leader-stability drill: a FULL-RATE flood with the gates
        # armed but chaos not yet installed must not depose the leader
        # by itself — overload alone is never an election
        self._arm(cluster)
        term0 = ld.raft.term
        burst = _OverloadStats()
        self._flood(cluster, burst, threading.Event(),
                    duration_s=0.8, register=False)
        ld2 = cluster.leader(timeout=5.0)
        ctx.notes["preflood_offered_per_s"] = round(
            burst.attempts / 0.8, 1)
        ctx.notes["leader_stable"] = bool(
            ld2 is ld and ld2.raft.term == term0)

    def during(self, cluster, rng, ctx, reg):
        self._arm(cluster)              # churn rebuilds servers bare
        if self._threads:
            # snapshot the ledger every tick: the LAST snapshot lands
            # within one tick of the chaos window closing, so the
            # offered/goodput gates measure the storm itself — not the
            # post-schedule recovery tail (churn restore can spend
            # seconds rebuilding servers while lanes wait on a leader)
            st = self._stats
            with st.lock:
                self._window = {
                    "s": max(1e-6, time.monotonic() - self._storm_t0),
                    "attempts": st.attempts,
                    "ok_reads": st.ok_reads,
                    "lat_ms": list(st.lat_ms),
                }
            return
        self._stats = _OverloadStats()
        self._stop = threading.Event()
        self._window = None
        self._threads, self._storm_t0 = self._flood(
            cluster, self._stats, self._stop,
            duration_s=None, register=True)

    def finish(self, cluster, ctx):
        stats = self._stats
        if self._threads:
            self._stop.set()
            # the offered window closes when stop is raised — measuring
            # after the joins would bill slow lane teardown (a register
            # draining its budget) to the storm denominator
            self._storm_s = max(1e-6,
                                time.monotonic() - self._storm_t0)
            # the join must outlast the LONGEST op budget a lane can be
            # inside (a register draining behind a recovering applier),
            # or a still-outstanding op reads as a silent drop
            for t in self._threads:
                t.join(self.REGISTER_BUDGET_S + 2.0)
            self._threads = []
        self._disarm(cluster)
        # every ACCEPTED registration must fully place: the battery
        # audits them like any other tracked job
        ctx.exact_jobs.extend(stats.accepted_jobs)
        # rate gates come from the last in-window snapshot; the final
        # totals (which include the drain tail) still feed the
        # silent-drop ledger below
        win = getattr(self, "_window", None) or {
            "s": self._storm_s, "attempts": stats.attempts,
            "ok_reads": stats.ok_reads, "lat_ms": stats.lat_ms}
        win_s = max(1e-6, win["s"])
        lat = sorted(win["lat_ms"])
        p99 = lat[int(0.99 * (len(lat) - 1))] if lat else 0.0
        ctx.notes.update({
            "storm_s": round(win_s, 2),
            "storm_offered_per_s": round(win["attempts"] / win_s, 1),
            "storm_goodput_per_s": round(win["ok_reads"] / win_s, 1),
            "accepted_jobs": len(stats.accepted_jobs),
            "shed_flood": stats.shed_flood,
            "shed_admission": stats.shed_admission,
            "shed_brownout": stats.shed_brownout,
            "deadline_exceeded": stats.deadline_exceeded,
            "transient": stats.transient,
            "errors": stats.errors,
            "read_p99_ms": round(p99, 2),
            "unresolved": stats.attempts - stats.resolved(),
            "outstanding_end": stats.outstanding,
        })

    def check(self, cluster, ctx, timeout: float = 60.0) -> dict:
        self._disarm(cluster)           # belt and braces
        res = check_convergence(cluster, ctx, timeout=timeout)
        notes = ctx.notes
        solo = max(1e-6, float(notes.get("solo_per_s", 0.0)))
        inv = res["invariants"]
        inv["goodput_70pct"] = {
            "ok": notes["storm_goodput_per_s"] >= 0.7 * solo,
            "detail": (f"goodput={notes['storm_goodput_per_s']}/s "
                       f"solo={notes['solo_per_s']}/s")}
        inv["offered_10x"] = {
            "ok": notes["storm_offered_per_s"] >= 10.0 * solo,
            "detail": (f"offered={notes['storm_offered_per_s']}/s "
                       f"solo={notes['solo_per_s']}/s")}
        inv["no_silent_drops"] = {
            "ok": notes["unresolved"] == 0
            and notes["outstanding_end"] == 0,
            "detail": (f"unresolved={notes['unresolved']} "
                       f"outstanding={notes['outstanding_end']}")}
        inv["deadline_p99"] = {
            "ok": notes["read_p99_ms"] <= self.READ_BUDGET_S * 1000.0,
            "detail": f"read_p99={notes['read_p99_ms']}ms "
                      f"budget={self.READ_BUDGET_S * 1000.0:.0f}ms"}
        inv["leader_stable"] = {
            "ok": bool(notes.get("leader_stable")),
            "detail": "pre-chaos full flood kept the leader"}
        res["converged"] = bool(res["converged"]) and \
            all(v["ok"] for v in inv.values())
        return res


class DivergenceDrillShape(Shape):
    """Replica-divergence drill for the integrity plane: mid-storm, one
    SEEDED-random non-leader replica is silently corrupted through a
    targeted chaos point (`fsm.apply_skip` drops one applied entry on
    that replica only; `store.bitflip` flips state bytes underneath the
    FSM with no dirty mark), while batch work keeps committing so the
    corruption is real divergence, not a no-op.  The cell gates the full
    detect -> quarantine -> repair -> re-admit story:

        injected            the targeted point actually fired inside the
                            chaos window (applies trickle, so a pending
                            target that outlives its victim is re-armed)
        detected_fast       the leader's majority vote convicted the
                            corrupted replica within DETECT_BOUND_S of
                            the corruption landing (interval=0.25s and
                            full_every=1 here, so every checkpoint is
                            ground truth)
        quarantined         the convicted replica self-quarantined
        no_wrong_reads      zero stale reads served by the replica while
                            quarantined — every probe was refused with
                            the `quarantined` hint
        repaired_readmitted the replica came back: quarantine cleared
                            only through digest-verified re-admission
                            (or a WAL-replay restart / server_replace
                            genuinely rebuilt it), and no live leader
                            still holds a conviction
        quorum_available    the surviving majority kept serving reads
                            after detection

    Byte-identical repair is then proven by the battery's own
    `fsm_identical` invariant — the same canonical encoding the runtime
    digests vote over.  Under the `storm` schedule the churn driver can
    hard-kill the victim before conviction lands; a WAL-replay restart
    legitimately heals the in-memory corruption, so the drill re-injects
    (bounded) until a conviction sticks inside the window."""

    name = "divergence_drill"
    n_nodes = 6
    POINTS = ("fsm.apply_skip", "store.bitflip")
    INJECT_AT_S = 1.2                   # mid-storm (both phases open)
    REINJECT_AFTER_S = 1.2              # fired but healed (restart) / lost
    MAX_INJECTIONS = 6
    # Detection-latency gate.  On a quiet cluster conviction lands
    # within ~one 0.25s checkpoint interval (tests/test_integrity.py
    # proves that case); here the victim can fire mid-partition and
    # stay unreachable until the chaos window closes (~2.6s after the
    # earliest injection), with conviction on the first checkpoints
    # after heal — the gate proves detection is prompt once the replica
    # is reachable, not that storms cannot delay gossip
    DETECT_BOUND_S = 5.0

    def tune_config(self, cfg: ServerConfig) -> None:
        # tight checkpoint cadence, and EVERY checkpoint full-walks:
        # silent corruption marks nothing dirty, so only the full walk
        # (ground truth) can convict it
        cfg.integrity_interval = 0.25
        cfg.integrity_full_every = 1

    def setup(self, cluster, rng, ctx):
        self._rng = rng                 # for the finish-phase fallback
        self._injected = None           # (point, victim_name)
        self._armed_raft_id = None
        self._injections = 0
        self._armed_at = 0.0
        self._fired_at = None
        self._detected_at = None
        self._quarantine_seen = False
        self._quarantine_cleared = False
        self._refused_reads = 0
        self._wrong_reads = 0
        self._quorum_reads_ok = 0
        self._extra_registered = False
        for _ in range(2):
            j = _batch_job(5)
            _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
            ctx.exact_jobs.append(j.id)
            _wait_live(cluster, ctx, j.id, 5)

    # ------------------------------------------------------- injection

    @staticmethod
    def _server(cluster, name):
        for s in cluster.servers:
            if s.name == name:
                return s
        return None

    def _pick_victim(self, cluster, rng):
        try:
            ld = cluster.leader(timeout=2.0)
        except TimeoutError:
            return None
        followers = [s for s in cluster.servers
                     if s is not ld and s.raft is not None]
        if not followers:
            return None
        return followers[rng.randrange(len(followers))]

    def _inject(self, cluster, rng, reg, ctx):
        victim = self._pick_victim(cluster, rng)
        if victim is None:
            return False
        if self._injected is not None:
            # disarm the previous target first: if its victim comes
            # back (storm restarts keep names) a second silent
            # corruption could diverge TWO followers at once and rob
            # the digest vote of any quorum
            old_point, old_name = self._injected
            try:
                reg.target(old_point, old_name, count=0)
            except Exception:           # noqa: BLE001
                pass
        point = self.POINTS[rng.randrange(len(self.POINTS))]
        try:
            reg.target(point, victim.raft.name)
        except Exception:               # noqa: BLE001 — victim died
            return False
        self._injected = (point, victim.raft.name)
        self._armed_raft_id = id(victim.raft)
        self._injections += 1
        self._armed_at = time.time()
        self._fired_at = None
        ctx.notes.setdefault("injections", []).append(
            {"point": point, "victim": victim.raft.name,
             "at_s": round(reg.elapsed() or 0.0, 2)})
        # pump fresh non-exempt applies through the log so the armed
        # point fires promptly — without them the victim may see
        # nothing but exempt entries (noops, checkpoints) until chaos
        # uninstalls.  Fingerprint deltas ride the batched write path
        # and mutate no alloc state, so they cannot skew placement
        # invariants the way an extra job would.
        for nid in ctx.node_ids[:3]:
            try:
                _on_leader(cluster, lambda ld, nid=nid:
                           ld.endpoints.handle(
                               "Node.UpdateFingerprint",
                               {"node_id": nid, "attributes": {
                                   "drill.pump":
                                   str(self._injections)}}))
            except Exception:           # noqa: BLE001 — election gap
                pass
        return True

    # --------------------------------------------------------- probing

    @staticmethod
    def _no_live_divergence(cluster) -> bool:
        """True only when every replica's newest checkpoint digest
        agrees with every replica that checkpointed at the same index.
        The raft-identity heal check alone is not enough before
        re-injecting: a store.bitflip that got folded into a snapshot
        SURVIVES the victim's restart, and corrupting one more replica
        on top would 3-way-split the vote with no convictable
        majority."""
        by_idx: Dict[int, set] = {}
        for s in cluster.servers:
            raft = getattr(s, "raft", None)
            if raft is None:
                continue
            last = raft.integrity.last
            if last is None:
                continue
            by_idx.setdefault(last["index"], set()).add(last["digest"])
        if not by_idx:
            return False
        return all(len(ds) == 1 for ds in by_idx.values())

    def _victim_tracker(self, cluster):
        _, name = self._injected
        srv = self._server(cluster, name)
        if srv is None or srv.raft is None:
            return None
        return srv.raft.integrity

    def _poll(self, cluster):
        """One observation pass: did the armed point fire, did the vote
        convict, is the quarantined victim refusing its local reads, is
        the healthy majority still serving."""
        point, name = self._injected
        now = time.time()
        # conviction, from whoever currently leads
        try:
            ld = cluster.leader(timeout=0.5)
            if ld.raft.integrity.peer_divergent(name) \
                    and self._detected_at is None:
                self._detected_at = now
        except Exception:               # noqa: BLE001 — election gap
            ld = None
        # durable evidence: conviction + repair counters survive on the
        # convicting server even when the victim's own tracker was
        # rebuilt with fresh counters by a churn restart
        for s in cluster.servers:
            try:
                cnt = s.raft.integrity.counters
            except Exception:           # noqa: BLE001 — churned member
                continue
            if cnt["repairs_started"] and self._detected_at is None:
                self._detected_at = now
            if cnt["repairs_verified"]:
                # a digest-verified repair implies the victim held its
                # quarantine through the install (the repair path
                # self-quarantines before the wipe)
                self._quarantine_seen = True
        tracker = self._victim_tracker(cluster)
        if tracker is not None and (
                tracker.quarantined or tracker.counters["quarantines"]):
            # counter, not just the flag: a quiet-cluster repair can
            # open and close the quarantine inside one poll interval
            self._quarantine_seen = True
            if self._detected_at is None:
                self._detected_at = now
        if self._quarantine_seen and tracker is not None \
                and not tracker.quarantined:
            self._quarantine_cleared = True
        # wrong-read probe: only reads served while the flag is up (on
        # both sides of the call) count against the zero-wrong-reads gate
        if tracker is not None and tracker.quarantined:
            srv = self._server(cluster, name)
            try:
                srv.read("Job.List", {}, consistency="stale", timeout=0.5)
                if tracker.quarantined:
                    self._wrong_reads += 1
            except RpcError as e:
                if e.kind == "quarantined":
                    self._refused_reads += 1
            except Exception:           # noqa: BLE001 — victim churned
                pass
        if self._detected_at is not None and ld is not None:
            try:
                ld.read("Job.List", {}, consistency="default", timeout=0.5)
                self._quorum_reads_ok += 1
            except Exception:           # noqa: BLE001 — chaos
                pass

    def during(self, cluster, rng, ctx, reg):
        now = reg.elapsed() or 0.0
        if not self._extra_registered and reg.phase_now():
            # keep non-exempt applies flowing so fsm.apply_skip has
            # entries to drop and the post-skip divergence is real
            # state; at-most tracking — a storm can legitimately strand
            # a mid-window registration's eval, and these jobs are
            # divergence fodder, not placement subjects
            self._extra_registered = True
            for _ in range(2):
                j = _batch_job(3)
                _on_leader(cluster, lambda ld, j=j: ld.register_job(j))
                ctx.at_most_jobs.append(j.id)
        if self._injected is None:
            if reg.phase_now() and now >= self.INJECT_AT_S:
                self._inject(cluster, rng, reg, ctx)
            return
        point, name = self._injected
        pending = reg.pending_target(point, name)
        if pending:
            # armed but unconsumed: if the victim was destroyed
            # (server_replace) the target can never fire, and a victim
            # stuck behind a storm partition may apply nothing for the
            # rest of the window — re-arm on a live follower, but only
            # while an injection phase is still open so the fresh
            # target has real runway before chaos uninstalls
            srv = self._server(cluster, name)
            stuck = time.time() - self._armed_at > self.REINJECT_AFTER_S
            if (srv is None or srv.raft is None or stuck) \
                    and reg.phase_now() \
                    and self._injections < self.MAX_INJECTIONS:
                self._inject(cluster, rng, reg, ctx)
            return
        if self._fired_at is None:
            self._fired_at = time.time()
        self._poll(cluster)
        # fired but never convicted, AND the victim restarted since the
        # fire (its WAL replay rebuilt the skipped entry, silently
        # healing the corruption): inject again (phase open = runway)
        # so a conviction lands inside the window.  While the original
        # raft instance still lives its corruption is live too — a
        # second corruption elsewhere would 3-way-split the digest vote
        # and leave NO majority to convict anyone — so there we only
        # wait for the (possibly storm-delayed) conviction.
        srv = self._server(cluster, name)
        healed = (srv is None or srv.raft is None
                  or id(srv.raft) != self._armed_raft_id)
        if self._detected_at is None and healed \
                and time.time() - self._fired_at > self.REINJECT_AFTER_S \
                and reg.phase_now() \
                and self._no_live_divergence(cluster) \
                and self._injections < self.MAX_INJECTIONS:
            self._inject(cluster, rng, reg, ctx)

    def finish(self, cluster, ctx):
        # chaos is uninstalled, but detection/quarantine/repair are not
        # chaos-gated: keep observing until the conviction resolves
        if self._injected is not None:
            _wait(lambda: (self._poll(cluster) or
                           self._detected_at is not None), 8.0, 0.05)
        if self._detected_at is None and self._fallback_safe(cluster):
            # every mid-window corruption was healed before a vote could
            # convict it (a churn restart's WAL replay legitimately
            # rebuilds the skipped entry): re-run the injection on the
            # now-quiet cluster through a private registry so the
            # detect -> quarantine -> repair story is exercised every
            # run, not just on seeds where the corruption outlives the
            # storm
            reg = ChaosRegistry.from_spec(
                f"seed={self._rng.randrange(1 << 30)}")
            prev = chaos.install(reg)
            reg.arm()
            try:
                for _ in range(3):      # victim pick can race an election
                    if not self._inject(cluster, self._rng, reg, ctx):
                        time.sleep(0.5)
                        continue
                    point, name = self._injected

                    def _observe():
                        if self._fired_at is None \
                                and not reg.pending_target(point, name):
                            self._fired_at = time.time()
                        self._poll(cluster)
                        return self._detected_at is not None
                    _wait(_observe, 10.0, 0.05)
                    break
            finally:
                chaos.install(prev)
        if self._injected is not None:
            _wait(lambda: (self._poll(cluster) or
                           self._resolved(cluster)), 20.0, 0.05)
        ctx.notes["integrity_drill"] = self._notes(cluster)

    def _fallback_safe(self, cluster) -> bool:
        """A second corruption is only safe when the first one cannot
        still be live — never fired, or the victim's raft instance was
        rebuilt since the fire (replay healed it).  Corrupting a second
        replica while the first is still divergent would 3-way-split
        the digest vote and strand the cluster with no convictable
        majority."""
        if self._injected is None:
            return True
        if self._fired_at is None:
            return True
        _, name = self._injected
        srv = self._server(cluster, name)
        healed = (srv is None or srv.raft is None
                  or id(srv.raft) != self._armed_raft_id)
        return healed and self._no_live_divergence(cluster)

    def _resolved(self, cluster) -> bool:
        """The divergence is over: nobody is quarantined and no live
        leader still holds a conviction against the victim."""
        if self._injected is None:
            return True
        _, name = self._injected
        tracker = self._victim_tracker(cluster)
        if tracker is not None and tracker.quarantined:
            return False
        try:
            ld = cluster.leader(timeout=1.0)
            return not ld.raft.integrity.peer_divergent(name)
        except Exception:               # noqa: BLE001
            return False

    def _notes(self, cluster) -> dict:
        repairs = 0
        for s in cluster.servers:
            try:
                repairs += s.raft.integrity.counters["repairs_verified"]
            except Exception:           # noqa: BLE001 — churned member
                pass
        latency = None
        if self._detected_at is not None and self._fired_at is not None:
            latency = round(self._detected_at - self._fired_at, 3)
        return {
            "injections": self._injections,
            "fired": self._fired_at is not None,
            "detect_latency_s": latency,
            "quarantine_seen": self._quarantine_seen,
            "quarantine_cleared": self._quarantine_cleared,
            "refused_reads": self._refused_reads,
            "wrong_reads": self._wrong_reads,
            "quorum_reads_ok": self._quorum_reads_ok,
            "repairs_verified": repairs,
        }

    def check(self, cluster, ctx, timeout: float = 60.0) -> dict:
        res = check_convergence(cluster, ctx, timeout=timeout)
        # the conviction can outlive finish() on a loaded box: a
        # re-elected leader self-heals its stale conviction only after
        # the next checkpoint round-trip — give it a real window rather
        # than judging one instantaneous snapshot
        if self._injected is not None and not self._resolved(cluster):
            _wait(lambda: (self._poll(cluster) or
                           self._resolved(cluster)), 15.0, 0.1)
        d = self._notes(cluster)
        ctx.notes["integrity_drill"] = d
        inv = res["invariants"]
        inv["injected"] = {
            "ok": d["fired"],
            "detail": f"injections={d['injections']} fired={d['fired']}"}
        inv["detected_fast"] = {
            "ok": d["detect_latency_s"] is not None
            and d["detect_latency_s"] <= self.DETECT_BOUND_S,
            "detail": f"latency={d['detect_latency_s']}s "
                      f"bound={self.DETECT_BOUND_S}s"}
        inv["quarantined"] = {
            "ok": d["quarantine_seen"],
            "detail": "victim self-quarantined" if d["quarantine_seen"]
            else "conviction never reached the victim"}
        inv["no_wrong_reads"] = {
            "ok": d["wrong_reads"] == 0,
            "detail": (f"refused={d['refused_reads']} "
                       f"wrong={d['wrong_reads']}")}
        inv["repaired_readmitted"] = {
            "ok": self._resolved(cluster),
            "detail": (f"repairs_verified={d['repairs_verified']} "
                       f"cleared={d['quarantine_cleared']}")}
        inv["quorum_available"] = {
            "ok": d["detect_latency_s"] is None
            or d["quorum_reads_ok"] > 0,
            "detail": f"quorum_reads_ok={d['quorum_reads_ok']}"}
        res["converged"] = bool(res["converged"]) and \
            all(v["ok"] for v in inv.values())
        return res


SHAPES: Dict[str, Callable[[], Shape]] = {
    "e2e_spine": E2ESpineShape,
    "scan_spread": ScanSpreadShape,
    "device_constrained": DeviceConstrainedShape,
    "preemption_heavy": PreemptionHeavyShape,
    "serving_plane": ServingPlaneShape,
    "rolling_deploy": RollingDeployShape,
    "autoscale_ramp": AutoscaleRampShape,
    "multi_tenant": MultiTenantShape,
    "multi_region": MultiRegionShape,
    "fleet_soak": FleetSoakShape,
    "overload_storm": OverloadStormShape,
    "divergence_drill": DivergenceDrillShape,
}


# ----------------------------------------------------------- invariants


def _open_evals(ld, ctx):
    out = []
    for e in ld.store.evals():
        if EvalStatus.terminal(e.status):
            continue
        if ctx.allow_blocked and e.status == EvalStatus.BLOCKED:
            continue
        out.append(e)
    return out


def _alloc_problems(ld, ctx) -> List[str]:
    problems = []
    nodes = {n.id: n for n in ld.store.nodes()}
    for job_id in ctx.tracked_jobs():
        exact = job_id in ctx.exact_jobs
        job_namespace = ctx.ns_of(job_id)
        job = ld.store.job_by_id(job_namespace, job_id)
        if job is None:
            problems.append(f"{job_id}: job vanished")
            continue
        live = _live(ld.store.allocs_by_job(job_namespace, job_id))
        for tg in job.task_groups:
            glive = [a for a in live if a.task_group == tg.name]
            names = [a.name for a in glive]
            if len(set(names)) != len(names):
                dupes = sorted({n for n in names if names.count(n) > 1})
                problems.append(
                    f"{job_id}/{tg.name}: duplicate live allocs {dupes}")
            if exact and len(glive) != tg.count:
                problems.append(
                    f"{job_id}/{tg.name}: live {len(glive)} != "
                    f"count {tg.count}")
            if not exact and len(glive) > tg.count:
                problems.append(
                    f"{job_id}/{tg.name}: live {len(glive)} > "
                    f"count {tg.count} (orphans)")
            for a in glive:
                node = nodes.get(a.node_id)
                if node is None:
                    problems.append(
                        f"{job_id}/{tg.name}: alloc {a.id[:8]} on "
                        f"missing node {a.node_id[:8]}")
                elif node.status != "ready":
                    problems.append(
                        f"{job_id}/{tg.name}: alloc {a.id[:8]} on "
                        f"{node.status} node {a.node_id[:8]}")
    return problems


def _deployment_problems(ld, ctx) -> List[str]:
    problems = []
    for d in ld.store.deployments():
        if d.active():
            problems.append(f"deployment {d.id[:8]} still "
                            f"{d.status} (job {d.job_id})")
            continue
        if d.status == DeploymentStatus.FAILED and any(
                s.auto_revert for s in d.task_groups.values()):
            job = ld.store.job_by_id(d.namespace, d.job_id)
            if job is not None and not job.stop \
                    and job.version <= d.job_version:
                problems.append(
                    f"deployment {d.id[:8]} FAILED with auto_revert but "
                    f"job {d.job_id} still at version {job.version}")
    return problems


def _drain_problems(ld, ctx) -> List[str]:
    problems = []
    for nid in ctx.drained:
        node = ld.store.node_by_id(nid)
        if node is None:
            continue                    # gc'd: trivially empty
        if node.drain_strategy is not None:
            problems.append(f"drained node {nid[:8]} still has a "
                            f"drain strategy")
        stuck = _live(ld.store.allocs_by_node(nid))
        if stuck:
            problems.append(f"drained node {nid[:8]} still holds "
                            f"{len(stuck)} live allocs")
    return problems


def _quick_converged(cluster, ctx) -> bool:
    try:
        ld = cluster.leader(timeout=2.0)
    except TimeoutError:
        return False
    if _open_evals(ld, ctx):
        return False
    with ld.broker._lock:
        leases = len(ld.broker._unack)
    if leases or ld.broker.ready_count() or ld.plan_queue._heap:
        return False
    if _alloc_problems(ld, ctx):
        return False
    if any(d.active() for d in ld.store.deployments()):
        return False
    if _drain_problems(ld, ctx):
        return False
    return True


def check_convergence(cluster: Cluster, ctx: CellCtx,
                      timeout: float = 60.0) -> dict:
    """Wait for post-chaos convergence, then run the full invariant
    battery and report per-invariant verdicts.  The battery retries a
    few times before declaring failure: a node reviving mid-battery
    kicks off node-update evals and legal transient states (an old
    alloc still draining next to its replacement), which settle within
    seconds — a genuine violation (duplicate live names, an orphaned
    deployment, a stuck eval) persists across every retry."""
    t0 = time.time()
    converged = _wait(lambda: _quick_converged(cluster, ctx),
                      timeout=timeout, interval=0.1)
    conv_time = time.time() - t0

    last = None
    for attempt in range(3):
        if attempt:
            time.sleep(5.0)
            converged = _wait(lambda: _quick_converged(cluster, ctx),
                              timeout=15.0, interval=0.1)
        last = _invariant_battery(cluster, ctx, converged, conv_time)
        if last["converged"]:
            return last
    return last


def _invariant_battery(cluster: Cluster, ctx: CellCtx,
                       converged: bool, conv_time: float) -> dict:
    ld = cluster.leader(timeout=10.0)
    invariants: Dict[str, dict] = {}

    open_evals = _open_evals(ld, ctx)
    ev_detail = [f"{e.id[:8]}({e.status}:{e.triggered_by})"
                 for e in open_evals[:8]]
    with ld.broker._lock:
        leases = len(ld.broker._unack)
    queued = len(ld.plan_queue._heap)
    invariants["evals_drained"] = {
        "ok": not open_evals and not leases and not queued,
        "detail": (f"open={ev_detail} leases={leases} plans={queued}"
                   if (open_evals or leases or queued) else "clean"),
    }

    probs = _alloc_problems(ld, ctx)
    invariants["allocs_consistent"] = {
        "ok": not probs, "detail": probs[:8] or "clean"}

    # identical FSM state across every member (survivors and restarted
    # crashers) once all have applied through the leader's index
    fsm_detail = "clean"
    fsm_ok = False
    try:
        ld.raft.barrier()
        if not cluster.wait_replication(ld.store.latest_index,
                                        timeout=15.0):
            fsm_detail = "replication did not catch up"
        else:
            # background writers (keeper heartbeats re-registering a
            # late-recovering node, the eval reapers) can commit an entry
            # between two members' snapshots — only an equal-index
            # quiescent mismatch is real divergence, so retry the compare
            # until the applied index holds still across one pass
            for _ in range(12):
                idx0 = ld.raft.last_applied
                if not _wait(lambda: all(
                        s.raft is not None
                        and s.raft.last_applied >= idx0
                        for s in cluster.servers), 15.0):
                    fsm_detail = "apply lag did not catch up"
                    break
                blobs = {s.name: _canon(s.raft.fsm.snapshot())
                         for s in cluster.servers}
                ref = blobs[ld.name]
                diverged = [name for name, blob in blobs.items()
                            if blob != ref]
                if not diverged:
                    fsm_ok = True
                    fsm_detail = "clean"
                    break
                tables = sorted({k for name in diverged
                                 for k in (set(blobs[name]) | set(ref))
                                 if blobs[name].get(k) != ref.get(k)})
                if all(s.raft.last_applied == idx0
                       for s in cluster.servers) \
                        and ld.raft.last_applied == idx0:
                    fsm_detail = (f"diverged members (quiescent): "
                                  f"{diverged} tables={tables}")
                    break
                fsm_detail = (f"diverged members (index moving): "
                              f"{diverged} tables={tables}")
                time.sleep(0.25)
    except Exception as e:              # noqa: BLE001
        fsm_detail = f"snapshot compare failed: {e!r}"
    invariants["fsm_identical"] = {"ok": fsm_ok, "detail": fsm_detail}

    probs = _deployment_problems(ld, ctx)
    invariants["deployments_settled"] = {
        "ok": not probs, "detail": probs[:8] or "clean"}

    probs = _drain_problems(ld, ctx)
    invariants["drained_nodes_empty"] = {
        "ok": not probs, "detail": probs[:8] or "clean"}

    all_ok = converged and all(v["ok"] for v in invariants.values())
    return {"converged": bool(converged and all_ok),
            "convergence_time_s": round(conv_time, 2),
            "invariants": invariants}


# --------------------------------------------------------------- runner


def _plan_submit_sample() -> dict:
    from nomad_tpu.telemetry import global_metrics
    m = global_metrics.take_sample("nomad.plan.submit")
    return {"p50": round(m["p50"], 2), "p99": round(m["p99"], 2),
            "count": m["count"]}


def run_cell(shape_name: str, schedule_name: str, seed: int = 1,
             out_dir: str = ".", spec_override: Optional[str] = None,
             converge_timeout: float = 60.0) -> dict:
    """Run one matrix cell and write its trajectory JSON.  Returns the
    trajectory dict; result["convergence"]["converged"] is the verdict."""
    shape = SHAPES[shape_name]()
    if spec_override is not None:
        spec = spec_override
        sched = Schedule(name=schedule_name, spec=spec_override,
                         duration_s=4.0, server_churn=False)
    else:
        sched = SCHEDULES[schedule_name]
        spec = shape.amend_spec(sched.spec.format(seed=seed))
    reg = ChaosRegistry.from_spec(spec)
    # crc32, not hash(): PYTHONHASHSEED randomizes hash() per process
    # and the cell rng must reproduce for a given --seed
    rng = random.Random(
        (seed << 20) ^ zlib.crc32(f"{shape_name}:{sched.name}".encode()))
    data_dir = tempfile.mkdtemp(prefix=f"matrix-{shape_name}-")
    cfg = ServerConfig(num_schedulers=2, heartbeat_ttl=1.5,
                       gc_interval=3600.0,
                       failed_eval_followup_delay=0.3)
    shape.tune_config(cfg)
    cluster = shape.make_cluster(
        cfg, RaftConfig(heartbeat_interval=0.02, election_timeout=0.1),
        data_dir)
    for s in cluster.servers:
        _tune(s)
    ctx = CellCtx()
    keeper = health = None
    churn = None
    t_cell = time.time()
    try:
        cluster.start()
        cluster.leader(timeout=15.0)

        nodes = shape.make_nodes(rng)
        for n in nodes:
            _on_leader(cluster, lambda ld, n=n: ld.register_node(n))
        ctx.node_ids = [n.id for n in nodes]
        keeper = NodeKeeper(cluster, ctx.node_ids)
        keeper.start()

        shape.setup(cluster, rng, ctx)
        health = HealthReporter(cluster, ctx)
        health.start()

        base_allocs = _on_leader(
            cluster, lambda ld: len(ld.store.allocs()))
        _plan_submit_sample()           # reset the series for this cell

        # ---- chaos window
        chaos.install(reg)
        reg.arm()
        if sched.server_churn:
            churn = ChurnDriver(cluster, reg, rng)
        replace = ReplaceDriver(cluster, reg, ctx) \
            if sched.server_replace else None
        try:
            while (reg.elapsed() or 0.0) < sched.duration_s:
                try:
                    shape.during(cluster, rng, ctx, reg)
                except TRANSIENT_ERRORS + (TimeoutError,):
                    pass
                if churn is not None:
                    churn.tick()
                if replace is not None:
                    replace.tick()
                # one mid-window drain with a deadline that expires
                # while chaos is still biting
                if ctx.drain_candidates and not ctx.drained \
                        and (reg.elapsed() or 0.0) \
                        > sched.duration_s * 0.35:
                    nid = ctx.drain_candidates[
                        rng.randrange(len(ctx.drain_candidates))]
                    try:
                        _on_leader(cluster,
                                   lambda ld: ld.drainer.drain_node(
                                       nid, deadline_s=1.0), timeout=5.0)
                        ctx.drained.append(nid)
                    except TRANSIENT_ERRORS + (TimeoutError,):
                        pass
                time.sleep(0.05)
        finally:
            chaos.uninstall()
            if churn is not None:
                churn.restore()
        chaos_dt = reg.elapsed() or sched.duration_s

        if replace is not None:
            replace.finish()
        shape.finish(cluster, ctx)
        convergence = shape.check(cluster, ctx, timeout=converge_timeout)
        placed = _on_leader(
            cluster, lambda ld: len(ld.store.allocs())) - base_allocs
        plan = _plan_submit_sample()

        traj = {
            "metric": f"matrix_{shape_name}_{sched.name}",
            "shape": shape_name,
            "schedule": sched.name,
            "seed": seed,
            "chaos_spec": spec,
            "chaos_fired": dict(reg.stats),
            "chaos_window_s": round(chaos_dt, 2),
            "allocs_placed": placed,
            "allocs_per_sec": round(placed / chaos_dt, 1)
            if chaos_dt else 0.0,
            "plan_submit_ms": plan,
            "server_churn": churn.events() if churn else {},
            "drained_nodes": len(ctx.drained),
            "convergence": convergence,
            "notes": ctx.notes,
            "wall_s": round(time.time() - t_cell, 1),
        }
        out_path = os.path.join(
            out_dir, f"BENCH_matrix_{shape_name}_{sched.name}.json")
        with open(out_path, "w") as f:
            json.dump(traj, f, indent=1, default=str)
        return traj
    finally:
        if keeper is not None:
            keeper.stop_flag.set()
        if health is not None:
            health.stop_flag.set()
        chaos.uninstall()
        cluster.stop()
        if keeper is not None:
            keeper.join(2.0)
        if health is not None:
            health.join(2.0)
        shutil.rmtree(data_dir, ignore_errors=True)


# curated subset that rides `bench.py --matrix --smoke` and the CI
# scenario-matrix leg: one cell per headline behavior, including both
# first-class new scenarios
SMOKE_CELLS = [
    ("e2e_spine", "storm"),
    ("scan_spread", "lease_flap"),
    ("rolling_deploy", "storm"),
    ("autoscale_ramp", "lease_flap"),
    ("e2e_spine", "server_replace"),
    ("multi_region", "region_partition"),
    ("overload_storm", "storm"),
    ("divergence_drill", "storm"),
]

# the core product crosses every single-cluster shape with every
# single-cluster schedule; the federated shape rides only its two
# first-class cells (storm churn across both regions, and the
# deterministic WAN-cut drill) — region_partition makes no sense for a
# one-region cluster and lease_flap/server_replace add nothing the
# single-cluster cells don't already cover; the divergence drill rides
# storm (churn can heal the victim, exercising re-injection) and
# server_replace (repair racing membership change)
ALL_CELLS = [(shape, schedule)
             for shape in SHAPES
             if shape not in ("multi_region", "multi_tenant", "fleet_soak",
                              "overload_storm", "divergence_drill")
             for schedule in SCHEDULES if schedule != "region_partition"] \
    + [("multi_region", "storm"), ("multi_region", "region_partition")] \
    + [("multi_tenant", "storm"), ("multi_tenant", "lease_flap")] \
    + [("overload_storm", "storm"), ("overload_storm", "lease_flap")] \
    + [("divergence_drill", "storm"), ("divergence_drill", "server_replace")]

# the 10K-agent fleet cells are their own tier (minutes per cell at
# full size): `bench.py --fleet-soak` runs them, the CI fleet-soak leg
# runs them at a reduced NOMAD_TPU_FLEET_AGENTS, and lease_flap adds
# nothing over storm for a shape whose whole point is churn + snapshot
# streams
FLEET_CELLS = [
    ("fleet_soak", "storm"),
    ("fleet_soak", "server_replace"),
]


def run_matrix(cells=None, seed: int = 1, out_dir: str = ".",
               log=print) -> dict:
    """Run a list of (shape, schedule) cells; returns a summary with
    per-cell verdicts.  Honors a NOMAD_TPU_CHAOS env spec as a schedule
    override for every cell (schedule name 'env')."""
    cells = list(cells if cells is not None else ALL_CELLS)
    spec_override = knobs.get_str("NOMAD_TPU_CHAOS") or None
    if spec_override:
        chaos.uninstall()               # the runner installs per cell
        cells = [(shape, "env")
                 for shape in dict.fromkeys(s for s, _ in cells)]
    results = []
    failed = []
    for shape_name, schedule_name in cells:
        log(f"matrix cell {shape_name} x {schedule_name} (seed {seed})")
        try:
            traj = run_cell(shape_name, schedule_name, seed=seed,
                            out_dir=out_dir,
                            spec_override=spec_override)
        except Exception as e:          # noqa: BLE001
            log(f"  CELL ERROR: {e!r}")
            traj = {"shape": shape_name, "schedule": schedule_name,
                    "seed": seed, "error": repr(e),
                    "convergence": {"converged": False,
                                    "invariants": {}}}
        results.append(traj)
        conv = traj["convergence"]
        bad = [k for k, v in conv.get("invariants", {}).items()
               if not v["ok"]]
        if not conv.get("converged"):
            failed.append((shape_name, schedule_name, bad
                           or ["no convergence"]))
            log(f"  FAILED: {bad or 'did not converge'}")
        else:
            log(f"  converged in {conv['convergence_time_s']}s, "
                f"fired={traj.get('chaos_fired')}")
    return {"cells": results, "passed": len(results) - len(failed),
            "failed": [{"shape": s, "schedule": c, "invariants": b}
                       for s, c, b in failed],
            "ok": not failed}
