"""wait-graph: lock-acquisition cycles and locks held across blocking
calls, statically.

The runtime LockOrderRecorder (lock_order.py) only sees interleavings
the test run actually hit; this checker builds the acquisition graph
from source.  Nodes are lock *allocation sites* (`file.py:line`, the
recorder's own naming — common.lock_alloc_sites), so a runtime corpus
dumped by `LockOrderRecorder.dump()` / `NOMAD_TPU_LOCK_ORDER=1` merges
edge-for-edge into the static graph and one corpus feeds both tools
(`python -m nomad_tpu.analysis --lock-corpus <dump.json>`).

Static edges come from `with <lock>:` nesting — directly nested with
statements, plus interprocedurally: a call made while holding L adds
L -> M for every lock M acquired anywhere in the callee's cone.
Receivers resolve through the enclosing class, attr-typed fields
(`self.store._lock`), annotated parameters, and local aliases
(`s = self.store`); calls resolve receiver-aware
(common.resolve_call_targets), since here a spurious edge manufactures
a deadlock report.  Unresolvable lock expressions are skipped: the
graph under-approximates, the cycle report never invents locks.

Findings:

  cycle          a directed cycle in the merged static+runtime graph —
                 two paths nest the same locks in opposite orders
                 (potential deadlock)
  held-blocking  a blocking call (fsync, socket send/recv/accept/
                 connect, future .result / raft commit wait,
                 time.sleep, cv .wait) reached while a lock is held.
                 Reported AT THE HOLDING with-statement: that is where
                 the design decision lives.  Exemptions:
                 - `cv.wait()` where the condition wraps the held lock
                   (releasing it is the point of a condition variable —
                   the _LOCK_ALIASES / Condition(self._lock) pattern)
                 - locks declared in their class's
                   `_LOCK_BLOCKING_OK = {"_lock": "reason"}`: locks
                   whose JOB is to serialize blocking I/O (WAL append+
                   fsync, RPC round-trip sockets, raft's
                   persist-before-respond).  A reasonless declaration
                   is itself a finding, like a reasonless allow.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, FuncInfo, SourceFile, class_attr_types, class_decl,
    decl_str_dict, dotted, enclosing_def_line, index_functions,
    lock_alloc_sites, receiver_classes, resolve_call_targets,
)
from nomad_tpu.analysis.lock_order import LOCK_ORDER_FORMAT

CHECKER = "wait-graph"

# attribute calls that block the calling thread
_BLOCKING_ATTRS = {
    "fsync": "fsync",
    "sendall": "socket send",
    "recv": "socket recv",
    "recv_into": "socket recv",
    "accept": "socket accept",
    "connect": "socket connect",
    "result": "future/commit wait",
    "wait": "condition/event wait",
    "wait_for": "condition wait",
}
# dotted calls that block
_BLOCKING_DOTTED = {
    "os.fsync": "fsync",
    "time.sleep": "sleep",
}


def _lock_site(expr: ast.AST, bases: Dict[str, str],
               sites: Dict[Tuple[str, str], str]) -> Optional[str]:
    """`<base>.<attr>` -> alloc site when the base's class allocates
    that lock attr, else None."""
    if not isinstance(expr, ast.Attribute):
        return None
    b = dotted(expr.value)
    if b is None:
        return None
    cls = bases.get(b)
    if cls is None:
        return None
    return sites.get((cls, expr.attr))


def _blocking_call(node: ast.Call, bases: Dict[str, str],
                   sites: Dict[Tuple[str, str], str]
                   ) -> Optional[Tuple[str, Optional[str]]]:
    """(description, waited-cv-site-or-None) if this call blocks."""
    d = dotted(node.func)
    if d in _BLOCKING_DOTTED:
        return _BLOCKING_DOTTED[d], None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
        cv_site = None
        if f.attr in ("wait", "wait_for") and \
                isinstance(f.value, ast.Attribute):
            b = dotted(f.value.value)
            cls = bases.get(b) if b is not None else None
            if cls is not None:
                cv_site = sites.get((cls, f.value.attr))
        return _BLOCKING_ATTRS[f.attr], cv_site
    return None


class _FnSummary:
    __slots__ = ("fi", "bases", "acquires", "blocking", "callees")

    def __init__(self, fi: FuncInfo):
        self.fi = fi
        self.bases: Dict[str, str] = {}
        self.acquires: Set[str] = set()
        # (rel, line, description, waited cv site) of blocking calls
        self.blocking: List[Tuple[str, int, str, Optional[str]]] = []
        self.callees: Set[str] = set()


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    files = corpus.py
    index = index_functions(files)
    attr_types = class_attr_types(files)
    sites = lock_alloc_sites(files)
    corpus_classes: Set[str] = {
        fi.cls for fis in index.values() for fi in fis
        if fi.cls is not None}

    # site -> every (class, attr) that names it (Condition aliases make
    # this one-to-many), for rendering and _LOCK_BLOCKING_OK lookup
    site_owners: Dict[str, Set[Tuple[str, str]]] = {}
    for (cls, attr), site in sites.items():
        site_owners.setdefault(site, set()).add((cls, attr))

    # (class, attr) -> stated reason the lock may be held across
    # blocking calls; reasonless declarations are findings
    blocking_ok: Dict[Tuple[str, str], str] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            decl = class_decl(node, "_LOCK_BLOCKING_OK")
            if decl is None:
                continue
            entries = decl_str_dict(decl)
            if isinstance(decl, ast.Dict):
                for k in decl.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str) and \
                            not entries.get(k.value, "").strip():
                        findings.append(Finding(
                            CHECKER, sf.rel, k.lineno,
                            f"_LOCK_BLOCKING_OK entry `{k.value}` on "
                            f"{node.name} states no reason"))
            for attr, reason in entries.items():
                if reason.strip():
                    blocking_ok[(node.name, attr)] = reason

    def site_exempt(site: str) -> bool:
        return any(owner in blocking_ok
                   for owner in site_owners.get(site, ()))

    def held_name(site: str) -> str:
        owners = site_owners.get(site)
        if owners:
            cls, attr = sorted(owners)[0]
            return f"{cls}.{attr} ({site})"
        return site

    # ---- per-function summaries
    summaries: Dict[str, _FnSummary] = {}
    for fis in index.values():
        for fi in fis:
            if fi.key in summaries:
                continue
            s = _FnSummary(fi)
            s.bases = receiver_classes(fi, attr_types)
            summaries[fi.key] = s
            sf = fi.sf
            for node in ast.walk(fi.node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        site = _lock_site(item.context_expr,
                                          s.bases, sites)
                        if site is not None:
                            s.acquires.add(site)
                elif isinstance(node, ast.Call):
                    line = node.lineno
                    if sf.allowed(CHECKER, line,
                                  enclosing_def_line(sf, line)):
                        continue
                    blk = _blocking_call(node, s.bases, sites)
                    if blk is not None:
                        s.blocking.append((sf.rel, line, blk[0], blk[1]))
                    for target in resolve_call_targets(
                            fi, node, index, s.bases, corpus_classes):
                        s.callees.add(target.key)

    # ---- fixpoint: locks acquired / blocking calls reached in the
    # cone below each function
    acq_all: Dict[str, Set[str]] = {
        k: set(s.acquires) for k, s in summaries.items()}
    blk_all: Dict[str, List[Tuple[str, int, str, Optional[str]]]] = {
        k: list(s.blocking) for k, s in summaries.items()}
    changed = True
    while changed:
        changed = False
        for k, s in summaries.items():
            for c in s.callees:
                if c == k or c not in summaries:
                    continue
                extra = acq_all[c] - acq_all[k]
                if extra:
                    acq_all[k] |= extra
                    changed = True
                have = {(p, ln) for (p, ln, _d, _c) in blk_all[k]}
                for ent in blk_all[c]:
                    if (ent[0], ent[1]) not in have and \
                            len(blk_all[k]) < 64:
                        blk_all[k].append(ent)
                        changed = True

    # ---- static edges + held-blocking findings
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    reported: Set[Tuple[str, int, str, int]] = set()

    def blocking_finding(sf: SourceFile, hold_line: int,
                         held_site: str, qual: str,
                         ent: Tuple[str, int, str, Optional[str]],
                         via: Tuple[str, ...]) -> None:
        sink_rel, sink_line, desc, cv = ent
        if cv is not None and cv == held_site:
            return
        if site_exempt(held_site):
            return
        key = (sf.rel, hold_line, sink_rel, sink_line)
        if key in reported:
            return
        if sf.allowed(CHECKER, hold_line,
                      enclosing_def_line(sf, hold_line)):
            return
        reported.add(key)
        findings.append(Finding(
            CHECKER, sf.rel, hold_line,
            f"{held_name(held_site)} held across a blocking call "
            f"({desc} at {sink_rel}:{sink_line})", via))

    def scan_body(sf: SourceFile, summ: _FnSummary,
                  node: ast.AST, held: List[Tuple[str, int]]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue   # nested defs run later, not under this lock
            acquired: List[Tuple[str, int]] = []
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    site = _lock_site(item.context_expr,
                                      summ.bases, sites)
                    if site is None:
                        continue
                    line = item.context_expr.lineno
                    if sf.allowed(CHECKER, line,
                                  enclosing_def_line(sf, line)):
                        continue
                    for h, _hl in held:
                        if h != site:
                            edges.setdefault(
                                (h, site),
                                (sf.rel, line, summ.fi.qualname))
                    acquired.append((site, line))
            elif isinstance(child, ast.Call) and held:
                h_site, h_line = held[-1]
                blk = _blocking_call(child, summ.bases, sites)
                if blk is not None:
                    blocking_finding(
                        sf, h_line, h_site, summ.fi.qualname,
                        (sf.rel, child.lineno, blk[0], blk[1]),
                        (summ.fi.qualname,))
                else:
                    for target in resolve_call_targets(
                            summ.fi, child, index, summ.bases,
                            corpus_classes):
                        for m in acq_all.get(target.key, ()):
                            for h, _hl in held:
                                if h != m:
                                    edges.setdefault(
                                        (h, m),
                                        (sf.rel, child.lineno,
                                         summ.fi.qualname))
                        for ent in blk_all.get(target.key, ()):
                            blocking_finding(
                                sf, h_line, h_site, summ.fi.qualname,
                                ent, (summ.fi.qualname,
                                      target.qualname))
            held.extend(acquired)
            scan_body(sf, summ, child, held)
            if acquired:
                del held[len(held) - len(acquired):]

    for summ in summaries.values():
        scan_body(summ.fi.sf, summ, summ.fi.node, [])

    # ---- merge the runtime corpus (same node namespace)
    runtime_edges: Dict[Tuple[str, str], str] = {}
    lc = corpus.lock_corpus
    if lc is not None:
        if lc.get("format") != LOCK_ORDER_FORMAT:
            findings.append(Finding(
                CHECKER, "<lock-corpus>", 0,
                f"lock corpus format {lc.get('format')!r} is not "
                f"{LOCK_ORDER_FORMAT!r}"))
        else:
            for e in lc.get("edges", ()):
                a, b = e.get("a"), e.get("b")
                if a and b and a != b:
                    runtime_edges.setdefault((a, b), e.get("thread", "?"))

    # ---- cycle detection over the merged graph
    g: Dict[str, Set[str]] = {}
    for (a, b) in list(edges) + list(runtime_edges):
        g.setdefault(a, set()).add(b)

    out_cycles: List[List[str]] = []
    seen: Set[Tuple[str, ...]] = set()
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in g}

    def dfs(node: str, path: List[str]) -> None:
        color[node] = GREY
        path.append(node)
        for nxt in sorted(g.get(node, ())):
            c = color.get(nxt, WHITE)
            if c == GREY:
                cyc = path[path.index(nxt):] + [nxt]
                canon = tuple(sorted(cyc[:-1]))
                if canon not in seen:
                    seen.add(canon)
                    out_cycles.append(cyc)
            elif c == WHITE:
                dfs(nxt, path)
        path.pop()
        color[node] = BLACK

    for n in sorted(g):
        if color.get(n, WHITE) == WHITE:
            dfs(n, [])

    for cyc in out_cycles:
        parts = []
        loc: Tuple[str, int] = ("<lock-corpus>", 0)
        chain: Tuple[str, ...] = ()
        for a, b in zip(cyc, cyc[1:]):
            if (a, b) in edges:
                rel, line, qual = edges[(a, b)]
                parts.append(f"{held_name(a)} -> {held_name(b)} "
                             f"[static: {qual}]")
                if loc[0] == "<lock-corpus>":
                    loc, chain = (rel, line), (qual,)
            else:
                thread = runtime_edges.get((a, b), "?")
                parts.append(f"{held_name(a)} -> {held_name(b)} "
                             f"[runtime: thread {thread}]")
        findings.append(Finding(
            CHECKER, loc[0], loc[1],
            "lock-order cycle (potential deadlock): " + "; ".join(parts),
            chain))
    return findings
