"""lock-discipline: every touch of a declared lock-protected attribute
happens with the owning lock held.

Classes declare their discipline inline:

    class StateStore:
        _LOCK_NAME = "_lock"
        _LOCK_ALIASES = ("_index_cv",)       # Condition over the same lock
        _LOCK_PROTECTED = frozenset({"_nodes", "_jobs", ...})

The checker then walks EVERY file in the corpus and requires each
read/write of a protected attribute — `self._nodes`, `store._nodes`,
`self.store._nodes`, whatever the receiver — to appear either:

- lexically inside a `with <receiver>.<lockname>:` block whose receiver
  expression matches the access's receiver (`with s._lock:` covers
  `s._nodes`), or
- inside a function decorated `@requires_lock("<lockname>")` (the
  caller-holds-the-lock contract for `_locked` helpers), or
- inside the owning class's own `__init__` with receiver `self`
  (construction precedes sharing), or
- on a line carrying `# analysis: allow(lock-discipline)`.

Receiver matching is textual (`ast.unparse`), which is exactly as strong
as the aliasing in this codebase: helpers bind `s = self.store` before
`with s._lock:`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, SourceFile, dotted, enclosing_def_line,
)

CHECKER = "lock-discipline"


def _const_str_set(node: ast.AST) -> Optional[Set[str]]:
    """Evaluate a literal set/frozenset/tuple/list of strings."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set") and node.args:
        return _const_str_set(node.args[0])
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
                return None
            out.add(el.value)
        return out
    return None


def _collect_declarations(files) -> Tuple[Set[str], Set[str], Dict[str, str]]:
    """-> (protected attr names, acceptable lock attr names,
           owning class name per protected attr)."""
    protected: Set[str] = set()
    locknames: Set[str] = set()
    owner: Dict[str, str] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            decl: Optional[Set[str]] = None
            lockname = "_lock"
            aliases: Set[str] = set()
            for item in node.body:
                if isinstance(item, ast.Assign) and len(item.targets) == 1 \
                        and isinstance(item.targets[0], ast.Name):
                    tname = item.targets[0].id
                    if tname == "_LOCK_PROTECTED":
                        decl = _const_str_set(item.value)
                    elif tname == "_LOCK_NAME" and \
                            isinstance(item.value, ast.Constant):
                        lockname = item.value.value
                    elif tname == "_LOCK_ALIASES":
                        aliases = _const_str_set(item.value) or set()
            if decl:
                protected |= decl
                locknames.add(lockname)
                locknames |= aliases
                for a in decl:
                    owner[a] = node.name
    return protected, locknames, owner


def _requires_lock(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted(target)
        if name and name.split(".")[-1] == "requires_lock":
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, protected: Set[str],
                 locknames: Set[str], owner: Dict[str, str],
                 findings: List[Finding]):
        self.sf = sf
        self.protected = protected
        self.locknames = locknames
        self.owner = owner
        self.findings = findings
        self.held: List[str] = []          # receiver exprs with lock held
        self.fn_stack: List[ast.AST] = []
        self.class_stack: List[str] = []
        self.reported: Set[int] = set()

    # ---- scope tracking

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_fn(self, node) -> None:
        self.fn_stack.append(node)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node: ast.With) -> None:
        added = 0
        for item in node.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Attribute) and ctx.attr in self.locknames:
                recv = _unparse(ctx.value)
                if recv is not None:
                    self.held.append(recv)
                    added += 1
            # `with self._lock` may also appear via a local alias:
            # `lk = store._lock; with lk:` — treat a bare Name context
            # whose id ends with a lock name as held-for-anything? No:
            # too loose; aliased lock handles stay on the allow comment.
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(added):
            self.held.pop()

    # ---- access checks

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in self.protected:
            self._check(node)
        self.generic_visit(node)

    def _check(self, node: ast.Attribute) -> None:
        line = node.lineno
        if line in self.reported:
            return
        sf = self.sf
        recv = _unparse(node.value)
        if recv is None:
            return
        # declaration site / class body (no function yet): skip
        if not self.fn_stack:
            return
        fn = self.fn_stack[-1]
        # any enclosing annotated function accepts the access
        if any(_requires_lock(f) for f in self.fn_stack):
            return
        if recv in self.held:
            return
        if recv == "self" and self.class_stack:
            # `self.X` in a class that is NOT X's declared owner refers to
            # that class's own attribute which merely shares the name
            # (e.g. StateSnapshot's immutable copies of store tables)
            if self.class_stack[-1] != self.owner.get(node.attr):
                return
            # construction in the owner's __init__ precedes sharing
            if fn.name == "__init__":
                return
        if sf.allowed(CHECKER, line, enclosing_def_line(sf, line)):
            return
        self.reported.add(line)
        owner = self.owner.get(node.attr, "?")
        self.findings.append(Finding(
            CHECKER, sf.rel, line,
            f"`{recv}.{node.attr}` ({owner} lock-protected) accessed "
            f"without holding `{recv}._lock` (wrap in `with "
            f"{recv}._lock:` or annotate the method with "
            f"@requires_lock)"))


def _unparse(node: ast.AST) -> Optional[str]:
    try:
        return ast.unparse(node)
    except Exception:               # noqa: BLE001 — exotic receivers
        return None


def run(corpus: Corpus) -> List[Finding]:
    protected, locknames, owner = _collect_declarations(corpus.py)
    if not protected:
        return []
    findings: List[Finding] = []
    for sf in corpus.py:
        _Visitor(sf, protected, locknames, owner, findings).visit(sf.tree)
    return findings
