"""recompile-budget: every hot-path jit kernel is named, counted, gated.

A shape-bucketing bug does not crash — it recompiles, silently turning a
microsecond dispatch into a multi-second XLA build.  This module makes
recompiles loud.

**Static** (part of `run_all`): modules that opt in with

    _RECOMPILE_TRACKED = True

must hand every jitted callable to the runtime registry:

    fn = recompile.register("scan:mesh", jax.jit(shard_map(body, ...)))

The checker collects jit sites (decorated defs and `x = jax.jit(...)`
assignments) and flags any whose name is never passed to a
`recompile.register(...)` call in the same module.  Unregistered kernels
are invisible to the budget, so the drift is the finding.

**Runtime**: `register()` keeps the jitted callables by name;
`cache_sizes()` polls their `_cache_size()` (one entry per traced
specialization); `install_listener()` hooks jax.monitoring's
`/jax/core/compile/backend_compile_duration` event, which fires once per
backend compile and never on a cache hit.  `Budget` snapshots both after
warmup; `violations()` names every kernel whose cache grew — plus the
raw compile-event delta — during the measured run.  bench.py folds
`report()` into the BENCH JSON and fails the run on violations;
per-kernel counts land in telemetry as `recompile.<name>` gauges.

Stdlib-only at import (the CI analysis leg lints before pip install);
jax is imported lazily inside the runtime helpers.
"""
from __future__ import annotations

import ast
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, SourceFile, dotted, enclosing_def_line,
)

CHECKER = "recompile-budget"

_JIT = {"jax.jit", "jit"}


# ===================================================================== runtime

_registry: Dict[str, Any] = {}
_compile_events = 0
_listener_installed = False


def register(key: str, fn: Any) -> Any:
    """Track `fn` (a jitted callable) under `key`; returns `fn` so call
    sites can register inline.  Re-registering a key replaces the entry
    (caches rebuilt per mesh re-register their current incarnation)."""
    _registry[key] = fn
    return fn


def cache_sizes() -> Dict[str, int]:
    """key -> number of traced specializations currently cached."""
    out: Dict[str, int] = {}
    for key, fn in _registry.items():
        try:
            out[key] = fn._cache_size()
        except Exception:   # noqa: BLE001 — probe must never raise
            out[key] = -1
    return out


def install_listener() -> None:
    """Count backend compiles process-wide (idempotent)."""
    global _listener_installed
    if _listener_installed:
        return
    from jax import monitoring   # runtime-only import

    def _on_event(event: str, *args, **kwargs) -> None:
        if event.endswith("backend_compile_duration"):
            global _compile_events
            _compile_events += 1

    monitoring.register_event_duration_secs_listener(_on_event)
    _listener_installed = True


def compile_events() -> int:
    return _compile_events


class Budget:
    """Snapshot compile state now; report growth later."""

    def __init__(self):
        install_listener()
        self.start_sizes = cache_sizes()
        self.start_events = compile_events()

    def report(self) -> Dict[str, Any]:
        sizes = cache_sizes()
        grew = {k: v - self.start_sizes.get(k, 0)
                for k, v in sizes.items()
                if v > self.start_sizes.get(k, 0)}
        return {
            "per_kernel": sizes,
            "recompiled": grew,
            "compile_events": compile_events() - self.start_events,
        }

    def violations(self) -> List[str]:
        rep = self.report()
        out = [f"kernel `{k}` recompiled {n}x after warmup"
               for k, n in sorted(rep["recompiled"].items())]
        if not out and rep["compile_events"] > 0:
            out.append(f"{rep['compile_events']} backend compile(s) after "
                       f"warmup outside the registered kernels")
        return out

    def publish(self, metrics) -> None:
        """Fold per-kernel counts into a MetricsRegistry as gauges (one
        atomic batch via set_gauges so readers never see a torn set)."""
        gauges = dict(cache_sizes())
        gauges["compile_events"] = compile_events()
        metrics.set_gauges(gauges, prefix="recompile.")


# ====================================================================== static

def _jit_sites(sf: SourceFile) -> List[Tuple[str, int]]:
    """(name, lineno) of every jitted def / `x = jax.jit(...)` assign."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = dotted(target)
                jitted = name in _JIT or (
                    name in ("functools.partial", "partial") and
                    isinstance(dec, ast.Call) and dec.args and
                    dotted(dec.args[0]) in _JIT)
                if jitted:
                    out.append((node.name, node.lineno))
                    break
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                dotted(node.value.func) in _JIT:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.append((t.id, node.lineno))
    return out


def _registered_names(sf: SourceFile) -> Set[str]:
    """Names appearing as arguments to recompile.register(...) — either
    `register(key, fn)` or the inline `x = register(key, jax.jit(...))`
    form, whose assign targets count as registered too."""
    out: Set[str] = set()

    def _is_register(call: ast.Call) -> bool:
        f = call.func
        return (isinstance(f, ast.Attribute) and f.attr == "register" and
                (dotted(f.value) or "").split(".")[-1] == "recompile") or \
            (isinstance(f, ast.Name) and f.id == "register")

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and _is_register(node):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_register(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.py:
        tracked = any(
            isinstance(node, ast.Assign) and len(node.targets) == 1 and
            isinstance(node.targets[0], ast.Name) and
            node.targets[0].id == "_RECOMPILE_TRACKED" and
            isinstance(node.value, ast.Constant) and node.value.value is True
            for node in sf.tree.body)
        if not tracked:
            continue
        registered = _registered_names(sf)
        for name, lineno in _jit_sites(sf):
            if name in registered:
                continue
            if sf.allowed(CHECKER, lineno, enclosing_def_line(sf, lineno)):
                continue
            findings.append(Finding(
                CHECKER, sf.rel, lineno,
                f"jitted kernel `{name}` is not registered with the "
                f"recompile budget (recompile.register(key, {name}))"))
    return findings
