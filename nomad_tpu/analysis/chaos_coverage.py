"""chaos-coverage: the chaos registry and its injection sites agree.

`nomad_tpu/chaos.py` registers the fault-point universe in
`FAULT_POINTS`; injection sites call `chaos.should("…")`,
`chaos.fire("…")`, or `chaos.maybe_delay("…")` (no-arg `maybe_delay()`
defaults to "rpc.delay").  Two drift directions, both flagged:

- a registered point with NO injection site is dead chaos config — a
  soak run setting its rate exercises nothing
- an injection site naming an UNREGISTERED point raises ValueError only
  when someone first sets a rate for it, i.e. never in CI

The registry may additionally pin points to the functions that must
carry them:

    REQUIRED_SITES = {
        "world.scatter_fail": ("DeviceWorld.apply_rank1",
                               "DeviceWorld._update_one"),
    }

Each listed `Class.method` (or bare function) qualname must contain an
injection site for that point — so a refactor that drops the fault hook
from a critical path (scatter commit, dirty-row diff, batched ticket
release) fails the lint even though the point still has *a* site
somewhere.  Required points must themselves be in FAULT_POINTS.

The file defining FAULT_POINTS is exempt from site collection (its own
function defs mention the default point).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, dotted, enclosing_def_line,
)

CHECKER = "chaos-coverage"

_SITE_FNS = {"should", "fire", "maybe_delay"}


def _fault_points(sf) -> Optional[Tuple[Set[str], int]]:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "FAULT_POINTS" and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            points = {el.value for el in node.value.elts
                      if isinstance(el, ast.Constant) and
                      isinstance(el.value, str)}
            return points, node.lineno
    return None


def _required_sites(sf) -> Optional[Tuple[Dict[str, Tuple[str, ...]], int]]:
    """Parse a literal `REQUIRED_SITES = {"point": ("Qual", ...)}`."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "REQUIRED_SITES" and \
                isinstance(node.value, ast.Dict):
            out: Dict[str, Tuple[str, ...]] = {}
            for kn, vn in zip(node.value.keys, node.value.values):
                if not (isinstance(kn, ast.Constant) and
                        isinstance(kn.value, str)):
                    continue
                quals = []
                if isinstance(vn, (ast.Tuple, ast.List)):
                    quals = [el.value for el in vn.elts
                             if isinstance(el, ast.Constant) and
                             isinstance(el.value, str)]
                elif isinstance(vn, ast.Constant) and \
                        isinstance(vn.value, str):
                    quals = [vn.value]
                out[kn.value] = tuple(quals)
            return out, node.lineno
    return None


def _enclosing_qualname(sf, lineno: int) -> Optional[str]:
    """Innermost def containing `lineno` as Class.method / bare name."""
    best: Optional[str] = None
    best_span = None

    def visit(node: ast.AST, cls: Optional[str]) -> None:
        nonlocal best, best_span
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                end = getattr(child, "end_lineno", child.lineno)
                if child.lineno <= lineno <= end:
                    span = end - child.lineno
                    if best_span is None or span < best_span:
                        best = f"{cls}.{child.name}" if cls else child.name
                        best_span = span
                visit(child, None)
            else:
                visit(child, cls)

    visit(sf.tree, None)
    return best


def run(corpus: Corpus) -> List[Finding]:
    registry_sf = None
    points: Set[str] = set()
    decl_line = 1
    for sf in corpus.py:
        got = _fault_points(sf)
        if got and sf.rel.endswith("chaos.py"):
            registry_sf, (points, decl_line) = sf, got
            break
    if registry_sf is None:
        return []

    findings: List[Finding] = []
    # point -> first site (rel, line); plus unknown-point findings
    sites: Dict[str, Tuple[str, int]] = {}
    # (point, enclosing qualname) of every site, for REQUIRED_SITES
    site_quals: Set[Tuple[str, str]] = set()
    for sf in corpus.py:
        if sf is registry_sf:
            continue
        # names bound to a chaos expression (`reg = chaos.active`)
        aliases: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and \
                    "chaos" in ((dotted(node.value) or "").lower()):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        aliases.add(tgt.id)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name not in _SITE_FNS:
                continue
            # only count chaos-module/registry receivers (incl. aliases)
            if isinstance(f, ast.Attribute):
                base = dotted(f.value) or ""
                if "chaos" not in base.lower() and \
                        base.split(".")[0] not in aliases:
                    continue
            if node.args:
                a = node.args[0]
                if not (isinstance(a, ast.Constant) and
                        isinstance(a.value, str)):
                    continue          # dynamic point: can't check statically
                point = a.value
            elif name == "maybe_delay":
                point = "rpc.delay"
            else:
                continue
            if point not in points:
                if not sf.allowed(CHECKER, node.lineno,
                                  enclosing_def_line(sf, node.lineno)):
                    findings.append(Finding(
                        CHECKER, sf.rel, node.lineno,
                        f"injection site names unregistered chaos point "
                        f"{point!r} (not in FAULT_POINTS)"))
            else:
                sites.setdefault(point, (sf.rel, node.lineno))
                qual = _enclosing_qualname(sf, node.lineno)
                if qual:
                    site_quals.add((point, qual))

    for point in sorted(points - set(sites)):
        if not registry_sf.allowed(CHECKER, decl_line):
            findings.append(Finding(
                CHECKER, registry_sf.rel, decl_line,
                f"registered chaos point {point!r} has no injection site "
                f"(dead fault config)"))

    required = _required_sites(registry_sf)
    if required is not None:
        req_map, req_line = required
        for point, quals in sorted(req_map.items()):
            if registry_sf.allowed(CHECKER, req_line):
                continue
            if point not in points:
                findings.append(Finding(
                    CHECKER, registry_sf.rel, req_line,
                    f"REQUIRED_SITES names {point!r} which is not in "
                    f"FAULT_POINTS"))
                continue
            for qual in quals:
                if (point, qual) not in site_quals:
                    findings.append(Finding(
                        CHECKER, registry_sf.rel, req_line,
                        f"required injection site missing: {qual} must "
                        f"carry chaos point {point!r}"))
    return findings
