"""knob-registry: every NOMAD_TPU_* env knob is declared exactly once.

`nomad_tpu/knobs.py` (marked `_KNOB_REGISTRY = True`) is the single
registry: name, default, type, one-line doc for every environment knob
the runtime consults, read through the typed accessors
(`knobs.get_str/get_int/get_float/get_bool/override`).  Scattered
`os.environ.get("NOMAD_TPU_...")` reads are how knobs rot: defaults
drift between call sites, dead knobs linger in READMEs, live knobs
never make it in.

Four rules, all static (this module never imports the registry — it
parses the `KNOBS` dict literal from the AST, so the CI analysis leg
lints before pip install):

    R1  a direct environ read/write of a `NOMAD_TPU_*` literal outside
        the registry file (environ.get/pop/setdefault, os.getenv,
        subscripting os.environ or a local alias of it)
    R2  an accessor call whose literal knob name is not registered
        (it would KeyError at runtime; the finding is earlier)
    R3  a registered knob never read through an accessor anywhere
        outside the registry (dead entry)
    R4  a registered knob missing from the root README.md (skipped
        when the analyzed tree has no README, so fixture corpora and
        bare package roots stay clean)

Suppress with `# analysis: allow(knob-registry) — reason` on the
finding line, the enclosing def line, or (for R3/R4) the registry
entry's own line.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from nomad_tpu.analysis.common import (
    Corpus, Finding, SourceFile, dotted, enclosing_def_line, module_decl,
)

CHECKER = "knob-registry"

_PREFIX = "NOMAD_TPU_"
_ACCESSORS = {"get_str", "get_int", "get_float", "get_bool", "override"}
_ENV_METHODS = {"get", "pop", "setdefault"}


def _find_registry(corpus: Corpus) -> Optional[SourceFile]:
    for sf in corpus.py:
        marker = module_decl(sf, "_KNOB_REGISTRY")
        if isinstance(marker, ast.Constant) and marker.value is True:
            return sf
    return None


def _registry_entries(sf: SourceFile) -> Dict[str, int]:
    """knob name -> declaration line, from the KNOBS dict literal."""
    out: Dict[str, int] = {}
    decl = module_decl(sf, "KNOBS")
    if isinstance(decl, ast.Dict):
        for k in decl.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out[k.value] = k.lineno
    return out


def _environ_aliases(sf: SourceFile) -> Set[str]:
    """Local names bound to os.environ anywhere in the file
    (`env = os.environ` makes `env.get(...)` an environ read)."""
    out: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and \
                dotted(node.value) == "os.environ":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _is_environ(expr: ast.AST, aliases: Set[str]) -> bool:
    d = dotted(expr)
    if d is None:
        return False
    return d.split(".")[-1] == "environ" or d in aliases


def _literal_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _imports_knobs(sf: SourceFile) -> bool:
    return any(imp == "nomad_tpu.knobs" or
               imp.startswith("nomad_tpu.knobs.") for imp in sf.imports)


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    reg_sf = _find_registry(corpus)
    entries = _registry_entries(reg_sf) if reg_sf is not None else {}
    used: Set[str] = set()

    for sf in corpus.py:
        is_registry = sf is reg_sf
        aliases = _environ_aliases(sf)
        for node in ast.walk(sf.tree):
            # ---- R1: raw environ access of a NOMAD_TPU_* literal
            if not is_registry:
                name = None
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and \
                            f.attr in _ENV_METHODS and \
                            _is_environ(f.value, aliases):
                        name = _literal_arg(node)
                    elif dotted(f) in ("os.getenv", "getenv"):
                        name = _literal_arg(node)
                elif isinstance(node, ast.Subscript) and \
                        _is_environ(node.value, aliases) and \
                        isinstance(node.slice, ast.Constant) and \
                        isinstance(node.slice.value, str):
                    name = node.slice.value
                if name is not None and name.startswith(_PREFIX):
                    line = node.lineno
                    if not sf.allowed(CHECKER, line,
                                      enclosing_def_line(sf, line)):
                        where = "no knob registry module found" \
                            if reg_sf is None else \
                            f"the registry is {reg_sf.rel}"
                        findings.append(Finding(
                            CHECKER, sf.rel, line,
                            f"raw environ access of `{name}` outside "
                            f"the knob registry ({where}); read it "
                            f"through nomad_tpu.knobs accessors"))
            # ---- R2 + usage collection: typed accessor calls
            if isinstance(node, ast.Call):
                f = node.func
                acc = None
                if isinstance(f, ast.Attribute) and f.attr in _ACCESSORS \
                        and (dotted(f.value) or
                             "").split(".")[-1] == "knobs":
                    acc = f.attr
                elif isinstance(f, ast.Name) and f.id in _ACCESSORS and \
                        _imports_knobs(sf):
                    acc = f.id
                if acc is None:
                    continue
                name = _literal_arg(node)
                if name is None:
                    continue
                if reg_sf is not None and name not in entries:
                    line = node.lineno
                    if not sf.allowed(CHECKER, line,
                                      enclosing_def_line(sf, line)):
                        findings.append(Finding(
                            CHECKER, sf.rel, line,
                            f"knobs.{acc}({name!r}) reads an "
                            f"unregistered knob (not declared in "
                            f"{reg_sf.rel} KNOBS)"))
                elif not is_registry:
                    used.add(name)

    if reg_sf is not None:
        # ---- R3: dead registry entries
        for name, line in sorted(entries.items()):
            if name not in used and not reg_sf.allowed(CHECKER, line):
                findings.append(Finding(
                    CHECKER, reg_sf.rel, line,
                    f"registered knob `{name}` is never read through "
                    f"an accessor outside the registry (dead entry)"))
        # ---- R4: README coverage
        readme = corpus.root / "README.md"
        if readme.is_file():
            try:
                text = readme.read_text()
            except (OSError, UnicodeDecodeError):
                text = None
            if text is not None:
                for name, line in sorted(entries.items()):
                    if name not in text and \
                            not reg_sf.allowed(CHECKER, line):
                        findings.append(Finding(
                            CHECKER, reg_sf.rel, line,
                            f"registered knob `{name}` is not "
                            f"documented in README.md (regenerate the "
                            f"knob table: python -m nomad_tpu.knobs)"))
    return findings
