"""snapshot-completeness: every replicated table survives the
snapshot/restore round trip, rebuilt by the SAME constructors apply uses.

A raft snapshot is the only state a late-joining (or compacted) replica
ever sees: a table the FSM apply cone mutates but snapshot() never
persists silently diverges the replica from the log, and a table
restore() rebuilds through different code than the apply path rebuilds
it (PR 5's aliasing bug, PR 13's quota-usage rebuild) diverges the
*bytes* even when the values agree.  This checker cross-references four
cones over the shared interprocedural core (common.walk_cone):

  apply cone      FSM `apply` + `_apply_*`  -> store-table mutations
  snapshot cone   FSM `snapshot`            -> persisted attrs + the
                                               string record keys
  restore cone    FSM `restore`             -> rebuilt attrs + the
                                               record keys read back

against the store's declarations:

  _LOCK_PROTECTED      the replicated-table universe
  _SNAPSHOT_DERIVED    {table: builder method} — derived indexes that
                       are rebuilt, not persisted; restore MUST route
                       every row through the named builder, and an
                       incremental builder (one that adds rows in
                       place) must also be reachable from the apply
                       cone, so apply and restore share one constructor
  _SNAPSHOT_EPHEMERAL  caches that legitimately die with the process

and reports:

  - write-only tables   mutated under apply, never persisted/derived
  - persist-only        persisted but never restored
  - restore-only        restored but never persisted (and not derived)
  - record-key drift    snapshot record keys vs the keys restore reads
  - inline rebuilds     restore mutating a derived index outside its
                        builder (resetting to an empty container is the
                        one legal inline form)
  - builder drift       a declared builder missing, unreachable from
                        restore, or incremental yet unreachable from
                        apply (rows rebuilt through a constructor the
                        apply path never uses)
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, FuncInfo, Mutation, attr_mutations, call_name,
    class_attr_types, class_decl, class_methods, decl_str_dict, dotted,
    enclosing_def_line, index_functions, is_empty_ctor, literal_strs,
    resolve_fsm_stores, store_bases, walk_cone,
)

CHECKER = "snapshot-completeness"


def _cone(index, seeds, store_cls: str, attr_types, universe: Set[str]):
    """Walk a cone, returning ({func key}, [(fi, chain, [Mutation])],
    {attr -> (sf, Mutation, chain)} first-mutation sites) restricted to
    the table universe."""
    keys: Set[str] = set()
    visits = []
    first: Dict[str, Tuple] = {}
    for fi, chain in walk_cone(index, seeds, CHECKER):
        keys.add(fi.key)
        bases = store_bases(fi, store_cls, attr_types)
        muts = [m for m in attr_mutations(fi.node, bases)
                if m.attr in universe] if bases else []
        visits.append((fi, chain, muts))
        for m in muts:
            first.setdefault(m.attr, (fi.sf, m, chain))
    return keys, visits, first


def _referenced_attrs(fi: FuncInfo, bases: Set[str],
                      universe: Set[str]) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Attribute) and node.attr in universe:
            b = dotted(node.value)
            if b is not None and b in bases:
                out.add(node.attr)
    return out


def _record_keys(fi: FuncInfo) -> Dict[str, int]:
    """String keys of dict literals built in the snapshot fn -> line."""
    out: Dict[str, int] = {}
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.setdefault(k.value, k.lineno)
    return out


def _blob_names(fi: FuncInfo) -> Set[str]:
    """Local names bound to the deserialized snapshot record
    (`data = pickle.loads(blob)` and aliases)."""
    names: Set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            callee = call_name(node.value)
            if callee in ("loads", "load"):
                names.add(node.targets[0].id)
    # aliases of the record dict
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in names \
                    and node.targets[0].id not in names:
                names.add(node.targets[0].id)
                changed = True
    return names


def _read_keys(fi: FuncInfo, blob_names: Set[str]) -> Dict[str, int]:
    """Record keys the restore fn reads: `data["k"]`, `data.get("k")`,
    `"k" in data` -> line."""
    out: Dict[str, int] = {}

    def is_blob(expr: ast.AST) -> bool:
        return isinstance(expr, ast.Name) and expr.id in blob_names

    for node in ast.walk(fi.node):
        if isinstance(node, ast.Subscript) and is_blob(node.value):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                out.setdefault(sl.value, node.lineno)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and is_blob(node.func.value):
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                out.setdefault(node.args[0].value, node.lineno)
        elif isinstance(node, ast.Compare) and \
                len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                is_blob(node.comparators[0]) and \
                isinstance(node.left, ast.Constant) and \
                isinstance(node.left.value, str):
            out.setdefault(node.left.value, node.lineno)
    return out


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    files = corpus.py
    index = index_functions(files)
    attr_types = class_attr_types(files)

    for pair in resolve_fsm_stores(files, attr_types):
        fsm_sf, fsm_cls = pair.fsm_sf, pair.fsm_cls
        store_cls_name = pair.store_cls.name
        universe = pair.tables
        if not universe:
            continue
        derived = decl_str_dict(
            class_decl(pair.store_cls, "_SNAPSHOT_DERIVED"))
        eph_decl = class_decl(pair.store_cls, "_SNAPSHOT_EPHEMERAL")
        ephemeral = literal_strs(eph_decl) if eph_decl is not None else set()
        methods = class_methods(fsm_cls)
        snap_fn = methods.get("snapshot")
        restore_fn = methods.get("restore")
        store_methods = class_methods(pair.store_cls)

        def fi_of(sf, cls, fn) -> FuncInfo:
            return FuncInfo(sf, fn, f"{cls.name}.{fn.name}")

        def report(sf, line: int, msg: str,
                   chain: Tuple[str, ...] = ()) -> None:
            if not sf.allowed(CHECKER, line, enclosing_def_line(sf, line)):
                findings.append(Finding(CHECKER, sf.rel, line, msg, chain))

        # ---- apply cone: every table the log can mutate
        apply_seeds = [fi_of(fsm_sf, fsm_cls, fn)
                       for name, fn in methods.items()
                       if name == "apply" or name.startswith("_apply_")]
        apply_keys, _apply_visits, apply_first = _cone(
            index, apply_seeds, store_cls_name, attr_types, universe)

        # ---- snapshot cone: persisted attrs + record keys
        persisted: Set[str] = set()
        snap_keys: Dict[str, int] = {}
        snap_line = fsm_cls.lineno
        if snap_fn is not None:
            snap_line = snap_fn.lineno
            for fi, _chain in walk_cone(
                    index, [fi_of(fsm_sf, fsm_cls, snap_fn)], CHECKER):
                bases = store_bases(fi, store_cls_name, attr_types)
                if bases:
                    persisted |= _referenced_attrs(fi, bases, universe)
                for k, ln in _record_keys(fi).items():
                    snap_keys.setdefault(k, ln)

        # ---- restore cone: rebuilt attrs + record keys read back
        restored: Set[str] = set()
        restore_keys: Dict[str, int] = {}
        restore_line = fsm_cls.lineno
        restore_visits = []
        restore_cone_keys: Set[str] = set()
        if restore_fn is not None:
            restore_line = restore_fn.lineno
            restore_cone_keys, restore_visits, restore_first = _cone(
                index, [fi_of(fsm_sf, fsm_cls, restore_fn)],
                store_cls_name, attr_types, universe)
            restored = set(restore_first)
            for fi, _chain, _muts in restore_visits:
                blobs = _blob_names(fi)
                if blobs:
                    for k, ln in _read_keys(fi, blobs).items():
                        restore_keys.setdefault(k, ln)

        # ---- write-only tables: mutated under apply, never persisted
        for attr in sorted(apply_first):
            if attr in persisted or attr in derived or attr in ephemeral:
                continue
            sf, m, chain = apply_first[attr]
            report(sf, m.line,
                   f"store table `{attr}` is mutated in the FSM apply "
                   f"cone but never persisted by snapshot() and not "
                   f"declared in _SNAPSHOT_DERIVED/_SNAPSHOT_EPHEMERAL "
                   f"(write-only replication state)", chain)

        # ---- persist-only / restore-only tables
        if snap_fn is not None and restore_fn is not None:
            for attr in sorted(persisted - restored - ephemeral):
                report(fsm_sf, snap_line,
                       f"snapshot() persists store table `{attr}` but "
                       f"restore() never rebuilds it (lost on every "
                       f"snapshot install)")
            for attr in sorted(restored - persisted
                               - set(derived) - ephemeral):
                report(fsm_sf, restore_line,
                       f"restore() rebuilds store table `{attr}` which "
                       f"snapshot() never persists (restore-only table: "
                       f"replicas that install the snapshot invent state "
                       f"the leader never had)")

            # ---- record-key drift between persist and restore
            for k in sorted(set(snap_keys) - set(restore_keys)):
                report(fsm_sf, snap_keys[k],
                       f"snapshot record key '{k}' is never read back "
                       f"by restore()")
            for k in sorted(set(restore_keys) - set(snap_keys)):
                report(fsm_sf, restore_keys[k],
                       f"restore() reads record key '{k}' that "
                       f"snapshot() never writes")

        # ---- derived indexes: restore must route rows through the
        # declared builder; resetting to an empty container is the one
        # legal inline mutation
        for fi, chain, muts in restore_visits:
            in_builder = fi.cls == store_cls_name and \
                fi.node.name in derived.values()
            if in_builder:
                continue
            via = {c.rsplit(".", 1)[-1] for c in chain}
            for m in muts:
                if m.attr not in derived:
                    continue
                if derived[m.attr] in via:
                    # reached through the declared builder (a helper it
                    # delegates to) — still the shared constructor
                    continue
                if m.kind == "assign" and is_empty_ctor(m.node.value):
                    continue
                report(fi.sf, m.line,
                       f"derived index `{m.attr}` rebuilt inline in the "
                       f"restore path; route rows through "
                       f"`{derived[m.attr]}` so apply and restore share "
                       f"one constructor", chain)

        # ---- builder declarations: exist, reachable from restore, and
        # (when incremental) shared with the apply path
        decl_node = class_decl(pair.store_cls, "_SNAPSHOT_DERIVED")
        decl_line = getattr(decl_node, "lineno", pair.store_cls.lineno)
        for attr, builder in sorted(derived.items()):
            fn = store_methods.get(builder)
            if fn is None:
                report(pair.store_sf, decl_line,
                       f"_SNAPSHOT_DERIVED maps `{attr}` to "
                       f"`{builder}`, which is not a method of "
                       f"{store_cls_name}")
                continue
            bkey = f"{pair.store_sf.rel}::{store_cls_name}.{builder}"
            if restore_fn is not None and bkey not in restore_cone_keys:
                report(pair.store_sf, fn.lineno,
                       f"derived-index builder `{builder}` (for "
                       f"`{attr}`) is never called from the restore "
                       f"path")
            own = [m for m in attr_mutations(fn, {"self"})
                   if m.attr == attr]
            incremental = any(m.kind != "assign" for m in own)
            if incremental and apply_seeds and bkey not in apply_keys:
                report(pair.store_sf, fn.lineno,
                       f"incremental builder `{builder}` rebuilds "
                       f"`{attr}` row-by-row in restore but is never "
                       f"called from the FSM apply cone (restore uses a "
                       f"constructor apply never uses)")
    return findings
