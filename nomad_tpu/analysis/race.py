"""happens-before: vector-clock data-race detection over declared tables.

Two halves, one checker name.

**Static** (part of `run_all`): classes that opt in declare

    _RACE_TRACED = {"_overlay": "_overlay_lock"}

mapping each traced attribute to the lock attribute that guards it.  The
checker cross-checks the declaration against the runtime hooks the same
way chaos-coverage ties registry to injection sites:

- `_RACE_TRACED` must be a literal ``{str: str}`` dict;
- the named lock attribute must actually be assigned somewhere in the
  class (``self._overlay_lock = ...``);
- every declared ``Class.attr`` key must be traced by at least one
  `race.read("Class.attr", ...)` / `race.write("Class.attr", ...)` hook
  in the corpus (a declaration nothing traces is drift);
- every hook key must be declared by some class (a hook nothing declares
  is drift the other way).

**Runtime** (`RaceDetector`, not part of `run_all`): extends the
lock-order recorder with vector clocks, FastTrack-style.  Wrapped locks
carry a clock that the releasing thread publishes and the acquiring
thread joins; `threading.Thread.start`/`join` are patched for fork/join
edges; `Condition` built over a wrapped RLock goes through an explicit
`_release_save`/`_acquire_restore` pair so waits keep the clocks honest
(attribute delegation alone would let Condition bypass the wrapper).
Production code marks accesses with the module-level hooks

    race.read("PlanApplier._overlay", self)
    race.write("PlanApplier._overlay", self)

which are a single global-load test when no detector is installed
(chaos-style zero overhead).  Two accesses to the same (key, instance)
with no happens-before path between them, at least one a write, produce
a `RaceReport`.  Lock-order cycles (deadlocks) are inherited from the
base recorder.  Enable suite-wide with ``NOMAD_TPU_RACE=1`` (see
tests/conftest.py).
"""
from __future__ import annotations

import _thread
import ast
import sys
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, SourceFile, dotted, enclosing_def_line,
)
from nomad_tpu.analysis.lock_order import (
    LockOrderRecorder, _RecordingLock, _alloc_site,
)

CHECKER = "happens-before"


# ===================================================================== runtime

# the installed detector, or None.  Hooks test this one global: the
# uninstrumented fast path is a load + is-check, nothing else.
active: Optional["RaceDetector"] = None


def read(key: str, obj: object = None) -> None:
    det = active
    if det is not None:
        det.on_access(key, obj, False)


def write(key: str, obj: object = None) -> None:
    det = active
    if det is not None:
        det.on_access(key, obj, True)


def _call_site() -> str:
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename.replace("\\", "/")
        if "analysis/race" not in fname:
            return f"{fname.rsplit('/', 1)[-1]}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _join_into(clk: Dict[int, int], other: Dict[int, int]) -> None:
    for t, c in other.items():
        if c > clk.get(t, 0):
            clk[t] = c


class _VCLock(_RecordingLock):
    """A recording lock that also carries a vector clock."""

    def __init__(self, inner, name: str, recorder: "RaceDetector"):
        super().__init__(inner, name, recorder)
        self._vc: Dict[int, int] = {}   # guarded by the lock itself

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._recorder._on_acquire(self._name)
            self._recorder._vc_acquire(self)
        return got

    def release(self) -> None:
        self._recorder._vc_release(self)
        self._recorder._on_release(self._name)
        self._inner.release()


class _VCRLock(_VCLock):
    """RLock flavor: implements the Condition protocol explicitly so
    `Condition.wait`'s release/reacquire pair updates the clocks (the
    base class only delegates via __getattr__, which hands Condition the
    inner lock's bound methods and silently skips the bookkeeping)."""

    def _release_save(self):
        self._recorder._vc_release(self)
        self._recorder._on_release(self._name)
        return self._inner._release_save()

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        self._recorder._on_acquire(self._name)
        self._recorder._vc_acquire(self)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


@dataclass
class RaceReport:
    key: str
    kind: str                       # write->write / write->read / read->write
    first: Tuple[str, str]          # (site, thread name)
    second: Tuple[str, str]

    def render(self) -> str:
        return (f"race on {self.key} [{self.kind}]: "
                f"{self.first[0]} (thread {self.first[1]}) unordered with "
                f"{self.second[0]} (thread {self.second[1]})")


class _VarState:
    __slots__ = ("write", "reads")

    def __init__(self):
        # (tid, clock component, site, thread name) of the last write
        self.write: Optional[Tuple[int, int, str, str]] = None
        # tid -> (clock component, site, thread name) of unordered reads
        self.reads: Dict[int, Tuple[int, str, str]] = {}


class RaceDetector(LockOrderRecorder):
    """Lock-order recorder + vector-clock happens-before detection."""

    MAX_REPORTS = 64

    def __init__(self):
        super().__init__()
        self.races: List[RaceReport] = []
        self._race_keys: Set[Tuple[str, str, str, str]] = set()
        self._vars: Dict[Tuple[str, int], _VarState] = {}
        self._tl = threading.local()
        self._final: Dict[int, Dict[int, int]] = {}     # id(Thread) -> clock
        self._torig: Optional[Tuple] = None

    # ---- patching (locks + thread fork/join edges)

    def install(self) -> "RaceDetector":
        if self._orig is not None:
            return self
        self._orig = (threading.Lock, threading.RLock)
        real_lock, real_rlock = self._orig
        det = self

        def lock_factory():
            return _VCLock(real_lock(), _alloc_site(), det)

        def rlock_factory():
            return _VCRLock(real_rlock(), _alloc_site(), det)

        threading.Lock = lock_factory
        threading.RLock = rlock_factory

        self._torig = (threading.Thread.start, threading.Thread.join)
        orig_start, orig_join = self._torig

        def start(t):
            clk = det._clock()
            snap = dict(clk)
            # the fork point splits the parent's timeline: bump so the
            # parent's *later* events are not covered by the child's
            # inherited clock
            clk[_thread.get_ident()] += 1
            orig_run = t.run

            # the inherited clock rides in the run() closure, NOT an
            # id(Thread)-keyed map popped via current_thread(): bootstrap
            # acquires the new thread's Event lock before the thread
            # registers in threading._active, where current_thread()
            # would fabricate a _DummyThread whose own Event acquisition
            # re-enters this path unboundedly
            def run():
                _join_into(det._clock(), snap)
                try:
                    orig_run()
                finally:
                    with det._meta:
                        det._final[id(t)] = dict(det._clock())

            t.run = run
            orig_start(t)

        def join(t, timeout=None):
            orig_join(t, timeout)
            if not t.is_alive():
                with det._meta:
                    fin = det._final.get(id(t))
                if fin:
                    _join_into(det._clock(), fin)

        threading.Thread.start = start
        threading.Thread.join = join
        return self

    def uninstall(self) -> None:
        if self._orig is not None:
            threading.Lock, threading.RLock = self._orig
            self._orig = None
        if self._torig is not None:
            threading.Thread.start, threading.Thread.join = self._torig
            self._torig = None

    # ---- vector clocks

    def _clock(self) -> Dict[int, int]:
        # must not touch threading.current_thread(): this runs inside
        # every wrapped-lock acquire, including bootstrap-time acquires
        # from threads not yet in threading._active
        clk = getattr(self._tl, "clock", None)
        if clk is None:
            clk = self._tl.clock = {_thread.get_ident(): 1}
        return clk

    def _vc_acquire(self, lock: _VCLock) -> None:
        # caller holds `lock`, so lock._vc is stable
        _join_into(self._clock(), lock._vc)

    def _vc_release(self, lock: _VCLock) -> None:
        clk = self._clock()
        lock._vc = dict(clk)
        clk[_thread.get_ident()] += 1

    # ---- accesses

    def on_access(self, key: str, obj: object, is_write: bool) -> None:
        clk = self._clock()
        tid = _thread.get_ident()
        own = clk[tid]
        site = _call_site()
        me = threading.current_thread().name
        k = (key, id(obj) if obj is not None else 0)
        with self._meta:
            st = self._vars.get(k)
            if st is None:
                st = self._vars[k] = _VarState()
            if is_write:
                for rt, (rc, rsite, rname) in st.reads.items():
                    if rt != tid and clk.get(rt, 0) < rc:
                        self._report(key, "read->write",
                                     (rsite, rname), (site, me))
                if st.write is not None:
                    wt, wc, wsite, wname = st.write
                    if wt != tid and clk.get(wt, 0) < wc:
                        self._report(key, "write->write",
                                     (wsite, wname), (site, me))
                st.write = (tid, own, site, me)
                st.reads = {}
            else:
                if st.write is not None:
                    wt, wc, wsite, wname = st.write
                    if wt != tid and clk.get(wt, 0) < wc:
                        self._report(key, "write->read",
                                     (wsite, wname), (site, me))
                st.reads[tid] = (own, site, me)

    def _report(self, key: str, kind: str, first: Tuple[str, str],
                second: Tuple[str, str]) -> None:
        dedupe = (key, kind, first[0], second[0])
        if dedupe in self._race_keys or len(self.races) >= self.MAX_REPORTS:
            return
        self._race_keys.add(dedupe)
        self.races.append(RaceReport(key, kind, first, second))

    def render_races(self) -> str:
        return "\n".join(r.render() for r in self.races)


# ====================================================================== static

def _class_self_attrs(cls: ast.ClassDef) -> Set[str]:
    """Every `self.X = ...` target in the class body."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    out.add(t.attr)
    return out


def _traced_decl(cls: ast.ClassDef):
    """(decl dict attr->lock, lineno) from a `_RACE_TRACED = {...}`
    class-level assignment, or (None, badness lineno) when malformed."""
    for item in cls.body:
        if isinstance(item, ast.Assign) and len(item.targets) == 1 and \
                isinstance(item.targets[0], ast.Name) and \
                item.targets[0].id == "_RACE_TRACED":
            if not isinstance(item.value, ast.Dict):
                return None, item.lineno
            decl: Dict[str, str] = {}
            for kn, vn in zip(item.value.keys, item.value.values):
                if not (isinstance(kn, ast.Constant) and
                        isinstance(kn.value, str) and
                        isinstance(vn, ast.Constant) and
                        isinstance(vn.value, str)):
                    return None, item.lineno
                decl[kn.value] = vn.value
            return decl, item.lineno
    return {}, None


def _hook_calls(sf: SourceFile) -> List[Tuple[str, int]]:
    """(key, lineno) for every race.read("K", ...) / race.write("K", ...)
    in the file."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("read", "write") and \
                dotted(node.func.value) == "race" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node.lineno))
    return out


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    # declared "Class.attr" -> (sf, decl lineno)
    declared: Dict[str, Tuple[SourceFile, int]] = {}
    for sf in corpus.py:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            decl, lineno = _traced_decl(node)
            if decl is None:
                if not sf.allowed(CHECKER, lineno):
                    findings.append(Finding(
                        CHECKER, sf.rel, lineno,
                        f"{node.name}._RACE_TRACED must be a literal "
                        f"{{'attr': 'lock_attr'}} dict of string constants"))
                continue
            if not decl:
                continue
            attrs = _class_self_attrs(node)
            for attr, lockname in decl.items():
                key = f"{node.name}.{attr}"
                declared[key] = (sf, lineno)
                if attr not in attrs and not sf.allowed(CHECKER, lineno):
                    findings.append(Finding(
                        CHECKER, sf.rel, lineno,
                        f"_RACE_TRACED declares `{key}` but the class "
                        f"never assigns self.{attr}"))
                if lockname not in attrs and not sf.allowed(CHECKER, lineno):
                    findings.append(Finding(
                        CHECKER, sf.rel, lineno,
                        f"_RACE_TRACED maps `{key}` to lock "
                        f"`{lockname}` but the class never assigns "
                        f"self.{lockname}"))
    hooked: Set[str] = set()
    for sf in corpus.py:
        for key, lineno in _hook_calls(sf):
            hooked.add(key)
            if key not in declared and \
                    not sf.allowed(CHECKER, lineno,
                                   enclosing_def_line(sf, lineno)):
                findings.append(Finding(
                    CHECKER, sf.rel, lineno,
                    f"race hook traces `{key}` but no class declares it "
                    f"in _RACE_TRACED"))
    for key, (sf, lineno) in sorted(declared.items()):
        if key not in hooked and not sf.allowed(CHECKER, lineno):
            findings.append(Finding(
                CHECKER, sf.rel, lineno,
                f"_RACE_TRACED declares `{key}` but no race.read/"
                f"race.write hook traces it (dead declaration)"))
    return findings
