"""CLI: `python -m nomad_tpu.analysis`.

Exit codes: 0 no findings, 1 findings, 2 usage/corpus error.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from nomad_tpu.analysis import CHECKERS, load_lock_corpus, run_all


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="Invariant linters: %s" % ", ".join(CHECKERS))
    ap.add_argument("--root", type=Path, default=None,
                    help="tree to analyze (default: the repo containing "
                         "this package)")
    ap.add_argument("--checker", action="append", dest="checkers",
                    metavar="NAME", choices=sorted(CHECKERS),
                    help="run only this checker (repeatable)")
    ap.add_argument("--checkers", dest="checkers_csv", metavar="A,B",
                    help="comma-separated checker names (combines with "
                         "--checker)")
    ap.add_argument("--list-checkers", action="store_true",
                    help="print the checker names, one per line, and "
                         "exit 0")
    ap.add_argument("--lock-corpus", type=Path, metavar="DUMP.json",
                    help="runtime lock-order corpus "
                         "(LockOrderRecorder.dump / "
                         "NOMAD_TPU_LOCK_ORDER=1) merged into the "
                         "wait-graph")
    ap.add_argument("--baseline", type=Path, metavar="REPORT.json",
                    help="a prior --json report: exit 0 when this run's "
                         "findings are a subset of it, report only the "
                         "NEW findings (ratchet mode — existing debt "
                         "doesn't fail the build, new debt does)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    if args.list_checkers:
        for name in CHECKERS:
            print(name)
        return 0

    checkers = list(args.checkers or [])
    if args.checkers_csv:
        checkers.extend(
            p.strip() for p in args.checkers_csv.split(",") if p.strip())

    root = args.root
    if root is None:
        root = Path(__file__).resolve().parents[2]

    lock_corpus = None
    if args.lock_corpus is not None:
        try:
            lock_corpus = load_lock_corpus(args.lock_corpus)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: --lock-corpus {args.lock_corpus}: {e}",
                  file=sys.stderr)
            return 2

    try:
        findings = run_all(root, checkers=checkers or None,
                           lock_corpus=lock_corpus)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baselined = 0
    if args.baseline is not None:
        try:
            base = json.loads(args.baseline.read_text())
        except (OSError, ValueError) as e:
            print(f"error: --baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        # line numbers drift with unrelated edits; identity is
        # (checker, path, message)
        known = {(f.get("checker"), f.get("path"), f.get("message"))
                 for f in base.get("findings", ())}
        kept = [f for f in findings
                if (f.checker, f.path, f.message) not in known]
        baselined = len(findings) - len(kept)
        findings = kept

    ran = checkers or list(CHECKERS)
    if args.json:
        counts = {name: 0 for name in ran}
        for f in findings:
            counts[f.checker] = counts.get(f.checker, 0) + 1
        report = {
            "root": str(root),
            "checkers": ran,
            "lock_corpus": (str(args.lock_corpus)
                            if args.lock_corpus else None),
            "counts": counts,
            "findings": [f.to_dict() for f in findings],
        }
        if args.baseline is not None:
            report["baseline"] = str(args.baseline)
            report["baselined"] = baselined
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        suffix = f" ({baselined} baselined)" if args.baseline else ""
        print(f"nomad_tpu.analysis: {n} "
              f"{'new ' if args.baseline else ''}finding"
              f"{'s' if n != 1 else ''} in {root} "
              f"({len(set(ran))} checkers){suffix}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
