"""CLI: `python -m nomad_tpu.analysis` — exit 1 on any finding."""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from nomad_tpu.analysis import CHECKERS, run_all


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="Invariant linters: %s" % ", ".join(CHECKERS))
    ap.add_argument("--root", type=Path, default=None,
                    help="tree to analyze (default: the repo containing "
                         "this package)")
    ap.add_argument("--checker", action="append", dest="checkers",
                    metavar="NAME", choices=sorted(CHECKERS),
                    help="run only this checker (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    args = ap.parse_args(argv)

    root = args.root
    if root is None:
        root = Path(__file__).resolve().parents[2]
    try:
        findings = run_all(root, checkers=args.checkers)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({"root": str(root),
                          "findings": [f.to_dict() for f in findings]},
                         indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"nomad_tpu.analysis: {n} finding{'s' if n != 1 else ''}"
              f" in {root}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
