"""Runtime lock-order recorder: acquisition-order cycles = deadlock risk.

Static discipline (lock_discipline.py) proves accesses hold *a* lock; it
cannot see in which ORDER two locks nest across threads.  This recorder
patches `threading.Lock` / `threading.RLock` so every lock allocated
while installed is wrapped: each successful acquire records an edge
`held -> acquired` for every lock the acquiring thread already holds.
A cycle in that graph means two code paths nest the same locks in
opposite orders — a latent deadlock even if the interleaving never hit.

Locks are named by their allocation site (`file:line`, threading.py
frames skipped) so `Condition()`-internal RLocks get the caller's site.
The wrapper delegates unknown attributes to the inner lock, keeping the
`hasattr(lock, "_release_save")` probes in `threading.Condition` honest:
a wrapped RLock still presents the Condition protocol, a wrapped Lock
still doesn't.  `Condition.wait` bypasses the wrapper for its
release/reacquire pair — harmless for edge recording, since a waiting
thread acquires nothing while blocked.

Intended use (pytest):

    rec = LockOrderRecorder()
    rec.install()
    try:
        ... exercise the system ...
    finally:
        rec.uninstall()
    assert rec.cycles() == []

or process-wide via `NOMAD_TPU_LOCK_ORDER=1` (see tests/conftest.py).
"""
from __future__ import annotations

import _thread
import json
import threading
from typing import Dict, List, Optional, Set, Tuple

# Interchange format shared with the static wait-graph checker: one
# corpus feeds both (the checker merges these runtime edges into its
# static acquisition graph, since nodes share the alloc-site naming).
LOCK_ORDER_FORMAT = "nomad-tpu-lock-order/1"


def _alloc_site(skip_modules: Tuple[str, ...] = ("threading",)) -> str:
    import sys
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename
        short = fname.rsplit("/", 1)[-1]
        if short.rsplit(".", 1)[0] not in skip_modules and \
                "analysis/lock_order" not in fname.replace("\\", "/"):
            return f"{short}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class _RecordingLock:
    """Wraps one real Lock/RLock; bookkeeping on acquire/release only."""

    def __init__(self, inner, name: str, recorder: "LockOrderRecorder"):
        self._inner = inner
        self._name = name
        self._recorder = recorder

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._recorder._on_acquire(self._name)
        return got

    def release(self) -> None:
        self._recorder._on_release(self._name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str):
        # Condition() probes _release_save/_acquire_restore/_is_owned via
        # hasattr — delegate so wrapped RLocks keep the protocol and
        # wrapped Locks keep lacking it.
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<RecordingLock {self._name} over {self._inner!r}>"


class LockOrderRecorder:
    def __init__(self):
        # edge -> one sample (thread name, held-stack snapshot)
        self.edges: Dict[Tuple[str, str], Tuple[str, Tuple[str, ...]]] = {}
        self._held = threading.local()
        self._meta = _thread.allocate_lock()   # raw: never self-recorded
        self._orig: Optional[Tuple] = None

    # ---- patching

    def install(self) -> "LockOrderRecorder":
        if self._orig is not None:
            return self
        self._orig = (threading.Lock, threading.RLock)
        real_lock, real_rlock = self._orig

        def lock_factory():
            return _RecordingLock(real_lock(), _alloc_site(), self)

        def rlock_factory():
            return _RecordingLock(real_rlock(), _alloc_site(), self)

        threading.Lock = lock_factory
        threading.RLock = rlock_factory
        return self

    def uninstall(self) -> None:
        if self._orig is not None:
            threading.Lock, threading.RLock = self._orig
            self._orig = None

    def __enter__(self) -> "LockOrderRecorder":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---- bookkeeping (called from the wrapper)

    def _stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def _on_acquire(self, name: str) -> None:
        stack = self._stack()
        if stack:
            snap = tuple(stack)
            me = threading.current_thread().name
            with self._meta:
                for held in stack:
                    if held != name:
                        self.edges.setdefault((held, name), (me, snap))
        stack.append(name)

    def _on_release(self, name: str) -> None:
        stack = self._stack()
        # remove the most recent matching entry: releases may interleave
        # out of LIFO order (condition waits, manual release())
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # ---- analysis

    def graph(self) -> Dict[str, Set[str]]:
        g: Dict[str, Set[str]] = {}
        with self._meta:
            for (a, b) in self.edges:
                g.setdefault(a, set()).add(b)
        return g

    def cycles(self) -> List[List[str]]:
        """Every distinct cycle found by DFS over the acquisition graph."""
        g = self.graph()
        out: List[List[str]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in g}

        def dfs(node: str, path: List[str]) -> None:
            color[node] = GREY
            path.append(node)
            for nxt in sorted(g.get(node, ())):
                c = color.get(nxt, WHITE)
                if c == GREY:
                    cyc = path[path.index(nxt):] + [nxt]
                    canon = tuple(sorted(cyc[:-1]))
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(cyc)
                elif c == WHITE:
                    dfs(nxt, path)
            path.pop()
            color[node] = BLACK

        for n in sorted(g):
            if color.get(n, WHITE) == WHITE:
                dfs(n, [])
        return out

    def render_cycles(self) -> str:
        lines = []
        for cyc in self.cycles():
            lines.append("lock-order cycle: " + " -> ".join(cyc))
            for a, b in zip(cyc, cyc[1:]):
                sample = self.edges.get((a, b))
                if sample:
                    thread, snap = sample
                    lines.append(f"    {a} -> {b}  (thread {thread}, "
                                 f"held {list(snap)})")
        return "\n".join(lines)

    # ---- interchange with the static wait-graph checker

    def to_corpus(self) -> dict:
        """The recorded edges in the shared wait-graph corpus format."""
        with self._meta:
            edges = [{"a": a, "b": b, "thread": thread,
                      "held": list(snap)}
                     for (a, b), (thread, snap) in sorted(self.edges.items())]
        return {"format": LOCK_ORDER_FORMAT, "edges": edges}

    def dump(self, path) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_corpus(), fh, indent=1, sort_keys=True)
            fh.write("\n")


def load_lock_corpus(path) -> dict:
    """Parse and validate a dumped corpus (ValueError on foreign JSON)."""
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or \
            data.get("format") != LOCK_ORDER_FORMAT:
        raise ValueError(
            f"{path}: not a {LOCK_ORDER_FORMAT} lock-order corpus")
    return data
