"""allow-audit: every suppression must earn its keep.

An `# analysis: allow(...)` comment is a standing exception to an
invariant checker, so each one must carry a stated reason (the grammar
is `# analysis: allow(names) — reason`), and each one must still be
*doing* something — an allow no checker consulted during the run is a
dead suppression left behind by refactored code, and dead suppressions
are how real findings sneak back in silently.

This checker audits the `allow`/`allow_reason`/`allow_used` bookkeeping
that `SourceFile.allowed()` populates, so it MUST run after every other
requested checker against the same Corpus (``run_all`` arranges this:
when allow-audit is requested it runs the full suite first and discards
the findings of checkers the caller did not ask for).

Rules:

- missing reason: the comment has no `— reason` tail.  Never
  suppressible — an allow cannot excuse its own missing justification.
- unused name: a named checker in the allow that never matched a
  finding at that line during the run.  `allow(*)` is unused when no
  checker at all consulted it.  Listing ``allow-audit`` itself among
  the names opts that comment out of the unused check (for allows
  covering findings only runtime halves would raise), but not out of
  the reason requirement.
"""
from __future__ import annotations

from typing import List

from nomad_tpu.analysis.common import Corpus, Finding

CHECKER = "allow-audit"


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.py:
        for ln in sorted(sf.allow):
            names = sf.allow[ln]
            reason = sf.allow_reason.get(ln, "")
            if not reason:
                findings.append(Finding(
                    CHECKER, sf.rel, ln,
                    "allow(%s) has no stated reason — write "
                    "`# analysis: allow(%s) — why this is safe`"
                    % (", ".join(sorted(names)), ", ".join(sorted(names)))))
            if CHECKER in names:
                # opted out of the unused check (covers runtime-half
                # findings the static pass cannot see); reason already
                # enforced above
                continue
            used = sf.allow_used.get(ln, set())
            if "*" in names:
                if not used:
                    findings.append(Finding(
                        CHECKER, sf.rel, ln,
                        "allow(*) suppressed nothing this run — dead "
                        "suppression; delete it or name the checker it "
                        "is for"))
                continue
            dead = sorted(names - used)
            if dead:
                findings.append(Finding(
                    CHECKER, sf.rel, ln,
                    "allow(%s) suppressed nothing this run — dead "
                    "suppression; delete the unused name%s"
                    % (", ".join(dead), "s" if len(dead) > 1 else "")))
    return findings
