"""jax-purity: jitted scheduler kernels must stay traceable.

Host-side escapes inside a jitted function force a trace-time
materialization (`ConcretizationTypeError` at best, silent recompiles or
stale constants at worst).  For every function that is jitted —

    @jax.jit
    @functools.partial(jax.jit, static_argnames=("k",))
    fn = jax.jit(body)            # incl. jax.jit(jax.shard_map(body, …))

— this checker flags:

- `float(x)` / `int(x)` / `bool(x)` coercions of traced values
- `.item()` calls
- `np.*` calls (numpy eagerly materializes; use `jnp`)
- Python `if` branching on a traced parameter (tests that only touch
  `static_argnames` parameters are fine) — applied to directly-jitted
  defs where the static set is visible

Same-module helpers called from a jitted body are checked transitively
for the first three (a helper can't know its caller's static set, so the
branching check stays local).  `# analysis: allow(jax-purity)` on the
line or the enclosing `def` line suppresses.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, SourceFile, dotted, enclosing_def_line,
)

CHECKER = "jax-purity"

_COERCIONS = {"float", "int", "bool"}
_NP_BASES = {"np", "numpy"}
# np attrs that are fine at trace time (dtype constructors / constants)
_NP_BENIGN = {"float32", "float64", "int32", "int64", "uint32", "uint8",
              "bool_", "dtype", "pi", "inf", "nan", "newaxis", "ndarray",
              "ctypeslib"}


def _static_argnames(dec: ast.expr) -> Optional[Set[str]]:
    """static_argnames from a functools.partial(jax.jit, ...) decorator;
    None if this decorator isn't a jit form at all."""
    if isinstance(dec, ast.Call):
        target = dotted(dec.func)
        if target in ("functools.partial", "partial") and dec.args and \
                dotted(dec.args[0]) in ("jax.jit", "jit"):
            names: Set[str] = set()
            for kw in dec.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    if isinstance(kw.value, (ast.Tuple, ast.List, ast.Set)):
                        for el in kw.value.elts:
                            if isinstance(el, ast.Constant) and \
                                    isinstance(el.value, str):
                                names.add(el.value)
                    elif isinstance(kw.value, ast.Constant) and \
                            isinstance(kw.value.value, str):
                        names.add(kw.value.value)
            return names
        if target in ("jax.jit", "jit"):
            return set()
    elif dotted(dec) in ("jax.jit", "jit"):
        return set()
    return None


def _jitted_defs(sf: SourceFile) -> List[Tuple[ast.AST, Optional[Set[str]]]]:
    """(def node, static names or None-when-unknown) for every function
    the module jits, by decorator or by `jax.jit(name)` reference."""
    by_name: Dict[str, ast.AST] = {}
    out: List[Tuple[ast.AST, Optional[Set[str]]]] = []
    picked: Set[ast.AST] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                statics = _static_argnames(dec)
                if statics is not None and node not in picked:
                    picked.add(node)
                    out.append((node, statics))

    def _jit_operands(call: ast.Call) -> List[str]:
        """Names passed (possibly through shard_map) to a jax.jit call."""
        if dotted(call.func) not in ("jax.jit", "jit"):
            return []
        names: List[str] = []
        for a in call.args:
            if isinstance(a, ast.Name):
                names.append(a.id)
            elif isinstance(a, ast.Call) and \
                    (dotted(a.func) or "").split(".")[-1] == "shard_map":
                for inner in a.args:
                    if isinstance(inner, ast.Name):
                        names.append(inner.id)
        return names

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            for name in _jit_operands(node):
                fn = by_name.get(name)
                # statics unknown for call-form jits: skip branch check
                if fn is not None and fn not in picked:
                    picked.add(fn)
                    out.append((fn, None))
    return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    return names


def _check_body(sf: SourceFile, fn: ast.AST, statics: Optional[Set[str]],
                qual: str, findings: List[Finding],
                reported: Set[Tuple[str, int]]) -> Set[str]:
    """Flag escapes in one jitted def; return same-module callee names."""
    callees: Set[str] = set()
    traced = (_param_names(fn) - statics) if statics is not None else set()

    def emit(line: int, msg: str) -> None:
        if sf.allowed(CHECKER, line, enclosing_def_line(sf, line)):
            return
        key = (sf.rel, line)
        if key not in reported:
            reported.add(key)
            findings.append(Finding(CHECKER, sf.rel, line, msg, (qual,)))

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                if f.id in _COERCIONS and node.args:
                    emit(node.lineno,
                         f"`{f.id}()` coercion inside jitted kernel "
                         f"forces host materialization of a tracer")
                else:
                    callees.add(f.id)
            elif isinstance(f, ast.Attribute):
                if f.attr == "item":
                    emit(node.lineno,
                         "`.item()` inside jitted kernel pulls the value "
                         "to host at trace time")
                else:
                    base = dotted(f.value)
                    if base in _NP_BASES and f.attr not in _NP_BENIGN:
                        emit(node.lineno,
                             f"`{base}.{f.attr}()` inside jitted kernel: "
                             f"numpy runs eagerly at trace time (use jnp)")
        elif isinstance(node, ast.If) and statics is not None:
            hit = next((n.id for n in ast.walk(node.test)
                        if isinstance(n, ast.Name) and n.id in traced), None)
            if hit:
                emit(node.lineno,
                     f"Python `if` on traced parameter `{hit}` inside "
                     f"jitted kernel (mark it static or use jnp.where / "
                     f"lax.cond)")
    return callees


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    reported: Set[Tuple[str, int]] = set()
    for sf in corpus.py:
        jitted = _jitted_defs(sf)
        if not jitted:
            continue
        module_defs: Dict[str, ast.AST] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                module_defs.setdefault(node.name, node)
        seen: Set[str] = set()
        frontier: List[Tuple[ast.AST, Optional[Set[str]], str]] = [
            (fn, statics, fn.name) for fn, statics in jitted]
        while frontier:
            fn, statics, qual = frontier.pop()
            if fn.name in seen:
                continue
            seen.add(fn.name)
            callees = _check_body(sf, fn, statics, qual, findings, reported)
            for name in callees:
                tgt = module_defs.get(name)
                if tgt is not None and name not in seen:
                    # helpers: escapes only; no branch check (statics=None)
                    frontier.append((tgt, None, f"{qual} -> {name}"))
    return findings
