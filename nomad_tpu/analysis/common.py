"""Shared infrastructure for the invariant linter suite.

Everything here is stdlib-only (ast + re): the analyzers parse source
trees, they never import the code under analysis, so `python -m
nomad_tpu.analysis` runs in a bare interpreter with no jax/numpy.

Suppression grammar (checked on the finding's line and on the line of
the enclosing `def`):

    # analysis: allow(checker-name)
    # analysis: allow(checker-a, checker-b)
    # analysis: allow(*)

A suppressed call site is also removed from call-graph traversal, so an
allowed edge does not leak findings from the functions behind it.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

ALLOW_RE = re.compile(r"#\s*analysis:\s*allow\(([^)]*)\)")

# directories never scanned, wherever the root points
EXCLUDED_PARTS = {"__pycache__", ".git", "build", ".scratch", ".jax_cache"}


@dataclass
class Finding:
    """One invariant violation."""
    checker: str
    path: str               # repo-relative (or root-relative) posix path
    line: int
    message: str
    chain: Tuple[str, ...] = ()   # call chain for transitive findings

    def to_dict(self) -> dict:
        d = {"checker": self.checker, "path": self.path,
             "line": self.line, "message": self.message}
        if self.chain:
            d["chain"] = list(self.chain)
        return d

    def render(self) -> str:
        s = f"{self.path}:{self.line}: [{self.checker}] {self.message}"
        if self.chain:
            s += f"  (via {' -> '.join(self.chain)})"
        return s


class SourceFile:
    """A parsed python source file plus its allow-comment map."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=str(path))
        # dotted module name from the root-relative path:
        # nomad_tpu/state/store.py -> nomad_tpu.state.store
        mod = rel[:-3] if rel.endswith(".py") else rel
        mod = mod.replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        self.module = mod
        self._imports: Optional[Set[str]] = None
        # line -> set of checker names allowed ("*" = all)
        self.allow: Dict[int, Set[str]] = {}
        for i, line in enumerate(text.splitlines(), 1):
            m = ALLOW_RE.search(line)
            if m:
                names = {p.strip() for p in m.group(1).split(",") if p.strip()}
                if names:
                    self.allow[i] = names

    @property
    def imports(self) -> Set[str]:
        """Dotted names this module imports (absolute and resolved
        relative), including `from pkg import sub` as `pkg.sub`."""
        if self._imports is None:
            out: Set[str] = set()
            pkg = self.module if self.rel.endswith("__init__.py") \
                else self.module.rpartition(".")[0]
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        out.add(alias.name)
                elif isinstance(node, ast.ImportFrom):
                    base = node.module or ""
                    if node.level:
                        parts = pkg.split(".") if pkg else []
                        parts = parts[: len(parts) - (node.level - 1)] \
                            if node.level > 1 else parts
                        base = ".".join(parts + ([base] if base else []))
                    if base:
                        out.add(base)
                    for alias in node.names:
                        if base:
                            out.add(f"{base}.{alias.name}")
                        else:
                            out.add(alias.name)
            self._imports = out
        return self._imports

    def allowed(self, checker: str, *lines: Optional[int]) -> bool:
        for ln in lines:
            if ln is None:
                continue
            names = self.allow.get(ln)
            if names and ("*" in names or checker in names):
                return True
        return False


@dataclass
class Corpus:
    """The file set one analysis run operates on."""
    root: Path
    py: List[SourceFile] = field(default_factory=list)
    cpp: List[Tuple[Path, str, str]] = field(default_factory=list)  # (path, rel, text)


def _is_excluded(rel: Path) -> bool:
    return any(part in EXCLUDED_PARTS for part in rel.parts)


def load_corpus(root: Path, include_tests: bool = False) -> Corpus:
    """Load every .py/.cpp under `root`.

    When `root` looks like the repo checkout (contains a `nomad_tpu`
    package), only `nomad_tpu/` and `native/` are scanned so the test
    fixtures' seeded violations never pollute a repo run.  Any other
    root (a fixture dir) is scanned wholesale.
    """
    root = Path(root).resolve()
    corpus = Corpus(root=root)
    if (root / "nomad_tpu").is_dir() and not include_tests:
        search_roots = [root / "nomad_tpu", root / "native"]
    else:
        search_roots = [root]
    seen: Set[Path] = set()
    for sr in search_roots:
        if not sr.exists():
            continue
        for p in sorted(sr.rglob("*.py")):
            rel = p.relative_to(root)
            if _is_excluded(rel) or p in seen:
                continue
            seen.add(p)
            try:
                text = p.read_text()
            except (OSError, UnicodeDecodeError):
                continue
            try:
                corpus.py.append(SourceFile(p, rel.as_posix(), text))
            except SyntaxError:
                continue
        for p in sorted(sr.rglob("*.cpp")):
            rel = p.relative_to(root)
            if _is_excluded(rel) or p in seen:
                continue
            seen.add(p)
            try:
                corpus.cpp.append((p, rel.as_posix(), p.read_text()))
            except (OSError, UnicodeDecodeError):
                continue
    return corpus


# ------------------------------------------------------------------ AST utils

def call_name(call: ast.Call) -> Optional[str]:
    """Bare callee name: `f(...)` -> 'f', `a.b.f(...)` -> 'f'."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted(expr: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def decorator_names(fn: ast.AST) -> List[str]:
    """Dotted names of each decorator (call decorators yield the callee,
    so `@functools.partial(jax.jit, ...)` yields 'functools.partial')."""
    out = []
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted(target)
        if name:
            out.append(name)
    return out


@dataclass
class FuncInfo:
    """A function definition located in the corpus."""
    sf: SourceFile
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    qualname: str                  # Class.method or module-level name

    @property
    def key(self) -> str:
        return f"{self.sf.rel}::{self.qualname}"


def index_functions(files: Sequence[SourceFile]) -> Dict[str, List[FuncInfo]]:
    """name -> every def with that bare name, package-wide.  The static
    call graph resolves calls by bare name (receiver types are unknown),
    which over-approximates: good for an invariant cone, where missing an
    edge is worse than following a spurious one."""
    index: Dict[str, List[FuncInfo]] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        index.setdefault(item.name, []).append(
                            FuncInfo(sf, item, f"{node.name}.{item.name}"))
            elif isinstance(node, ast.Module):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        index.setdefault(item.name, []).append(
                            FuncInfo(sf, item, item.name))
    return index


def enclosing_def_line(sf: SourceFile, lineno: int) -> Optional[int]:
    """Line of the innermost def containing `lineno` (for def-level
    allow comments)."""
    best: Optional[int] = None
    best_span = None
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = node.lineno, span
    return best
