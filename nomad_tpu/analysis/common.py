"""Shared infrastructure for the invariant linter suite.

Everything here is stdlib-only (ast + tokenize + re): the analyzers
parse source trees, they never import the code under analysis, so
`python -m nomad_tpu.analysis` runs in a bare interpreter with no
jax/numpy.

Suppression grammar (checked on the finding's line and on the line of
the enclosing `def`); every allow must state its reason after the
closing paren (the allow-audit satellite reports reasonless and unused
allows):

    ... code ...   # analysis: allow(checker-name) — why this is safe
    ... code ...   # analysis: allow(checker-a, checker-b) — reason
    ... code ...   # analysis: allow(*) — reason

A suppressed call site is also removed from call-graph traversal, so an
allowed edge does not leak findings from the functions behind it.
Allow comments are extracted from real COMMENT tokens (tokenize), never
from docstrings or string literals, so documentation that *quotes* the
grammar does not create suppressions.

The interprocedural core shared by the cone-walking checkers
(fsm-determinism, snapshot-completeness, canonical-form, wait-graph):

    index_functions    bare-name -> every def with that name
    walk_cone          BFS over the bare-name call graph with allow
                       pruning, the EDGE_DENYLIST, and the importable
                       edge filter
    find_fsm_classes   classes shaped like a raft FSM (apply + _apply_*)
    class_attr_types   per-class `self.attr` -> constructed/annotated
                       class name (receiver resolution)
    container_kinds    per-class `self.attr` -> container constructor
                       kind from __init__ (set/dict/defaultdict/...)
    lock_alloc_sites   per-class lock attr -> `file.py:line` allocation
                       site, the SAME naming the runtime
                       LockOrderRecorder uses, so the static wait-graph
                       and the runtime corpus share one node namespace
    attr_mutations     def-use: every mutation of `<base>.<attr>` in a
                       function body (assign/subscript/augassign/del/
                       mutator-method)
    expand_aliases     local names bound to a tracked base
                       (`s = self.store` makes `s._tbl.add(...)` a
                       store-table mutation)
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

ALLOW_RE = re.compile(
    r"#\s*analysis:\s*allow\(([^)]*)\)[ \t]*(?:[—:–-]+[ \t]*)?(.*)")

# directories never scanned, wherever the root points
EXCLUDED_PARTS = {"__pycache__", ".git", "build", ".scratch", ".jax_cache"}


@dataclass
class Finding:
    """One invariant violation."""
    checker: str
    path: str               # repo-relative (or root-relative) posix path
    line: int
    message: str
    chain: Tuple[str, ...] = ()   # call chain for transitive findings

    def to_dict(self) -> dict:
        d = {"checker": self.checker, "path": self.path,
             "line": self.line, "message": self.message}
        if self.chain:
            d["chain"] = list(self.chain)
        return d

    def render(self) -> str:
        s = f"{self.path}:{self.line}: [{self.checker}] {self.message}"
        if self.chain:
            s += f"  (via {' -> '.join(self.chain)})"
        return s


class SourceFile:
    """A parsed python source file plus its allow-comment map."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=str(path))
        # dotted module name from the root-relative path:
        # nomad_tpu/state/store.py -> nomad_tpu.state.store
        mod = rel[:-3] if rel.endswith(".py") else rel
        mod = mod.replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        self.module = mod
        self._imports: Optional[Set[str]] = None
        # line -> set of checker names allowed ("*" = all)
        self.allow: Dict[int, Set[str]] = {}
        # line -> the stated reason text ("" when missing)
        self.allow_reason: Dict[int, str] = {}
        # line -> checkers that actually consulted-and-matched the allow
        # during this corpus' lifetime (fed to the allow-audit)
        self.allow_used: Dict[int, Set[str]] = {}
        for ln, names, reason in _scan_allow_comments(text):
            self.allow[ln] = names
            self.allow_reason[ln] = reason

    @property
    def imports(self) -> Set[str]:
        """Dotted names this module imports (absolute and resolved
        relative), including `from pkg import sub` as `pkg.sub`."""
        if self._imports is None:
            out: Set[str] = set()
            pkg = self.module if self.rel.endswith("__init__.py") \
                else self.module.rpartition(".")[0]
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        out.add(alias.name)
                elif isinstance(node, ast.ImportFrom):
                    base = node.module or ""
                    if node.level:
                        parts = pkg.split(".") if pkg else []
                        parts = parts[: len(parts) - (node.level - 1)] \
                            if node.level > 1 else parts
                        base = ".".join(parts + ([base] if base else []))
                    if base:
                        out.add(base)
                    for alias in node.names:
                        if base:
                            out.add(f"{base}.{alias.name}")
                        else:
                            out.add(alias.name)
            self._imports = out
        return self._imports

    def allowed(self, checker: str, *lines: Optional[int]) -> bool:
        for ln in lines:
            if ln is None:
                continue
            names = self.allow.get(ln)
            if names and ("*" in names or checker in names):
                self.allow_used.setdefault(ln, set()).add(checker)
                return True
        return False


def _scan_allow_comments(
        text: str) -> Iterator[Tuple[int, Set[str], str]]:
    """(line, names, reason) for every `# analysis: allow(...)` COMMENT
    token.  Docstrings and string literals quoting the grammar are NOT
    suppressions — only real comments count."""
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError):
        return
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = ALLOW_RE.search(tok.string)
        if not m:
            continue
        names = {p.strip() for p in m.group(1).split(",") if p.strip()}
        if names:
            yield tok.start[0], names, m.group(2).strip()


@dataclass
class Corpus:
    """The file set one analysis run operates on."""
    root: Path
    py: List[SourceFile] = field(default_factory=list)
    cpp: List[Tuple[Path, str, str]] = field(default_factory=list)  # (path, rel, text)
    # merged runtime lock-order corpus (LockOrderRecorder.dump JSON),
    # fed to the wait-graph checker when provided
    lock_corpus: Optional[dict] = None


def _is_excluded(rel: Path) -> bool:
    return any(part in EXCLUDED_PARTS for part in rel.parts)


def load_corpus(root: Path, include_tests: bool = False) -> Corpus:
    """Load every .py/.cpp under `root`.

    When `root` looks like the repo checkout (contains a `nomad_tpu`
    package), only `nomad_tpu/` and `native/` are scanned so the test
    fixtures' seeded violations never pollute a repo run.  Any other
    root (a fixture dir) is scanned wholesale.
    """
    root = Path(root).resolve()
    corpus = Corpus(root=root)
    if (root / "nomad_tpu").is_dir() and not include_tests:
        search_roots = [root / "nomad_tpu", root / "native"]
    else:
        search_roots = [root]
    seen: Set[Path] = set()
    for sr in search_roots:
        if not sr.exists():
            continue
        for p in sorted(sr.rglob("*.py")):
            rel = p.relative_to(root)
            if _is_excluded(rel) or p in seen:
                continue
            seen.add(p)
            try:
                text = p.read_text()
            except (OSError, UnicodeDecodeError):
                continue
            try:
                corpus.py.append(SourceFile(p, rel.as_posix(), text))
            except SyntaxError:
                continue
        for p in sorted(sr.rglob("*.cpp")):
            rel = p.relative_to(root)
            if _is_excluded(rel) or p in seen:
                continue
            seen.add(p)
            try:
                corpus.cpp.append((p, rel.as_posix(), p.read_text()))
            except (OSError, UnicodeDecodeError):
                continue
    return corpus


# ------------------------------------------------------------------ AST utils

def call_name(call: ast.Call) -> Optional[str]:
    """Bare callee name: `f(...)` -> 'f', `a.b.f(...)` -> 'f'."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted(expr: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def decorator_names(fn: ast.AST) -> List[str]:
    """Dotted names of each decorator (call decorators yield the callee,
    so `@functools.partial(jax.jit, ...)` yields 'functools.partial')."""
    out = []
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted(target)
        if name:
            out.append(name)
    return out


@dataclass
class FuncInfo:
    """A function definition located in the corpus."""
    sf: SourceFile
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    qualname: str                  # Class.method or module-level name

    @property
    def key(self) -> str:
        return f"{self.sf.rel}::{self.qualname}"

    @property
    def cls(self) -> Optional[str]:
        """Enclosing class name, None for module-level defs."""
        if "." in self.qualname:
            return self.qualname.rsplit(".", 1)[0]
        return None


def index_functions(files: Sequence[SourceFile]) -> Dict[str, List[FuncInfo]]:
    """name -> every def with that bare name, package-wide.  The static
    call graph resolves calls by bare name (receiver types are unknown),
    which over-approximates: good for an invariant cone, where missing an
    edge is worse than following a spurious one."""
    index: Dict[str, List[FuncInfo]] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        index.setdefault(item.name, []).append(
                            FuncInfo(sf, item, f"{node.name}.{item.name}"))
            elif isinstance(node, ast.Module):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        index.setdefault(item.name, []).append(
                            FuncInfo(sf, item, item.name))
    return index


def enclosing_def_line(sf: SourceFile, lineno: int) -> Optional[int]:
    """Line of the innermost def containing `lineno` (for def-level
    allow comments)."""
    best: Optional[int] = None
    best_span = None
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = node.lineno, span
    return best


# --------------------------------------------------- interprocedural core

# bare names whose edges are never followed: dict/list/str methods that
# collide with ubiquitous helper names and cannot reach replicated state
EDGE_DENYLIST = {
    "get", "items", "keys", "values", "append", "extend", "pop",
    "popleft", "add", "discard", "remove", "clear", "update",
    "setdefault", "sort", "sorted", "join", "split", "strip",
    "startswith", "endswith", "encode", "decode", "format", "index",
    "count", "insert", "reverse", "lower", "upper", "replace",
}


def importable(src: SourceFile, dst: SourceFile) -> bool:
    """Edge filter: a module can only call into modules it imports (or
    itself).  Prunes bare-name collisions like `subprocess.run` matching
    `Worker.run` — the native module never imports the worker."""
    if src is dst:
        return True
    dst_mod = dst.module
    return any(imp == dst_mod or imp.startswith(dst_mod + ".")
               for imp in src.imports)


def walk_cone(index: Dict[str, List[FuncInfo]],
              seeds: Sequence[FuncInfo], checker: str,
              prune=None) -> Iterator[Tuple[FuncInfo, Tuple[str, ...]]]:
    """BFS over the bare-name call graph from `seeds`, yielding each
    reachable def ONCE with the shortest call chain that reached it.

    Edges are pruned by: `# analysis: allow(<checker>)` on the call line
    or the enclosing def line (the suppression fences the whole subtree),
    the EDGE_DENYLIST, the importable() module filter, and an optional
    `prune(call_node) -> bool` (e.g. sink calls whose internals are not
    part of the cone)."""
    visited: Set[str] = set()
    queue: List[Tuple[FuncInfo, Tuple[str, ...]]] = [
        (fi, (fi.qualname,)) for fi in seeds]
    while queue:
        fi, chain = queue.pop(0)
        if fi.key in visited:
            continue
        visited.add(fi.key)
        yield fi, chain
        sf = fi.sf
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            line = node.lineno
            if sf.allowed(checker, line, enclosing_def_line(sf, line)):
                continue
            if prune is not None and prune(node):
                continue
            callee = call_name(node)
            if callee is None or callee in EDGE_DENYLIST:
                continue
            for target in index.get(callee, ()):
                if target.key not in visited and importable(sf, target.sf):
                    queue.append((target, chain + (target.qualname,)))


def find_fsm_classes(
        files: Sequence[SourceFile]) -> List[Tuple[SourceFile, ast.ClassDef]]:
    """Classes shaped like the raft FSM: an `apply` plus `_apply_*`
    dispatch methods."""
    out = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                names = {i.name for i in node.body
                         if isinstance(i, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))}
                if "apply" in names and any(n.startswith("_apply_")
                                            for n in names):
                    out.append((sf, node))
    return out


def find_class(files: Sequence[SourceFile],
               name: str) -> Optional[Tuple[SourceFile, ast.ClassDef]]:
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                return sf, node
    return None


def class_methods(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    return {i.name: i for i in cls.body
            if isinstance(i, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _annotation_name(ann: ast.AST) -> Optional[str]:
    """Bare class name from a parameter annotation (`StateStore`,
    `"StateStore"`, `state.StateStore`, `Optional[StateStore]`)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip("'\"").split(".")[-1] or None
    if isinstance(ann, ast.Subscript):
        return _annotation_name(ann.slice)
    name = dotted(ann)
    if name:
        return name.split(".")[-1]
    return None


def class_attr_types(
        files: Sequence[SourceFile]) -> Dict[str, Dict[str, str]]:
    """class name -> {self-attr: bare class name} inferred from method
    bodies: `self.x = ClassName(...)` and `self.x = param` where the
    parameter is annotated `param: ClassName`.  First binding wins."""
    out: Dict[str, Dict[str, str]] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = out.setdefault(node.name, {})
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                ann: Dict[str, str] = {}
                for a in item.args.args + item.args.kwonlyargs:
                    if a.annotation is not None:
                        t = _annotation_name(a.annotation)
                        if t:
                            ann[a.arg] = t
                for st in ast.walk(item):
                    if not (isinstance(st, ast.Assign)
                            and len(st.targets) == 1):
                        continue
                    tgt = st.targets[0]
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    v = st.value
                    if isinstance(v, ast.Call):
                        n = dotted(v.func)
                        if n:
                            attrs.setdefault(tgt.attr, n.split(".")[-1])
                    elif isinstance(v, ast.Name) and v.id in ann:
                        attrs.setdefault(tgt.attr, ann[v.id])
    return out


_CONTAINER_CTORS = {"set", "frozenset", "dict", "defaultdict", "list",
                    "deque", "OrderedDict", "Counter"}


def container_kinds(cls: ast.ClassDef) -> Dict[str, str]:
    """self-attr -> container constructor kind, from `__init__` assigns:
    `self._x = set()` -> 'set', `= defaultdict(list)` -> 'defaultdict',
    `= {}` -> 'dict', `= []` -> 'list', `= {...}` (literal) -> 'dict'."""
    out: Dict[str, str] = {}
    init = class_methods(cls).get("__init__")
    if init is None:
        return out
    for st in ast.walk(init):
        if not (isinstance(st, ast.Assign) and len(st.targets) == 1):
            continue
        tgt = st.targets[0]
        if not (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            continue
        v = st.value
        kind = None
        if isinstance(v, ast.Dict):
            kind = "dict"
        elif isinstance(v, (ast.List, ast.ListComp)):
            kind = "list"
        elif isinstance(v, (ast.Set, ast.SetComp)):
            kind = "set"
        elif isinstance(v, ast.Call):
            n = dotted(v.func)
            if n and n.split(".")[-1] in _CONTAINER_CTORS:
                kind = n.split(".")[-1]
        if kind:
            out.setdefault(tgt.attr, kind)
    return out


_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def lock_alloc_sites(
        files: Sequence[SourceFile]) -> Dict[Tuple[str, str], str]:
    """(class name, self-attr) -> `file.py:line` for every lock the
    class allocates (`self._lock = threading.RLock()` and friends).

    The naming deliberately matches the runtime LockOrderRecorder's
    `_alloc_site` (basename:lineno, threading frames skipped): a
    `threading.Condition()` wrapping nothing allocates its own RLock at
    the Condition() call line, while `Condition(self._lock)` aliases the
    wrapped lock's site — so the static wait-graph and the runtime
    corpus agree on node names and their edges merge."""
    sites: Dict[Tuple[str, str], str] = {}
    wraps: Dict[Tuple[str, str], Tuple[str, str]] = {}
    for sf in files:
        base = sf.rel.rsplit("/", 1)[-1]
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for st in ast.walk(item):
                    if not (isinstance(st, ast.Assign)
                            and len(st.targets) == 1):
                        continue
                    tgt = st.targets[0]
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and isinstance(st.value, ast.Call)):
                        continue
                    n = dotted(st.value.func)
                    ctor = n.split(".")[-1] if n else None
                    if ctor not in _LOCK_CTORS:
                        continue
                    key = (node.name, tgt.attr)
                    if ctor == "Condition" and st.value.args:
                        inner = st.value.args[0]
                        if isinstance(inner, ast.Attribute) and \
                                isinstance(inner.value, ast.Name) and \
                                inner.value.id == "self":
                            wraps[key] = (node.name, inner.attr)
                            continue
                    sites[key] = f"{base}:{st.lineno}"
    for key, target in wraps.items():
        sites[key] = sites.get(target, f"{target[0]}.{target[1]}")
    return sites


# ----------------------------------------------------- def-use helpers

# container methods that mutate their receiver in place
MUTATOR_METHODS = {"add", "append", "appendleft", "extend", "insert",
                   "discard", "remove", "clear", "update", "setdefault",
                   "pop", "popleft", "popitem"}


def _subscript_root(expr: ast.AST) -> ast.AST:
    node = expr
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def _base_attr(expr: ast.AST, bases: Set[str]) -> Optional[str]:
    """`<base>.<attr>` (possibly under Subscript chains) -> attr when
    the dotted base is tracked, else None."""
    node = _subscript_root(expr)
    if isinstance(node, ast.Attribute):
        b = dotted(node.value)
        if b is not None and b in bases:
            return node.attr
    return None


def _recv_attr(recv: ast.AST, bases: Set[str]) -> Optional[str]:
    """Receiver resolution for mutator-method calls, one chain level
    deep: `self._t.add(x)`, `self._t[k].add(x)`, and
    `self._t.setdefault(k, set()).add(x)`."""
    attr = _base_attr(recv, bases)
    if attr is not None:
        return attr
    if isinstance(recv, ast.Call) and isinstance(recv.func, ast.Attribute) \
            and recv.func.attr in ("setdefault", "get"):
        return _base_attr(recv.func.value, bases)
    return None


@dataclass
class Mutation:
    """One write to `<base>.<attr>` inside a function body."""
    attr: str
    line: int
    kind: str        # assign | subscript | augassign | del | method
    node: ast.AST    # the mutating statement/call


def attr_mutations(fn_node: ast.AST,
                   bases: Set[str]) -> List[Mutation]:
    """Every mutation of `<base>.<attr>` (base in `bases`, e.g.
    {'self'} or {'self.store', 's'}) in `fn_node`'s body:

    - `base.attr = v`               assign (wholesale rebind)
    - `base.attr[k] = v`            subscript
    - `base.attr[k] += v` etc.      augassign
    - `del base.attr[k]`            del
    - `base.attr.add(v)` etc.       method (incl. one-level chains via
                                    setdefault/get)
    """
    out: List[Mutation] = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute):
                    b = dotted(tgt.value)
                    if b is not None and b in bases:
                        out.append(Mutation(tgt.attr, node.lineno,
                                            "assign", node))
                elif isinstance(tgt, (ast.Subscript, ast.Tuple)):
                    tgts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                    for t in tgts:
                        attr = _base_attr(t, bases)
                        if attr is not None and isinstance(t, ast.Subscript):
                            out.append(Mutation(attr, node.lineno,
                                                "subscript", node))
        elif isinstance(node, ast.AugAssign):
            attr = _base_attr(node.target, bases)
            if attr is not None:
                out.append(Mutation(attr, node.lineno, "augassign", node))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = _base_attr(tgt, bases)
                if attr is not None:
                    out.append(Mutation(attr, node.lineno, "del", node))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS:
                attr = _recv_attr(f.value, bases)
                if attr is not None:
                    out.append(Mutation(attr, node.lineno, "method", node))
    return out


def expand_aliases(fn_node: ast.AST, bases: Set[str]) -> Set[str]:
    """`bases` plus every local name bound to a tracked base
    (`s = self.store` adds 's'), to a fixpoint."""
    out = set(bases)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                d = dotted(node.value)
                if d is not None and d in out and \
                        node.targets[0].id not in out:
                    out.add(node.targets[0].id)
                    changed = True
    return out


def literal_strs(node: ast.AST) -> Set[str]:
    """Every string constant inside a literal expression (tuple/set/
    frozenset/dict-keys declarations like _LOCK_PROTECTED)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)
    return out


def module_decl(sf: "SourceFile", name: str) -> Optional[ast.AST]:
    """The value expression of a module-level `name = <literal>`
    declaration, else None (the module-scope twin of class_decl)."""
    for item in sf.tree.body:
        if isinstance(item, ast.Assign):
            for tgt in item.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return item.value
        elif isinstance(item, ast.AnnAssign):
            if isinstance(item.target, ast.Name) and \
                    item.target.id == name and item.value is not None:
                return item.value
    return None


def class_decl(cls: ast.ClassDef, name: str) -> Optional[ast.AST]:
    """The value expression of a class-level `name = <literal>`
    declaration, else None."""
    for item in cls.body:
        if isinstance(item, ast.Assign):
            for tgt in item.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return item.value
        elif isinstance(item, ast.AnnAssign):
            if isinstance(item.target, ast.Name) and \
                    item.target.id == name and item.value is not None:
                return item.value
    return None


def decl_str_dict(expr: Optional[ast.AST]) -> Dict[str, str]:
    """{str: str} from a dict literal declaration, tolerating non-str
    entries (skipped)."""
    out: Dict[str, str] = {}
    if isinstance(expr, ast.Dict):
        for k, v in zip(expr.keys, expr.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                out[k.value] = v.value
    return out


# ------------------------------------------- FSM / store pair resolution

@dataclass
class FsmStorePair:
    """One raft FSM class and the lock-protected store it replicates."""
    fsm_sf: SourceFile
    fsm_cls: ast.ClassDef
    store_sf: SourceFile
    store_cls: ast.ClassDef

    @property
    def tables(self) -> Set[str]:
        """The replicated-table universe: the store's _LOCK_PROTECTED."""
        decl = class_decl(self.store_cls, "_LOCK_PROTECTED")
        return literal_strs(decl) if decl is not None else set()


def resolve_fsm_stores(files: Sequence[SourceFile],
                       attr_types: Dict[str, Dict[str, str]]
                       ) -> List[FsmStorePair]:
    """Pair every FSM class with its store: the FSM attr (usually
    `self.store`) whose inferred type is a corpus class declaring
    `_LOCK_PROTECTED`."""
    out: List[FsmStorePair] = []
    for fsm_sf, fsm_cls in find_fsm_classes(files):
        for _attr, type_name in attr_types.get(fsm_cls.name, {}).items():
            hit = find_class(files, type_name)
            if hit is None:
                continue
            store_sf, store_cls = hit
            if class_decl(store_cls, "_LOCK_PROTECTED") is not None:
                out.append(FsmStorePair(fsm_sf, fsm_cls,
                                        store_sf, store_cls))
                break
    return out


def store_bases(fi: FuncInfo, store_cls_name: str,
                attr_types: Dict[str, Dict[str, str]]) -> Set[str]:
    """Dotted base expressions through which `fi`'s body can reach the
    store: `self` inside the store class itself, `self.<attr>` for attrs
    typed as the store, parameters annotated with the store class, and
    local aliases of any of those (`s = self.store`)."""
    bases: Set[str] = set()
    if fi.cls == store_cls_name:
        bases.add("self")
    for attr, t in attr_types.get(fi.cls or "", {}).items():
        if t == store_cls_name:
            bases.add(f"self.{attr}")
    args = fi.node.args
    for a in args.args + args.kwonlyargs:
        if a.annotation is not None and \
                _annotation_name(a.annotation) == store_cls_name:
            bases.add(a.arg)
    if not bases:
        return bases
    return expand_aliases(fi.node, bases)


def receiver_classes(fi: FuncInfo,
                     attr_types: Dict[str, Dict[str, str]]
                     ) -> Dict[str, str]:
    """Dotted base expression -> class name for every way `fi`'s body
    can name an object of known class: `self`, `self.<attr>` for typed
    attrs, annotated parameters, and local aliases of each."""
    out: Dict[str, str] = {}
    if fi.cls is not None:
        out["self"] = fi.cls
    for attr, t in attr_types.get(fi.cls or "", {}).items():
        out[f"self.{attr}"] = t
    args = fi.node.args
    for a in args.args + args.kwonlyargs:
        if a.annotation is not None:
            t = _annotation_name(a.annotation)
            if t is not None:
                out.setdefault(a.arg, t)
    for base, cls in list(out.items()):
        for alias in expand_aliases(fi.node, {base}):
            out.setdefault(alias, cls)
    return out


def resolve_call_targets(fi: FuncInfo, call: ast.Call,
                         index: Dict[str, List[FuncInfo]],
                         bases: Dict[str, str],
                         corpus_classes: Optional[Set[str]] = None
                         ) -> List[FuncInfo]:
    """Precise-when-possible call resolution (used by wait-graph, where
    a spurious edge manufactures a deadlock report; the invariant-cone
    checkers keep walk_cone's over-approximation instead, where a
    MISSED edge is the dangerous direction):

    - `self.m()` / `<typed base>.m()` -> that class's `m` when it has
      one; a known class with no methods in the corpus is EXTERNAL
      (threading.Thread, stdlib) and resolves to nothing; a corpus
      class missing the method (inheritance) falls back to the
      bare-name importable set
    - `<unknown receiver>.m()` -> bare-name importable set MINUS the
      enclosing class's own `m` (a foreign receiver is not `self`)
    """
    callee = call_name(call)
    if callee is None or callee in EDGE_DENYLIST:
        return []
    f = call.func
    candidates = index.get(callee, ())
    if isinstance(f, ast.Attribute):
        b = dotted(f.value)
        cls = bases.get(b) if b is not None else None
        if cls is not None:
            typed = [t for t in candidates if t.cls == cls]
            if typed:
                return typed
            if corpus_classes is not None and cls not in corpus_classes:
                return []
            return [t for t in candidates if importable(fi.sf, t.sf)]
        return [t for t in candidates
                if t.cls != fi.cls and importable(fi.sf, t.sf)]
    return [t for t in candidates if importable(fi.sf, t.sf)]


def is_empty_ctor(expr: ast.AST) -> bool:
    """A fresh-empty container expression: `{}`, `[]`, `set()`,
    `dict()`, `list()`, `deque()`, `defaultdict(factory)` — the legal
    'reset' shape for a derived index before its builder repopulates
    it row by row."""
    if isinstance(expr, ast.Dict):
        return not expr.keys
    if isinstance(expr, ast.List):
        return not expr.elts
    if isinstance(expr, ast.Call):
        n = dotted(expr.func)
        ctor = n.split(".")[-1] if n else None
        if ctor in ("set", "dict", "list", "deque", "OrderedDict",
                    "Counter"):
            return not expr.args and not expr.keywords
        if ctor == "defaultdict":
            return True    # args are the default factory, not contents
    return False
