"""nomad_tpu.analysis — static + runtime invariant analysis plane.

Eight checkers over the repo tree (stdlib-only; never imports the code
it analyzes, so this runs without jax/numpy installed):

    fsm-determinism   no wall-clock/entropy/set-iteration in the raft
                      FSM apply cone
    lock-discipline   declared lock-protected attrs only touched under
                      their lock or in @requires_lock methods
    native-abi        ctypes bindings match the extern "C" prototypes
                      and the abi version gate
    jax-purity        no host escapes / tracer branching in jitted
                      kernels
    chaos-coverage    chaos registry and injection sites agree (incl.
                      chaos.REQUIRED_SITES pinning points to functions)
    transfer-purity   no implicit host<->device transfers in declared
                      hot-path modules (_TRANSFER_HOT_PATH)
    recompile-budget  every jit site in _RECOMPILE_TRACKED modules is
                      registered with the recompile registry
    happens-before    _RACE_TRACED declarations and race.read/write
                      hooks agree (the vector-clock detector is the
                      runtime half)

Run: `python -m nomad_tpu.analysis [--json] [--checker NAME] [--root D]`
Suppress: `# analysis: allow(checker-name)` on the finding's line or the
enclosing `def` line.  The runtime halves — lock-order recorder
(`lock_order`), vector-clock race detector (`race.RaceDetector`,
`NOMAD_TPU_RACE=1`), transfer guard (`transfer_purity.
steady_state_guard`), and recompile budget (`recompile.Budget`) — are
dynamic and not part of `run_all`.
"""
from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Sequence

from nomad_tpu.analysis import (
    chaos_coverage, fsm_determinism, jax_purity, lock_discipline,
    native_abi, race, recompile, transfer_purity,
)
from nomad_tpu.analysis.common import Corpus, Finding, load_corpus
from nomad_tpu.analysis.lock_order import LockOrderRecorder

CHECKERS = {
    fsm_determinism.CHECKER: fsm_determinism.run,
    lock_discipline.CHECKER: lock_discipline.run,
    native_abi.CHECKER: native_abi.run,
    jax_purity.CHECKER: jax_purity.run,
    chaos_coverage.CHECKER: chaos_coverage.run,
    transfer_purity.CHECKER: transfer_purity.run,
    recompile.CHECKER: recompile.run,
    race.CHECKER: race.run,
}


def run_all(root: Path, checkers: Optional[Sequence[str]] = None,
            include_tests: bool = False) -> List[Finding]:
    names = list(checkers) if checkers else list(CHECKERS)
    unknown = [n for n in names if n not in CHECKERS]
    if unknown:
        raise ValueError(f"unknown checker(s): {', '.join(unknown)} "
                         f"(known: {', '.join(CHECKERS)})")
    corpus = load_corpus(root, include_tests=include_tests)
    findings: List[Finding] = []
    for name in names:
        findings.extend(CHECKERS[name](corpus))
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings


__all__ = ["CHECKERS", "Corpus", "Finding", "LockOrderRecorder",
           "load_corpus", "race", "recompile", "run_all",
           "transfer_purity"]
