"""nomad_tpu.analysis — static + runtime invariant analysis plane.

Fifteen invariant checkers plus the suppression audit, all over the
repo tree (stdlib-only; never imports the code it analyzes, so this
runs without jax/numpy installed):

    fsm-determinism        no wall-clock/entropy/set-iteration in the
                           raft FSM apply cone
    lock-discipline        declared lock-protected attrs only touched
                           under their lock or in @requires_lock methods
    native-abi             ctypes bindings match the extern "C"
                           prototypes and the abi version gate
    jax-purity             no host escapes / tracer branching in jitted
                           kernels
    chaos-coverage         chaos registry and injection sites agree
                           (incl. chaos.REQUIRED_SITES pinning points
                           to functions)
    transfer-purity        no implicit host<->device transfers in
                           declared hot-path modules (_TRANSFER_HOT_PATH)
    recompile-budget       every jit site in _RECOMPILE_TRACKED modules
                           is registered with the recompile registry
    happens-before         _RACE_TRACED declarations and race.read/write
                           hooks agree (the vector-clock detector is the
                           runtime half)
    snapshot-completeness  every store table the FSM apply cone mutates
                           round-trips through snapshot persist AND
                           restore, and restore rebuilds derived rows
                           through the same _SNAPSHOT_DERIVED builders
                           the apply path uses
    canonical-form         values flowing into replicated state stay
                           byte-identical across peers: no set-order
                           payloads, id()-keyed rows, order-sensitive
                           float accumulation, or defaultdict
                           read-materialization on persisted tables
    wait-graph             static lock-acquisition graph (merged with
                           the runtime LockOrderRecorder corpus):
                           cycles, and locks held across blocking calls
                           not declared _LOCK_BLOCKING_OK
    context-propagation    reserved RPC-args keys (rpc/reserved.py
                           _RESERVED_KEYS) survive every declared
                           forwarding site; strips are declared or
                           re-stamped
    deadline-coverage      blocking primitives reachable from the
                           serving roots consult the request deadline;
                           stage names form a closed declared set
    donation-safety        every donate_argnums jit declares its
                           loan/adopt protocol; loaned buffers are
                           never read after dispatch or aliased into
                           caches
    knob-registry          every NOMAD_TPU_* env knob is declared in
                           nomad_tpu/knobs.py and read through its
                           typed accessors; dead and undocumented
                           entries fail
    allow-audit            every `# analysis: allow(...)` carries a
                           stated reason and suppressed something this
                           run (dead suppressions are findings)

Run: `python -m nomad_tpu.analysis [--json] [--checker NAME]
[--checkers a,b] [--lock-corpus DUMP.json] [--root D]`
Suppress: `# analysis: allow(checker-name) — reason` on the finding's
line or the enclosing `def` line.  The runtime halves — lock-order
recorder (`lock_order`, `NOMAD_TPU_LOCK_ORDER=1`, dumps the corpus
wait-graph merges), vector-clock race detector (`race.RaceDetector`,
`NOMAD_TPU_RACE=1`), transfer guard (`transfer_purity.
steady_state_guard`), and recompile budget (`recompile.Budget`) — are
dynamic and not part of `run_all`.
"""
from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Sequence

from nomad_tpu.analysis import (
    allow_audit, canonical_form, chaos_coverage, context_propagation,
    deadline_coverage, donation_safety, fsm_determinism, jax_purity,
    knob_registry, lock_discipline, native_abi, race, recompile,
    snapshot_completeness, transfer_purity, wait_graph,
)
from nomad_tpu.analysis.common import Corpus, Finding, load_corpus
from nomad_tpu.analysis.lock_order import (
    LockOrderRecorder, load_lock_corpus,
)

CHECKERS = {
    fsm_determinism.CHECKER: fsm_determinism.run,
    lock_discipline.CHECKER: lock_discipline.run,
    native_abi.CHECKER: native_abi.run,
    jax_purity.CHECKER: jax_purity.run,
    chaos_coverage.CHECKER: chaos_coverage.run,
    transfer_purity.CHECKER: transfer_purity.run,
    recompile.CHECKER: recompile.run,
    race.CHECKER: race.run,
    snapshot_completeness.CHECKER: snapshot_completeness.run,
    canonical_form.CHECKER: canonical_form.run,
    wait_graph.CHECKER: wait_graph.run,
    context_propagation.CHECKER: context_propagation.run,
    deadline_coverage.CHECKER: deadline_coverage.run,
    donation_safety.CHECKER: donation_safety.run,
    knob_registry.CHECKER: knob_registry.run,
    allow_audit.CHECKER: allow_audit.run,
}


def run_all(root: Path, checkers: Optional[Sequence[str]] = None,
            include_tests: bool = False,
            lock_corpus: Optional[dict] = None) -> List[Finding]:
    names = list(checkers) if checkers else list(CHECKERS)
    unknown = [n for n in names if n not in CHECKERS]
    if unknown:
        raise ValueError(f"unknown checker(s): {', '.join(unknown)} "
                         f"(known: {', '.join(CHECKERS)})")
    corpus = load_corpus(root, include_tests=include_tests)
    if lock_corpus is not None:
        corpus.lock_corpus = lock_corpus
    findings: List[Finding] = []
    requested = set(names)
    if allow_audit.CHECKER in requested:
        # the unused-allow audit judges `allow_used`, which only the
        # other checkers populate — so the whole suite runs against this
        # corpus and findings from checkers the caller did not request
        # are discarded; the audit itself always runs last
        for name, fn in CHECKERS.items():
            if name == allow_audit.CHECKER:
                continue
            out = fn(corpus)
            if name in requested:
                findings.extend(out)
        findings.extend(allow_audit.run(corpus))
    else:
        for name in names:
            findings.extend(CHECKERS[name](corpus))
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings


__all__ = ["CHECKERS", "Corpus", "Finding", "LockOrderRecorder",
           "context_propagation", "deadline_coverage",
           "donation_safety", "knob_registry", "load_corpus",
           "load_lock_corpus", "race", "recompile", "run_all",
           "transfer_purity"]
