"""native-abi: the ctypes bindings must match the `extern "C"` surface.

Parses every prototype inside `extern "C" { ... }` blocks of the corpus
.cpp files and cross-checks the .py files that declare `lib.<fn>.argtypes`
/ `.restype`:

- every exported function with parameters has `argtypes` declared
- argument count matches the prototype
- each ctype is compatible with the C parameter type (ndpointer dtypes
  are resolved from the binding module's own helper assignments)
- every non-void function declares `restype`; VOID functions must set
  `restype = None` explicitly — ctypes silently defaults restype to
  c_int, which reads a garbage register on void returns
- the binding's `nomad_native_abi_version` gate compares against the
  version the .cpp actually returns

Bindings for functions absent from the .cpp (stale bindings) are flagged
too — that's the drift direction ctypes never catches at runtime.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import Corpus, Finding, SourceFile

CHECKER = "native-abi"

_EXTERN_RE = re.compile(r'extern\s+"C"\s*\{(.*)\}', re.DOTALL)
_PROTO_RE = re.compile(
    r'^[ \t]*((?:[A-Za-z_][\w]*[ \t*]+)+)'     # return type tokens
    r'([A-Za-z_]\w*)[ \t]*'                    # function name
    r'\(([^)]*)\)[ \t]*\{',                    # params up to the body
    re.MULTILINE | re.DOTALL)

# canonical C scalar/pointer type -> acceptable ctypes names
_SCALAR_OK = {
    "int": {"c_int", "c_int32"},
    "int32_t": {"c_int32", "c_int"},
    "uint32_t": {"c_uint32", "c_uint"},
    "int64_t": {"c_int64", "c_longlong"},
    "size_t": {"c_size_t"},
    "float": {"c_float"},
    "double": {"c_double"},
    "char*": {"c_char_p"},
}
_PTR_DTYPE = {
    "float*": "float32",
    "double*": "float64",
    "int32_t*": "int32",
    "uint32_t*": "uint32",
    "uint8_t*": "uint8",
    "int8_t*": "int8",
    "int64_t*": "int64",
    "uint64_t*": "uint64",
}

_NP_DTYPES = {"float32", "float64", "int8", "int32", "int64",
              "uint8", "uint32", "uint64"}


def _canon_ctype(raw: str) -> str:
    """'const float* capacity' -> 'float*'; 'int n_rows' -> 'int'."""
    raw = raw.strip()
    raw = re.sub(r"\bconst\b", "", raw)
    raw = raw.replace("*", " * ")
    toks = raw.split()
    if toks and toks[-1] != "*" and re.match(r"^[A-Za-z_]\w*$", toks[-1]) \
            and len(toks) > 1:
        toks = toks[:-1]           # drop the parameter name
    return "".join(toks)


class _CFunc:
    def __init__(self, name: str, ret: str, params: List[str], line: int):
        self.name = name
        self.ret = ret
        self.params = params
        self.line = line


def _parse_cpp(text: str) -> Dict[str, _CFunc]:
    out: Dict[str, _CFunc] = {}
    m = _EXTERN_RE.search(text)
    body = m.group(1) if m else text
    offset = text[:m.start(1)].count("\n") if m else 0
    # strip comments so commented-out prototypes don't register
    stripped = re.sub(r"//[^\n]*", "", body)
    for pm in _PROTO_RE.finditer(stripped):
        if re.search(r"\bstatic\b|\binline\b", pm.group(1)):
            continue                # internal helper, not part of the ABI
        ret = _canon_ctype(pm.group(1) + " _")   # reuse param canon; fake name
        name = pm.group(2)
        raw_params = pm.group(3).strip()
        params = []
        if raw_params and raw_params != "void":
            params = [_canon_ctype(p) for p in raw_params.split(",")]
        line = offset + stripped[:pm.start()].count("\n") + 1
        out[name] = _CFunc(name, ret, params, line)
    return out


def _abi_version_value(cpp_text: str) -> Optional[int]:
    m = re.search(r"nomad_native_abi_version\s*\([^)]*\)\s*\{\s*return\s+"
                  r"(\d+)\s*;", cpp_text)
    return int(m.group(1)) if m else None


# ------------------------------------------------------------------ bindings

class _Binding:
    def __init__(self):
        self.argtypes: Optional[List[str]] = None   # canonical ctype names
        self.argtypes_line: int = 0
        self.restype: Optional[str] = "UNSET"       # canonical or None/"UNSET"
        self.restype_line: int = 0


def _ndpointer_dtypes(sf: SourceFile) -> Dict[str, str]:
    """Helper-name -> numpy dtype for `X = np.ctypeslib.ndpointer(np.T,…)`
    (and direct ndpointer calls resolved inline elsewhere)."""
    out: Dict[str, str] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            dt = _dtype_of_ndpointer(node.value)
            if dt:
                out[node.targets[0].id] = dt
    return out


def _dtype_of_ndpointer(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "ndpointer" and node.args:
        a = node.args[0]
        if isinstance(a, ast.Attribute) and a.attr in _NP_DTYPES:
            return a.attr
        if isinstance(a, ast.Constant) and a.value in _NP_DTYPES:
            return a.value
    return None


def _ctype_token(node: ast.AST, helpers: Dict[str, str]) -> str:
    """One element of an argtypes list -> canonical token:
    'nd:<dtype>' for ndpointers, ctypes member name otherwise."""
    if isinstance(node, ast.Name):
        if node.id in helpers:
            return f"nd:{helpers[node.id]}"
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr                          # ctypes.c_int -> c_int
    dt = _dtype_of_ndpointer(node)
    if dt:
        return f"nd:{dt}"
    return "?"


def _collect_bindings(sf: SourceFile) -> Dict[str, _Binding]:
    helpers = _ndpointer_dtypes(sf)
    out: Dict[str, _Binding] = {}
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute) and
                isinstance(tgt.value, ast.Attribute)):
            continue
        fn_name = tgt.value.attr
        b = out.setdefault(fn_name, _Binding())
        if tgt.attr == "argtypes" and isinstance(node.value,
                                                 (ast.List, ast.Tuple)):
            b.argtypes = [_ctype_token(el, helpers)
                          for el in node.value.elts]
            b.argtypes_line = node.lineno
        elif tgt.attr == "restype":
            if isinstance(node.value, ast.Constant) and \
                    node.value.value is None:
                b.restype = None
            else:
                b.restype = _ctype_token(node.value, helpers)
            b.restype_line = node.lineno
    return out


def _gate_versions(sf: SourceFile) -> List[Tuple[int, int]]:
    """(compared value, line) for `... nomad_native_abi_version() ==/!= N`
    — directly or through a variable (`got = lib.…(); if got != N`)."""
    gate_names: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                _is_abi_call(node.value):
            gate_names.add(node.targets[0].id)
    out = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Compare) and len(node.comparators) == 1:
            left, right = node.left, node.comparators[0]
            for a, b in ((left, right), (right, left)):
                direct = _is_abi_call(a)
                via_var = isinstance(a, ast.Name) and a.id in gate_names
                if (direct or via_var) and isinstance(b, ast.Constant) \
                        and isinstance(b.value, int):
                    out.append((b.value, node.lineno))
    return out


def _is_abi_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and \
        isinstance(node.func, ast.Attribute) and \
        node.func.attr == "nomad_native_abi_version"


def _compatible(ctok: str, cparam: str) -> bool:
    if cparam in _PTR_DTYPE:
        return ctok == f"nd:{_PTR_DTYPE[cparam]}" or ctok == "c_void_p"
    if cparam in _SCALAR_OK:
        return ctok in _SCALAR_OK[cparam]
    return True                                   # unknown C type: no claim


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    cfuncs: Dict[str, Tuple[str, _CFunc]] = {}
    abi_cpp: Optional[int] = None
    for _, rel, text in corpus.cpp:
        if 'extern "C"' not in text:
            continue
        for name, cf in _parse_cpp(text).items():
            cfuncs[name] = (rel, cf)
        v = _abi_version_value(text)
        if v is not None:
            abi_cpp = v
    if not cfuncs:
        return []

    binding_files = [sf for sf in corpus.py
                     if any(isinstance(n, ast.Assign) and n.targets and
                            isinstance(n.targets[0], ast.Attribute) and
                            n.targets[0].attr in ("argtypes", "restype") and
                            isinstance(n.targets[0].value, ast.Attribute)
                            for n in ast.walk(sf.tree))]
    if not binding_files:
        return []

    for sf in binding_files:
        bindings = _collect_bindings(sf)
        gates = _gate_versions(sf)

        def emit(line: int, msg: str) -> None:
            from nomad_tpu.analysis.common import enclosing_def_line
            if not sf.allowed(CHECKER, line, enclosing_def_line(sf, line)):
                findings.append(Finding(CHECKER, sf.rel, line, msg))

        for name, (crel, cf) in cfuncs.items():
            b = bindings.get(name)
            if name == "nomad_native_abi_version":
                if abi_cpp is not None and gates and \
                        all(v != abi_cpp for v, _ in gates):
                    emit(gates[0][1],
                         f"abi version gate compares against "
                         f"{gates[0][0]} but {crel} returns {abi_cpp}")
                if abi_cpp is not None and not gates:
                    emit(1, f"no abi version gate: binding never checks "
                            f"nomad_native_abi_version() (== {abi_cpp})")
            if b is None or b.argtypes is None:
                if cf.params:
                    emit(b.restype_line if b else 1,
                         f"`{name}` exported by {crel}:{cf.line} has no "
                         f"argtypes declaration (ctypes would not check "
                         f"{len(cf.params)} args)")
                if cf.ret != "void" and (b is None or b.restype == "UNSET") \
                        and name != "nomad_native_abi_version":
                    emit(1, f"`{name}` returns {cf.ret} but restype is "
                            f"undeclared (ctypes defaults to c_int)")
                continue
            if len(b.argtypes) != len(cf.params):
                emit(b.argtypes_line,
                     f"`{name}` argtypes declares {len(b.argtypes)} args "
                     f"but {crel}:{cf.line} takes {len(cf.params)}")
            else:
                for i, (ctok, cparam) in enumerate(zip(b.argtypes,
                                                       cf.params)):
                    if not _compatible(ctok, cparam):
                        emit(b.argtypes_line,
                             f"`{name}` arg {i}: binding declares {ctok} "
                             f"but C prototype wants `{cparam}`")
            if cf.ret == "void":
                if b.restype == "UNSET":
                    emit(b.argtypes_line,
                         f"`{name}` returns void but restype is not set "
                         f"to None (ctypes defaults to c_int and reads a "
                         f"garbage register)")
                elif b.restype is not None:
                    emit(b.restype_line,
                         f"`{name}` returns void but restype is "
                         f"{b.restype}")
            else:
                if b.restype == "UNSET":
                    emit(b.argtypes_line,
                         f"`{name}` returns {cf.ret} but restype is "
                         f"undeclared (ctypes defaults to c_int)")
                elif b.restype is None or not _compatible(b.restype, cf.ret):
                    emit(b.restype_line or b.argtypes_line,
                         f"`{name}` returns {cf.ret} but restype is "
                         f"{b.restype}")

        for name, b in bindings.items():
            if name not in cfuncs and b.argtypes is not None:
                emit(b.argtypes_line,
                     f"stale binding: `{name}` is not exported by any "
                     f"extern \"C\" block in the corpus")
    return findings
