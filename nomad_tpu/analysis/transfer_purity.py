"""transfer-purity: hot-path code must not move world bytes implicitly.

The device-resident world (PR 6) only pays off if steady-state dispatch
ships zero host<->device bytes.  Modules on that path opt in with

    _TRANSFER_HOT_PATH = True          # checked
    _TRANSFER_UPLOAD_SITE = True       # also sanctioned to device_put

and the checker flags, inside every function of a hot-path module:

- `jax.device_put(...)` anywhere in a module that is not a declared
  upload site (uploads belong in world.py; a cache-fill device_put
  elsewhere carries an `# analysis: allow(transfer-purity)` with its
  reason);
- `np.asarray()` / `np.array()` / `np.copy()` / `float()` / `int()` /
  `bool()` / `.item()` applied to a device-valued name — an implicit
  device->host sync (use `jax.device_get` and say so);
- `if x:` / `while x:` on a bare device-valued name — `__bool__` syncs;
- a numpy-valued name passed positionally to a same-module jitted
  kernel — an implicit host->device transfer (device_put it explicitly,
  which the runtime guard permits).

"Device-valued" is a per-function heuristic: parameters/locals ending in
`_dev`, names assigned from `jax.device_put(...)`, and comprehension /
for-loop variables iterating over such a name.  "Numpy-valued" means
assigned from an `np.*`/`numpy.*` call in the same function.

The runtime half is `steady_state_guard()`: flips the process-wide
`jax_transfer_guard` to "disallow" (the context-manager form is
thread-scoped and would miss the engine thread) so any implicit
host->device or device->device transfer raises inside the dispatch loop.
bench.py arms it after warmup; on the CPU backend implicit
device->host is zero-copy and invisible to the guard, so steady-state
re-uploads are asserted separately from `DeviceWorld.stats`.
"""
from __future__ import annotations

import ast
import contextlib
from typing import Dict, Iterator, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, SourceFile, dotted, enclosing_def_line,
)

CHECKER = "transfer-purity"

_COERCIONS = {"float", "int", "bool"}
_NP_BASES = {"np", "numpy"}
_NP_SYNCS = {"asarray", "array", "copy"}
_DEVICE_PUT = {"jax.device_put", "device_put"}
_JIT = {"jax.jit", "jit"}


def _module_flag(sf: SourceFile, name: str) -> bool:
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == name and \
                isinstance(node.value, ast.Constant) and \
                node.value.value is True:
            return True
    return False


def _jitted_names(sf: SourceFile) -> Set[str]:
    """Defs jitted by decorator plus names assigned from jax.jit(...)."""
    out: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = dotted(target)
                if name in _JIT:
                    out.add(node.name)
                elif name in ("functools.partial", "partial") and \
                        isinstance(dec, ast.Call) and dec.args and \
                        dotted(dec.args[0]) in _JIT:
                    out.add(node.name)
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                dotted(node.value.func) in _JIT:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _walk_local(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk `fn` without descending into nested defs/classes (they are
    visited as functions of their own, so descending double-reports)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))


def _device_names(fn: ast.AST) -> Set[str]:
    """Names the heuristic treats as device arrays inside `fn`."""
    out: Set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        if p.arg.endswith("_dev"):
            out.add(p.arg)

    def _targets(t: ast.AST) -> Iterator[str]:
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                yield from _targets(el)

    for node in _walk_local(fn):
        if isinstance(node, ast.Assign):
            is_put = isinstance(node.value, ast.Call) and \
                dotted(node.value.func) in _DEVICE_PUT
            for t in node.targets:
                for name in _targets(t):
                    if is_put or name.endswith("_dev"):
                        out.add(name)
    # propagate through one level of iteration: `for x in packed_dev:`
    # and `[f(x) for x in packed_dev]` make x device-valued
    changed = True
    while changed:
        changed = False
        for node in _walk_local(fn):
            it, tgt = None, None
            if isinstance(node, ast.For):
                it, tgt = node.iter, node.target
            elif isinstance(node, ast.comprehension):
                it, tgt = node.iter, node.target
            if isinstance(it, ast.Name) and it.id in out:
                for name in _targets(tgt):
                    if name not in out:
                        out.add(name)
                        changed = True
    return out


def _numpy_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in _walk_local(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                isinstance(node.value.func, ast.Attribute) and \
                dotted(node.value.func.value) in _NP_BASES:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _check_fn(sf: SourceFile, fn: ast.AST, upload_site: bool,
              jitted: Set[str], findings: List[Finding]) -> None:
    dev = _device_names(fn)
    npv = _numpy_names(fn)

    def emit(line: int, msg: str) -> None:
        if not sf.allowed(CHECKER, line, enclosing_def_line(sf, line)):
            findings.append(Finding(CHECKER, sf.rel, line, msg, (fn.name,)))

    for node in _walk_local(fn):
        if isinstance(node, ast.Call):
            f = node.func
            callee = dotted(f)
            if callee in _DEVICE_PUT and not upload_site:
                emit(node.lineno,
                     "`jax.device_put` outside the sanctioned upload "
                     "site (world.py owns uploads; annotate cache fills "
                     "with a reason)")
            elif isinstance(f, ast.Name) and f.id in _COERCIONS and \
                    len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in dev:
                emit(node.lineno,
                     f"`{f.id}({node.args[0].id})` syncs a device array "
                     f"to host on the hot path")
            elif isinstance(f, ast.Attribute):
                if f.attr == "item" and isinstance(f.value, ast.Name) and \
                        f.value.id in dev:
                    emit(node.lineno,
                         f"`{f.value.id}.item()` syncs a device array "
                         f"to host on the hot path")
                elif f.attr in _NP_SYNCS and \
                        dotted(f.value) in _NP_BASES and node.args and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id in dev:
                    emit(node.lineno,
                         f"`np.{f.attr}({node.args[0].id})` implicitly "
                         f"syncs a device array to host (use "
                         f"`jax.device_get`)")
            if isinstance(f, ast.Name) and f.id in jitted:
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in npv:
                        emit(node.lineno,
                             f"numpy value `{arg.id}` passed to jitted "
                             f"kernel `{f.id}`: implicit host->device "
                             f"transfer (device_put it explicitly)")
        elif isinstance(node, (ast.If, ast.While)):
            t = node.test
            if isinstance(t, ast.Name) and t.id in dev:
                emit(node.lineno,
                     f"truth-test on device array `{t.id}` forces a "
                     f"host sync (`__bool__`)")


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.py:
        if not _module_flag(sf, "_TRANSFER_HOT_PATH"):
            continue
        upload_site = _module_flag(sf, "_TRANSFER_UPLOAD_SITE")
        jitted = _jitted_names(sf)
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_fn(sf, node, upload_site, jitted, findings)
    return findings


# ===================================================================== runtime

@contextlib.contextmanager
def steady_state_guard(enabled: bool = True) -> Iterator[None]:
    """Process-wide `jax_transfer_guard = "disallow"` for the duration.

    Covers every thread (the dispatch loop runs on the engine thread,
    which `with jax.transfer_guard(...)` — thread-local — would miss).
    Explicit `jax.device_put` / `jax.device_get` stay permitted; any
    implicit host->device or device->device transfer raises.
    """
    if not enabled:
        yield
        return
    import jax  # runtime-only: the static half must import without jax
    prev = getattr(jax.config, "jax_transfer_guard", None)
    jax.config.update("jax_transfer_guard", "disallow")
    try:
        yield
    finally:
        jax.config.update("jax_transfer_guard", prev or "allow")
