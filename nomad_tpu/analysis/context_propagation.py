"""context-propagation: reserved RPC-args keys survive every forward.

Request-scoped context rides RPC args dicts in underscore-prefixed
reserved keys (trace context, deadline budget, read classification,
hop guard).  The failure mode is silent: a site that re-constructs,
copies, or filters an args dict on a forwarding path drops a key and
the request runs untraced / unbounded / unclassified on the far side.

`nomad_tpu/rpc/reserved.py` declares the contract as module-level
literals this checker parses from the AST (never imported):

    _RESERVED_KEYS      key -> one-line meaning (the key universe)
    _THREAD_KEYS        keys `restamp()` recovers from thread-locals
    _FORWARDING_SITES   qualname -> (kind, keys the site must stamp);
                        "origin" sites build fresh args and must cover
                        every thread-recoverable key
    _ALLOWED_STRIPS     (site, key) pairs where a pop is deliberate
                        consumption
    _WIRE_HEADERS       HTTP header spelling -> key (stamping the
                        header is stamping the key)

Findings: a declared site that does not exist; a site missing a stamp
of a declared key (a dict-store of the key or its module-level alias
constant, a `restamp(...)` call for thread keys, or a wire-header
stamp); an "origin" declaration not covering the thread keys; a
pop/del of a reserved key at a site that is neither an allowed strip
nor re-stamped later in the same function; an underscore-prefixed key
stamped or popped at a site but absent from the registry; a filtered
dict-comprehension rebuild inside a site; a pop/del of a reserved key
elsewhere in a module that hosts a site; a registered key that never
occurs outside the registry (dead key).

Suppress with `# analysis: allow(context-propagation) — reason`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, FuncInfo, SourceFile, call_name, dotted,
    enclosing_def_line, index_functions, literal_strs, module_decl,
)

CHECKER = "context-propagation"


def _find_registry(corpus: Corpus) -> Optional[SourceFile]:
    for sf in corpus.py:
        if isinstance(module_decl(sf, "_RESERVED_KEYS"), ast.Dict):
            return sf
    return None


def _reserved_keys(sf: SourceFile) -> Dict[str, int]:
    """key -> declaration line from the _RESERVED_KEYS dict literal."""
    out: Dict[str, int] = {}
    decl = module_decl(sf, "_RESERVED_KEYS")
    if isinstance(decl, ast.Dict):
        for k in decl.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out[k.value] = k.lineno
    return out


def _sites(sf: SourceFile) -> Dict[str, Tuple[str, Tuple[str, ...], int]]:
    """qualname -> (kind, required keys, declaration line)."""
    out: Dict[str, Tuple[str, Tuple[str, ...], int]] = {}
    decl = module_decl(sf, "_FORWARDING_SITES")
    if not isinstance(decl, ast.Dict):
        return out
    for k, v in zip(decl.keys, decl.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, (ast.Tuple, ast.List)) and v.elts):
            continue
        kind = v.elts[0].value \
            if isinstance(v.elts[0], ast.Constant) else "forward"
        keys = tuple(sorted(literal_strs(v.elts[1]))) \
            if len(v.elts) > 1 else ()
        out[k.value] = (str(kind), keys, k.lineno)
    return out


def _strips(sf: SourceFile) -> Dict[str, Set[str]]:
    out: Dict[str, Set[str]] = {}
    decl = module_decl(sf, "_ALLOWED_STRIPS")
    if isinstance(decl, ast.Dict):
        for k, v in zip(decl.keys, decl.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out[k.value] = literal_strs(v)
    return out


def _wire_headers(sf: SourceFile) -> Dict[str, str]:
    out: Dict[str, str] = {}
    decl = module_decl(sf, "_WIRE_HEADERS")
    if isinstance(decl, ast.Dict):
        for k, v in zip(decl.keys, decl.values):
            if isinstance(k, ast.Constant) and isinstance(v, ast.Constant):
                out[str(k.value)] = str(v.value)
    return out


def _key_aliases(corpus: Corpus, reserved: Dict[str, int]) -> Dict[str, str]:
    """Module-level `TRACE_KEY = "_trace"`-style constants, corpus-wide:
    alias name -> reserved key.  Stamping `args[deadline.DEADLINE_KEY]`
    is stamping `_deadline`."""
    out: Dict[str, str] = {}
    for sf in corpus.py:
        for item in sf.tree.body:
            if isinstance(item, ast.Assign) and \
                    isinstance(item.value, ast.Constant) and \
                    isinstance(item.value.value, str) and \
                    item.value.value in reserved:
                for t in item.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = item.value.value
    return out


def _key_of(expr: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """A dict key expression resolved to its string: a literal, or a
    Name/Attribute whose last component is a known key constant."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    d = dotted(expr)
    if d is not None:
        return aliases.get(d.split(".")[-1])
    return None


def _scan_fn(fi: FuncInfo, aliases: Dict[str, str],
             wire: Dict[str, str]):
    """(stores, pops, restamp_lines, header_stamps, filtered_comps) —
    each a list of (key, line) except restamp_lines/filtered_comps."""
    stores: List[Tuple[str, int]] = []
    pops: List[Tuple[str, int]] = []
    restamps: List[int] = []
    headers: List[Tuple[str, int]] = []
    comps: List[int] = []
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    key = _key_of(tgt.slice, aliases)
                    if key is not None:
                        stores.append((key, node.lineno))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    key = _key_of(tgt.slice, aliases)
                    if key is not None:
                        pops.append((key, node.lineno))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "pop" \
                    and node.args:
                key = _key_of(node.args[0], aliases)
                if key is not None:
                    pops.append((key, node.lineno))
            elif call_name(node) == "restamp":
                restamps.append(node.lineno)
            for arg in node.args:
                if isinstance(arg, ast.Constant) and arg.value in wire:
                    headers.append((wire[arg.value], node.lineno))
        elif isinstance(node, ast.DictComp) and \
                any(gen.ifs for gen in node.generators):
            comps.append(node.lineno)
    return stores, pops, restamps, headers, comps


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    reg_sf = _find_registry(corpus)
    if reg_sf is None:
        return findings
    reserved = _reserved_keys(reg_sf)
    thread_keys = literal_strs(module_decl(reg_sf, "_THREAD_KEYS") or
                               ast.Tuple(elts=[], ctx=ast.Load()))
    sites = _sites(reg_sf)
    strips = _strips(reg_sf)
    wire = _wire_headers(reg_sf)
    aliases = _key_aliases(corpus, reserved)
    index = index_functions(corpus.py)

    site_files: Dict[str, List[Tuple[int, int]]] = {}  # rel -> fn spans

    for qualname, (kind, req_keys, decl_line) in sorted(sites.items()):
        bare = qualname.split(".")[-1]
        matches = [fi for fi in index.get(bare, ())
                   if fi.qualname == qualname]
        if not matches:
            if not reg_sf.allowed(CHECKER, decl_line):
                findings.append(Finding(
                    CHECKER, reg_sf.rel, decl_line,
                    f"declared forwarding site `{qualname}` does not "
                    f"exist in the corpus (dead declaration)"))
            continue
        if kind == "origin" and not set(req_keys) >= set(thread_keys):
            if not reg_sf.allowed(CHECKER, decl_line):
                undeclared = sorted(set(thread_keys) - set(req_keys))
                findings.append(Finding(
                    CHECKER, reg_sf.rel, decl_line,
                    f"origin site `{qualname}` must declare every "
                    f"thread-recoverable key; missing "
                    f"{', '.join(undeclared)}"))
        for fi in matches:
            sf = fi.sf
            end = getattr(fi.node, "end_lineno", fi.node.lineno)
            site_files.setdefault(sf.rel, []).append(
                (fi.node.lineno, end))
            stores, pops, restamps, headers, comps = \
                _scan_fn(fi, aliases, wire)
            stamped = {k for k, _ in stores} | {k for k, _ in headers}
            if restamps:
                stamped |= set(thread_keys)
            missing = [k for k in req_keys if k not in stamped]
            if missing and not sf.allowed(CHECKER, fi.node.lineno):
                findings.append(Finding(
                    CHECKER, sf.rel, fi.node.lineno,
                    f"forwarding site `{qualname}` never stamps "
                    f"{', '.join(missing)} (declared in "
                    f"{reg_sf.rel} _FORWARDING_SITES)"))
            for key, line in pops:
                if key not in reserved:
                    continue
                if key in strips.get(qualname, set()):
                    continue
                if any(k == key and ln > line for k, ln in stores):
                    continue  # pop-then-restore (the hop counter)
                if key in thread_keys and any(ln > line
                                              for ln in restamps):
                    continue
                if sf.allowed(CHECKER, line,
                              enclosing_def_line(sf, line)):
                    continue
                findings.append(Finding(
                    CHECKER, sf.rel, line,
                    f"site `{qualname}` strips reserved key `{key}` "
                    f"without an _ALLOWED_STRIPS entry or a later "
                    f"re-stamp"))
            for key, line in stores + pops:
                if key.startswith("_") and key not in reserved and \
                        not sf.allowed(CHECKER, line,
                                       enclosing_def_line(sf, line)):
                    findings.append(Finding(
                        CHECKER, sf.rel, line,
                        f"site `{qualname}` handles underscore key "
                        f"`{key}` that is not in _RESERVED_KEYS"))
            for line in comps:
                if not sf.allowed(CHECKER, line,
                                  enclosing_def_line(sf, line)):
                    findings.append(Finding(
                        CHECKER, sf.rel, line,
                        f"site `{qualname}` rebuilds a dict through a "
                        f"filtered comprehension — reserved keys may "
                        f"be dropped wholesale"))

    # modules hosting a site: a reserved-key pop/del in ANY other
    # function there is a propagation hazard (the site's contract can
    # be bypassed by a helper that strips first)
    for sf in corpus.py:
        spans = site_files.get(sf.rel)
        if not spans or sf is reg_sf:
            continue
        for node in ast.walk(sf.tree):
            key = line = None
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "pop" and node.args:
                key, line = _key_of(node.args[0], aliases), node.lineno
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        key = _key_of(tgt.slice, aliases)
                        line = node.lineno
            if key is None or key not in reserved or line is None:
                continue
            if any(lo <= line <= hi for lo, hi in spans):
                continue  # inside a declared site: judged above
            if sf.allowed(CHECKER, line, enclosing_def_line(sf, line)):
                continue
            findings.append(Finding(
                CHECKER, sf.rel, line,
                f"reserved key `{key}` stripped outside any declared "
                f"forwarding site in a module that hosts one"))

    # dead keys: registered but never spelled anywhere else
    for key, line in sorted(reserved.items()):
        alive = False
        for sf in corpus.py:
            if sf is reg_sf:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Constant) and node.value == key:
                    alive = True
                    break
            if alive:
                break
        if not alive and not reg_sf.allowed(CHECKER, line):
            findings.append(Finding(
                CHECKER, reg_sf.rel, line,
                f"reserved key `{key}` is registered but never used "
                f"outside the registry (dead key)"))
    return findings
