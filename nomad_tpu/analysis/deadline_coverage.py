"""deadline-coverage: every wait in the serving cone honors the budget.

End-to-end deadlines only work if every queueing/parking stage between
ingress and reply consults the bound budget: one timeout-less
`future.result()` or `cv.wait()` on the request path and a 500ms
deadline request can park for 30s.  The deadline module declares the
contract as module-level literals this checker parses from the AST:

    _DEADLINE_STAGES    the closed set of stage names; `check(stage)`
                        counts `deadline.expired.<stage>`, so this
                        tuple IS the telemetry namespace
    _SERVING_ROOTS      request-ingress qualnames (fnmatch patterns)
                        seeding the reachability cone
    _SERVING_MODULES    modules whose reached functions are judged
                        (the cone also crosses helper modules whose
                        waits are not request-scoped; those stay out)

Findings:

    D1  `deadline.check/expire(<non-literal>)` — stages must be
        spellable or the counter namespace drifts silently
    D2  a stage literal not declared in _DEADLINE_STAGES
    D3  a declared stage no call site ever checks/expires (dead stage:
        its `deadline.expired.<stage>` counter can never fire)
    D4  a blocking primitive (`.result()`, `.wait()`,
        `.wait_for_index()`, `.wait_for()`, a timeout-less `.get()`,
        `sleep` inside a retry loop) in a function reachable from a
        serving root, inside a serving module, whose enclosing
        function never consults the deadline (check/expire/remaining/
        current/expired)

The cone walk shares walk_cone's over-approximation (bare-name edges,
import-filtered); handlers dispatched purely via getattr — the HTTP
`_h_*` table — are reached through their dispatcher roots, not by
name.  Suppress with `# analysis: allow(deadline-coverage) — reason`.
"""
from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, SourceFile, dotted, enclosing_def_line,
    index_functions, literal_strs, module_decl, walk_cone,
)

CHECKER = "deadline-coverage"

_STAGE_CALLS = {"check", "expire"}
_CONSULT_CALLS = {"check", "expire", "remaining", "current", "expired"}
_BLOCKING_ATTRS = {"result", "wait", "wait_for_index", "wait_for"}


def _find_decl(corpus: Corpus) -> Optional[SourceFile]:
    for sf in corpus.py:
        if module_decl(sf, "_DEADLINE_STAGES") is not None:
            return sf
    return None


def _stage_entries(sf: SourceFile) -> List[Tuple[str, int]]:
    """(stage, declaration line) in declaration order."""
    decl = module_decl(sf, "_DEADLINE_STAGES")
    out: List[Tuple[str, int]] = []
    if isinstance(decl, (ast.Tuple, ast.List, ast.Set)):
        for elt in decl.elts:
            if isinstance(elt, ast.Constant) and \
                    isinstance(elt.value, str):
                out.append((elt.value, elt.lineno))
    return out


def _deadline_attr(node: ast.Call) -> Optional[str]:
    """The method name when `node` is a call on a deadline-ish base
    (`deadline.check(...)`, `request_deadline.remaining()`)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        base = dotted(f.value)
        if base is not None and base.split(".")[-1].endswith("deadline"):
            return f.attr
    return None


def _loop_spans(fn_node: ast.AST) -> List[Tuple[int, int]]:
    return [(n.lineno, getattr(n, "end_lineno", n.lineno))
            for n in ast.walk(fn_node)
            if isinstance(n, (ast.While, ast.For, ast.AsyncFor))]


def _blocking_sites(fn_node: ast.AST) -> List[Tuple[int, str]]:
    """(line, description) of every blocking primitive in the body."""
    out: List[Tuple[int, str]] = []
    loops = _loop_spans(fn_node)
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _BLOCKING_ATTRS and \
                    _deadline_attr(node) is None:
                out.append((node.lineno, f".{f.attr}(...)"))
                continue
            if f.attr == "get" and not node.args and not node.keywords:
                out.append((node.lineno, "timeout-less .get()"))
                continue
        name = dotted(f)
        if name is not None and name.split(".")[-1] == "sleep" and \
                any(lo <= node.lineno <= hi for lo, hi in loops):
            out.append((node.lineno, "sleep inside a retry loop"))
    return out


def _consults_deadline(fn_node: ast.AST) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call) and \
                _deadline_attr(node) in _CONSULT_CALLS:
            return True
    return False


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    decl_sf = _find_decl(corpus)
    if decl_sf is None:
        return findings
    stages = _stage_entries(decl_sf)
    declared: Dict[str, int] = dict(stages)
    roots = sorted(literal_strs(
        module_decl(decl_sf, "_SERVING_ROOTS") or ast.Constant(value=0)))
    modules = literal_strs(
        module_decl(decl_sf, "_SERVING_MODULES") or ast.Constant(value=0))

    # D1/D2 + stage usage, corpus-wide (the declaring module is the
    # implementation — its internal forwarding of the `stage` argument
    # is not a call site)
    used: Set[str] = set()
    for sf in corpus.py:
        if sf is decl_sf:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or \
                    _deadline_attr(node) not in _STAGE_CALLS:
                continue
            line = node.lineno
            arg = node.args[0] if node.args else None
            if not (isinstance(arg, ast.Constant) and
                    isinstance(arg.value, str)):
                if not sf.allowed(CHECKER, line,
                                  enclosing_def_line(sf, line)):
                    findings.append(Finding(
                        CHECKER, sf.rel, line,
                        "deadline stage must be a string literal (the "
                        "deadline.expired.<stage> counter namespace "
                        "is closed)"))
                continue
            used.add(arg.value)
            if arg.value not in declared and \
                    not sf.allowed(CHECKER, line,
                                   enclosing_def_line(sf, line)):
                findings.append(Finding(
                    CHECKER, sf.rel, line,
                    f"deadline stage `{arg.value}` is not declared in "
                    f"{decl_sf.rel} _DEADLINE_STAGES"))

    # D3: dead stages
    for stage, line in stages:
        if stage not in used and not decl_sf.allowed(CHECKER, line):
            findings.append(Finding(
                CHECKER, decl_sf.rel, line,
                f"declared stage `{stage}` is never checked/expired "
                f"anywhere (its deadline.expired.{stage} counter can "
                f"never fire)"))

    # D4: blocking primitives in the request-serving cone
    if not roots or not modules:
        return findings
    index = index_functions(corpus.py)
    seeds = []
    seen_keys: Set[str] = set()
    for infos in index.values():
        for fi in infos:
            if fi.key not in seen_keys and \
                    any(fnmatch.fnmatchcase(fi.qualname, pat)
                        for pat in roots):
                seen_keys.add(fi.key)
                seeds.append(fi)
    for fi, chain in walk_cone(index, seeds, CHECKER):
        if fi.sf.module not in modules:
            continue
        sites = _blocking_sites(fi.node)
        if not sites or _consults_deadline(fi.node):
            continue
        sf = fi.sf
        for line, desc in sites:
            if sf.allowed(CHECKER, line, enclosing_def_line(sf, line),
                          fi.node.lineno):
                continue
            findings.append(Finding(
                CHECKER, sf.rel, line,
                f"`{fi.qualname}` blocks on {desc} in the "
                f"request-serving cone without ever consulting the "
                f"deadline", chain=chain))
    return findings
