"""donation-safety: donated device buffers are never touched again.

`donate_argnums` aliases an input buffer into the kernel's output: the
moment the dispatch is issued the Python reference is a dangling
handle, and reading it raises (best case) or silently serves deleted
memory on some backends (worst).  The repo's donated-carry protocol
(`world.loan_basis()` -> dispatch -> `world.adopt_basis(carry)` /
`world.invalidate_basis()` on failure) makes the ownership transfer
explicit; this checker makes the protocol mechanical:

    G1  every `donate_argnums` jit site (an `x = jax.jit(...,
        donate_argnums=...)` assignment — possibly behind an IfExp
        donate toggle — or a decorated def, incl.
        `@partial(jax.jit, ..., donate_argnums=...)`) must be declared
        in its module's `_DONATE_PROTOCOL` dict (name -> one-line
        loan/adopt contract); a protocol entry naming no site is a
        dead declaration
    G2  after `x = <world>.loan_basis()` the loaned name (and local
        aliases, `basis_dev = x`) must reach `adopt_basis(...)` or
        `invalidate_basis()` in the same function, and must not be
        READ between the donating dispatch (the first call taking the
        loaned name as an argument) and that adopt/invalidate
    G3  assigning a loaned name into a subscript/attribute target
        (`cache[k] = loaned`, `self.basis = loaned`) aliases a
        to-be-donated buffer into a longer-lived structure — a
        use-after-donate waiting for the next dispatch

All static, AST-only (the CI analysis leg runs before pip install).
Suppress with `# analysis: allow(donation-safety) — reason`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, SourceFile, dotted, enclosing_def_line,
    module_decl,
)

CHECKER = "donation-safety"

_JIT = {"jax.jit", "jit"}
_PARTIAL = {"functools.partial", "partial"}
_ADOPT = {"adopt_basis", "invalidate_basis"}


def _donating_jit(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Call) and dotted(expr.func) in _JIT and \
        any(kw.arg == "donate_argnums" for kw in expr.keywords)


def _donate_sites(sf: SourceFile) -> List[Tuple[str, int]]:
    """(bound name, line) of every donate_argnums jit site."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            v = node.value
            cands = [v.body, v.orelse] if isinstance(v, ast.IfExp) else [v]
            if any(_donating_jit(c) for c in cands):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.append((t.id, node.lineno))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                donating = _donating_jit(dec) or (
                    dotted(dec.func) in _PARTIAL and dec.args and
                    dotted(dec.args[0]) in _JIT and
                    any(kw.arg == "donate_argnums"
                        for kw in dec.keywords))
                if donating:
                    out.append((node.name, node.lineno))
                    break
    return out


def _protocol_entries(sf: SourceFile) -> Dict[str, int]:
    """declared site name -> declaration line from _DONATE_PROTOCOL."""
    out: Dict[str, int] = {}
    decl = module_decl(sf, "_DONATE_PROTOCOL")
    if isinstance(decl, ast.Dict):
        for k in decl.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out[k.value] = k.lineno
    return out


def _loan_flows(fn_node: ast.AST) -> List[Tuple[Set[str], int]]:
    """(loaned names incl. aliases, loan line) per loan in the body."""
    loans: List[Tuple[str, int]] = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                isinstance(node.value.func, ast.Attribute) and \
                node.value.func.attr == "loan_basis":
            loans.append((node.targets[0].id, node.lineno))
    out: List[Tuple[Set[str], int]] = []
    for name, line in loans:
        names = {name}
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn_node):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in names and \
                        node.lineno > line and \
                        node.targets[0].id not in names:
                    names.add(node.targets[0].id)
                    changed = True
        out.append((names, line))
    return out


def _call_uses(call: ast.Call, names: Set[str]) -> bool:
    for arg in call.args:
        if isinstance(arg, ast.Name) and arg.id in names:
            return True
        if isinstance(arg, ast.Starred):
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name) and sub.id in names:
                    return True
    return False


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.py:
        # ---- G1: declared protocol for every donate_argnums jit
        sites = _donate_sites(sf)
        protocol = _protocol_entries(sf)
        site_names = {name for name, _ in sites}
        for name, line in sites:
            if name in protocol:
                continue
            if sf.allowed(CHECKER, line, enclosing_def_line(sf, line)):
                continue
            findings.append(Finding(
                CHECKER, sf.rel, line,
                f"donate_argnums jit `{name}` has no _DONATE_PROTOCOL "
                f"entry declaring its loan/adopt contract"))
        for name, line in sorted(protocol.items()):
            if name not in site_names and not sf.allowed(CHECKER, line):
                findings.append(Finding(
                    CHECKER, sf.rel, line,
                    f"_DONATE_PROTOCOL entry `{name}` names no "
                    f"donate_argnums jit site in this module (dead "
                    f"declaration)"))

        # ---- G2/G3: loan dataflow per function
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for names, loan_line in _loan_flows(node):
                calls = sorted(
                    (c for c in ast.walk(node)
                     if isinstance(c, ast.Call) and c.lineno > loan_line),
                    key=lambda c: c.lineno)
                adopt_line = None
                dispatch_end = None
                for c in calls:
                    if isinstance(c.func, ast.Attribute) and \
                            c.func.attr in _ADOPT:
                        if adopt_line is None:
                            adopt_line = c.lineno
                    elif dispatch_end is None and _call_uses(c, names):
                        dispatch_end = getattr(c, "end_lineno", c.lineno)
                if adopt_line is None:
                    if not sf.allowed(CHECKER, loan_line,
                                      enclosing_def_line(sf, loan_line)):
                        findings.append(Finding(
                            CHECKER, sf.rel, loan_line,
                            f"`{node.name}` takes loan_basis() but "
                            f"never adopt_basis(...) or "
                            f"invalidate_basis() — the resident basis "
                            f"is left dangling after the donated "
                            f"dispatch"))
                    continue
                if dispatch_end is not None:
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name) and \
                                isinstance(sub.ctx, ast.Load) and \
                                sub.id in names and \
                                dispatch_end < sub.lineno < adopt_line \
                                and not sf.allowed(
                                    CHECKER, sub.lineno,
                                    enclosing_def_line(sf, sub.lineno)):
                            findings.append(Finding(
                                CHECKER, sf.rel, sub.lineno,
                                f"`{sub.id}` read after the donating "
                                f"dispatch and before "
                                f"adopt/invalidate — the buffer was "
                                f"donated and may already be deleted"))
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and \
                            isinstance(sub.value, ast.Name) and \
                            sub.value.id in names and \
                            sub.lineno > loan_line and \
                            any(isinstance(t, (ast.Subscript,
                                               ast.Attribute))
                                for t in sub.targets) and \
                            not sf.allowed(
                                CHECKER, sub.lineno,
                                enclosing_def_line(sf, sub.lineno)):
                        findings.append(Finding(
                            CHECKER, sf.rel, sub.lineno,
                            f"loaned buffer `{sub.value.id}` aliased "
                            f"into a longer-lived structure — it will "
                            f"dangle once the donating dispatch "
                            f"consumes it"))
    return findings
