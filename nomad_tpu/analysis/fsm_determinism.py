"""fsm-determinism: no nondeterminism in the raft FSM apply cone.

Replicas (and log replay onto a restored snapshot) must produce
byte-identical state from the same log entries, so everything reachable
from `NomadFSM.apply` may depend ONLY on the log payload and the current
store state.  This checker walks the shared interprocedural cone
(common.walk_cone) from the FSM's apply/restore methods and flags:

- wall-clock reads (`time.time`, `monotonic`, `perf_counter`, datetime
  now/utcnow)
- entropy (`random.*` draws, `uuid4`/`uuid1`, `os.urandom`) — including
  transitively, e.g. a helper that formats uuids
- iteration over unordered sets (set literals / `set()` constructions),
  whose order varies across processes when hash randomization differs

Resolution is by bare callee name over every def in the corpus — an
over-approximation (receiver types are unknown), kept honest by the
allow escape hatch (see common): an allowed call line is neither
flagged nor traversed, so leader-local side effects (broker enqueue,
heartbeat timers) can be fenced off explicitly at the FSM boundary.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, FuncInfo, dotted, enclosing_def_line,
    find_fsm_classes, index_functions, walk_cone,
)

CHECKER = "fsm-determinism"

_WALLCLOCK_ATTRS = {"time", "time_ns", "monotonic", "monotonic_ns",
                    "perf_counter", "perf_counter_ns"}
_TIME_MODULES = {"time", "_time", "_t"}
_DATETIME_ATTRS = {"now", "utcnow", "today"}
_RANDOM_FNS = {"random", "randint", "randrange", "choice", "choices",
               "shuffle", "sample", "uniform", "gauss", "normalvariate",
               "expovariate", "betavariate", "getrandbits", "randbytes"}
_ENTROPY_NAMES = {"uuid4", "uuid1", "urandom", "token_hex", "token_bytes"}


def _sink(call: ast.Call) -> Optional[str]:
    """Nondeterminism description if this call is a sink, else None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        base = dotted(f.value)
        if f.attr in _WALLCLOCK_ATTRS and base in _TIME_MODULES:
            return f"wall-clock read `{base}.{f.attr}()`"
        if f.attr in _DATETIME_ATTRS and base and \
                base.split(".")[-1] in ("datetime", "date"):
            return f"wall-clock read `{base}.{f.attr}()`"
        if f.attr in _ENTROPY_NAMES:
            return f"entropy source `.{f.attr}()`"
        if f.attr in _RANDOM_FNS and base is not None and \
                base.split(".")[-1] == "random":
            return f"entropy source `{base}.{f.attr}()`"
    elif isinstance(f, ast.Name):
        if f.id in _ENTROPY_NAMES:
            return f"entropy source `{f.id}()`"
    return None


def _is_set_expr(expr: ast.AST, local_sets: Set[str]) -> bool:
    if isinstance(expr, ast.Set):
        return True
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
            and expr.func.id in ("set", "frozenset"):
        return True
    if isinstance(expr, ast.Name) and expr.id in local_sets:
        return True
    return False


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    index = index_functions(corpus.py)

    seeds: List[FuncInfo] = []
    for sf, cls in find_fsm_classes(corpus.py):
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and (item.name == "apply" or item.name == "restore"
                         or item.name.startswith("_apply_")):
                seeds.append(FuncInfo(sf, item, f"{cls.name}.{item.name}"))

    reported: Set[Tuple[str, int]] = set()
    # sink calls are findings, not edges: their internals (stdlib) are
    # not part of the cone
    cone = walk_cone(index, seeds, CHECKER,
                     prune=lambda call: _sink(call) is not None)
    for fi, chain in cone:
        sf = fi.sf

        # names bound to set() expressions in this function, for the
        # unordered-iteration check
        local_sets: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and _is_set_expr(node.value, set()):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local_sets.add(tgt.id)

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                line = node.lineno
                if sf.allowed(CHECKER, line,
                              enclosing_def_line(sf, line)):
                    continue
                sink = _sink(node)
                if sink is not None:
                    key = (sf.rel, line)
                    if key not in reported:
                        reported.add(key)
                        findings.append(Finding(
                            CHECKER, sf.rel, line,
                            f"{sink} reachable from FSM apply", chain))
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                line = getattr(node, "lineno",
                               getattr(it, "lineno", None)) or it.lineno
                if sf.allowed(CHECKER, line,
                              enclosing_def_line(sf, line)):
                    continue
                if _is_set_expr(it, local_sets):
                    key = (sf.rel, line)
                    if key not in reported:
                        reported.add(key)
                        findings.append(Finding(
                            CHECKER, sf.rel, line,
                            "iteration over an unordered set in the FSM "
                            "apply cone (order varies across replicas)",
                            chain))
    return findings
