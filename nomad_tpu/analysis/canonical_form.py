"""canonical-form: replicated bytes must not depend on hash order,
object identity, or accumulation order.

The survivor-comparison gate (tests/test_raft.py byte-identity checks)
and snapshot install both compare *pickled bytes*, not values: two
stores that agree on every value still diverge if a set pickles in a
different iteration order, a float sum folds in a different order, or a
dict materialized keys in a different sequence.  PR 13 fixed one
instance by hand (_quota_usage_add's fixed key order + delete-at-zero);
this checker proves the whole class, complementing fsm-determinism
(which owns set *iteration* inside the apply cone):

  set-in-record     a set-typed value placed in the snapshot record
                    (directly or through `list(...)`) pickles in hash
                    order — wrap it in `sorted(...)`
  id-keyed          `id(...)` used as a dict key or subscript in the
                    apply/snapshot/restore cones keys replicated state
                    by process-local addresses
  float-accum       `sum()`/`fsum()` over a set-typed operand in the
                    apply cone folds floats in hash order
  defaultdict-read  a Load-context subscript of a persisted defaultdict
                    table outside the apply/restore cones materializes
                    keys on the READ path, mutating dict layout (and so
                    snapshot bytes) without a log entry — use `.get()`
  canon-bypass      in-place mutation of a _CANONICAL table outside its
                    declared canonicalizer (wholesale reassignment is
                    the one legal outside form: replacement, not drift)

Declarations consumed (state store class level):

  _CANONICAL = {"_quota_usage": "_quota_usage_add"}
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.analysis.common import (
    Corpus, Finding, FuncInfo, attr_mutations, class_attr_types,
    class_decl, class_methods, container_kinds, decl_str_dict, dotted,
    enclosing_def_line, index_functions, literal_strs, resolve_fsm_stores,
    store_bases, walk_cone,
)

CHECKER = "canonical-form"

_SET_CTORS = {"set", "frozenset"}
_SEQ_WRAPPERS = {"list", "tuple"}   # preserve iteration order of the arg


def _is_set_typed(expr: ast.AST, bases: Set[str],
                  set_attrs: Set[str]) -> bool:
    """Conservatively: does `expr` evaluate to a set (whose pickle/fold
    order is hash order)?  `sorted(...)` canonicalizes and is never
    set-typed; `list(x)`/`tuple(x)` preserve x's order."""
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        n = dotted(expr.func)
        ctor = n.split(".")[-1] if n else None
        if ctor in _SET_CTORS:
            return True
        if ctor in _SEQ_WRAPPERS and expr.args:
            return _is_set_typed(expr.args[0], bases, set_attrs)
        return False
    if isinstance(expr, ast.Attribute):
        b = dotted(expr.value)
        if b is not None and b in bases and expr.attr in set_attrs:
            return True
    if isinstance(expr, (ast.GeneratorExp, ast.ListComp)):
        gens = expr.generators
        if gens:
            return _is_set_typed(gens[0].iter, bases, set_attrs)
    return False


def _id_key_sites(fn_node: ast.AST) -> List[int]:
    """Lines where `id(...)` keys a structure: subscript slices and
    dict-literal keys."""
    def has_id_call(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "id":
                return True
        return False

    out: List[int] = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Subscript) and has_id_call(node.slice):
            out.append(node.lineno)
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if k is not None and has_id_call(k):
                    out.append(k.lineno)
    return out


def run(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    files = corpus.py
    index = index_functions(files)
    attr_types = class_attr_types(files)
    reported: Set[Tuple[str, int, str]] = set()

    def report(sf, line: int, rule: str, msg: str,
               chain: Tuple[str, ...] = ()) -> None:
        key = (sf.rel, line, rule)
        if key in reported:
            return
        if sf.allowed(CHECKER, line, enclosing_def_line(sf, line)):
            return
        reported.add(key)
        findings.append(Finding(CHECKER, sf.rel, line, msg, chain))

    for pair in resolve_fsm_stores(files, attr_types):
        fsm_sf, fsm_cls = pair.fsm_sf, pair.fsm_cls
        store_cls_name = pair.store_cls.name
        universe = pair.tables
        kinds = container_kinds(pair.store_cls)
        set_attrs = {a for a, k in kinds.items()
                     if k in ("set", "frozenset")}
        canonical = decl_str_dict(class_decl(pair.store_cls, "_CANONICAL"))
        derived = decl_str_dict(
            class_decl(pair.store_cls, "_SNAPSHOT_DERIVED"))
        eph_decl = class_decl(pair.store_cls, "_SNAPSHOT_EPHEMERAL")
        ephemeral = literal_strs(eph_decl) if eph_decl is not None else set()
        methods = class_methods(fsm_cls)
        store_methods = class_methods(pair.store_cls)

        def fi_of(fn) -> FuncInfo:
            return FuncInfo(fsm_sf, fn, f"{fsm_cls.name}.{fn.name}")

        apply_seeds = [fi_of(fn) for name, fn in methods.items()
                       if name == "apply" or name.startswith("_apply_")]
        snap_seeds = [fi_of(methods["snapshot"])] \
            if "snapshot" in methods else []
        restore_seeds = [fi_of(methods["restore"])] \
            if "restore" in methods else []

        apply_visits = list(walk_cone(index, apply_seeds, CHECKER))
        snap_visits = list(walk_cone(index, snap_seeds, CHECKER))
        restore_visits = list(walk_cone(index, restore_seeds, CHECKER))
        apply_keys = {fi.key for fi, _ in apply_visits}
        restore_keys = {fi.key for fi, _ in restore_visits}

        # ---- set-in-record: set-typed values in the snapshot record
        for fi, chain in snap_visits:
            bases = store_bases(fi, store_cls_name, attr_types)
            for node in ast.walk(fi.node):
                values = []
                if isinstance(node, ast.Dict):
                    values = [v for v in node.values]
                elif isinstance(node, ast.DictComp):
                    values = [node.value]
                for v in values:
                    if _is_set_typed(v, bases, set_attrs):
                        report(fi.sf, v.lineno, "set-in-record",
                               "set-typed value in the snapshot record "
                               "pickles in hash order (bytes differ "
                               "across replicas) — wrap it in sorted()",
                               chain)

        # ---- id-keyed structures anywhere in the replicated cones
        for fi, chain in apply_visits + snap_visits + restore_visits:
            for line in _id_key_sites(fi.node):
                report(fi.sf, line, "id-keyed",
                       "id()-keyed structure in the replication cone: "
                       "object addresses are process-local, so keys "
                       "(and byte layout) differ across replicas",
                       chain)

        # ---- float accumulation order in the apply cone
        for fi, chain in apply_visits:
            bases = store_bases(fi, store_cls_name, attr_types)
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call) and node.args:
                    callee = dotted(node.func)
                    short = callee.split(".")[-1] if callee else None
                    if short in ("sum", "fsum") and \
                            _is_set_typed(node.args[0], bases, set_attrs):
                        report(fi.sf, node.lineno, "float-accum",
                               f"{short}() over a set-typed operand in "
                               f"the FSM apply cone folds in hash order "
                               f"— sort the operand first", chain)

        # ---- defaultdict key materialization on read paths
        dd_tables = {a for a in universe
                     if kinds.get(a) == "defaultdict"
                     and a not in derived and a not in ephemeral}
        if dd_tables:
            seen_fn: Set[str] = set()
            for fis in index.values():
                for fi in fis:
                    if fi.key in seen_fn or fi.key in apply_keys \
                            or fi.key in restore_keys:
                        continue
                    seen_fn.add(fi.key)
                    bases = store_bases(fi, store_cls_name, attr_types)
                    if not bases:
                        continue
                    for node in ast.walk(fi.node):
                        if not (isinstance(node, ast.Subscript)
                                and isinstance(node.ctx, ast.Load)):
                            continue
                        tgt = node.value
                        if isinstance(tgt, ast.Attribute):
                            b = dotted(tgt.value)
                            if b is not None and b in bases \
                                    and tgt.attr in dd_tables:
                                report(fi.sf, node.lineno,
                                       "defaultdict-read",
                                       f"Load-subscript of persisted "
                                       f"defaultdict table `{tgt.attr}` "
                                       f"outside the apply/restore cones "
                                       f"materializes keys on the read "
                                       f"path (snapshot bytes change "
                                       f"without a log entry) — use "
                                       f".get()")

        # ---- _CANONICAL tables: one mutation path
        decl_node = class_decl(pair.store_cls, "_CANONICAL")
        decl_line = getattr(decl_node, "lineno", pair.store_cls.lineno)
        for attr, canon in sorted(canonical.items()):
            if canon not in store_methods:
                report(pair.store_sf, decl_line, "canon-bypass",
                       f"_CANONICAL maps `{attr}` to `{canon}`, which "
                       f"is not a method of {store_cls_name}")
                continue
            seen_fn = set()
            for fis in index.values():
                for fi in fis:
                    if fi.key in seen_fn:
                        continue
                    seen_fn.add(fi.key)
                    if fi.cls == store_cls_name and fi.node.name == canon:
                        continue
                    bases = store_bases(fi, store_cls_name, attr_types)
                    if not bases:
                        continue
                    for m in attr_mutations(fi.node, bases):
                        if m.attr != attr or m.kind == "assign":
                            continue
                        report(fi.sf, m.line, "canon-bypass",
                               f"in-place mutation of canonical table "
                               f"`{attr}` outside its canonicalizer "
                               f"`{canon}` (key order / delete-at-zero "
                               f"discipline bypassed)")
    return findings
