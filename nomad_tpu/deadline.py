"""End-to-end request deadlines (the ``"_deadline"`` ctx).

A reserved ``"_deadline"`` key rides RPC args exactly like tracing's
``"_trace"`` ctx: stamped at HTTP ingress (``X-Nomad-Deadline`` header
or the ``NOMAD_TPU_DEFAULT_DEADLINE`` env default), decremented across
federation/forward hops, and checked at every queueing stage — the
broker refuses to mint a lease for an expired dequeue, the plan applier
rejects expired pending plans *before* the raft append+fsync edge, and
retry loops clamp their backoff to the remaining budget.

Wire format is the REMAINING BUDGET in seconds (a relative float),
never an absolute timestamp: only relative budgets cross process/hop
boundaries, so clock skew between servers cannot spuriously expire (or
immortalize) a request.  The ``overload.deadline_skew`` chaos point
injects exactly that mis-stamping at decode, proving every downstream
stage still resolves the request with an honest ``deadline_exceeded``
instead of silently dropping it.  Locally a binding is an absolute
``time.monotonic()`` deadline.

Zero-cost when unused (tracing.py / chaos.py idiom): an unbound thread
pays one thread-local attribute load per check, nothing more.  Expiry
observed at a stage lands in telemetry as ``deadline.expired.<stage>``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from nomad_tpu import chaos, knobs
from nomad_tpu.telemetry import global_metrics

# reserved args key (stripped before dispatch, like tracing.TRACE_KEY)
DEADLINE_KEY = "_deadline"

# Every stage name that may appear in a `check(stage)` / `expire(stage)`
# call — and therefore in a `deadline.expired.<stage>` counter.  The
# deadline-coverage checker cross-checks both directions: a stage
# checked but not declared is a finding (dashboards would miss the
# counter), and a declared stage nothing checks is a dead stage.
_DEADLINE_STAGES = (
    "rpc",           # endpoint dispatch gate (Endpoints.handle)
    "rpc.forward",   # cross-region forward refusal (Endpoints.handle)
    "read_gate",     # consistency-gate establishment (read path)
    "federation",    # region-router retry loop
    "worker",        # scheduler worker RPC retry backoff
    "applier",       # plan applier pre-raft rejection
    "broker",        # eval broker dequeue park
    "plan.submit",   # Plan.Submit applier-result wait
)

# Roots of the request-serving cone ("*" globs endpoint handlers) and
# the modules whose blocking primitives inside that cone must consult
# the deadline (check/expire/remaining/current) or carry an allow.
_SERVING_ROOTS = (
    "Endpoints.handle",
    "Endpoints.rpc_*",
    "RegionRouter.route",
    "HTTPServer._route",
    "HTTPServer._rpc",
)
_SERVING_MODULES = (
    "nomad_tpu.rpc.endpoints",
    "nomad_tpu.agent.http",
    "nomad_tpu.federation.router",
    "nomad_tpu.core.broker",
    "nomad_tpu.core.worker",
    "nomad_tpu.core.plan_apply",
)

_tls = threading.local()


class DeadlineExceeded(Exception):
    """The request's deadline budget ran out before the work finished."""


def current() -> Optional[float]:
    """This thread's absolute monotonic deadline, or None (unbounded)."""
    return getattr(_tls, "deadline", None)


def bind(deadline: Optional[float]) -> Optional[float]:
    """Bind an absolute monotonic deadline to this thread; returns the
    previous binding so callers restore it in a finally block."""
    prev = getattr(_tls, "deadline", None)
    _tls.deadline = deadline
    return prev


def remaining() -> Optional[float]:
    """Seconds of budget left, or None when unbounded.  Clamped at 0."""
    dl = getattr(_tls, "deadline", None)
    if dl is None:
        return None
    return max(0.0, dl - time.monotonic())


def expired() -> bool:
    dl = getattr(_tls, "deadline", None)
    return dl is not None and time.monotonic() >= dl


def expire(stage: str) -> None:
    """Record a deadline expiry observed at `stage` (telemetry only —
    the caller owns the refusal/unwind)."""
    global_metrics.incr(f"deadline.expired.{stage}")


def check(stage: str) -> bool:
    """True (and counted against `stage`) iff the bound deadline has
    expired; False for unbound threads."""
    if expired():
        expire(stage)
        return True
    return False


def default_budget() -> Optional[float]:
    """The ingress default budget (seconds) from
    ``NOMAD_TPU_DEFAULT_DEADLINE``; None/<=0 disables the default."""
    try:
        budget = knobs.get_float("NOMAD_TPU_DEFAULT_DEADLINE")
    except ValueError:
        return None
    if budget is None:
        return None
    return budget if budget > 0.0 else None


def to_wire() -> Optional[float]:
    """Encode this thread's binding as a relative budget for an RPC hop
    (the decrement happens here: elapsed time is already subtracted)."""
    return remaining()


def from_wire(budget: float) -> float:
    """Decode a relative hop budget into a local monotonic deadline.
    The deadline_skew chaos point models a sender whose clock drifted
    mid-flight mis-stamping the budget: downstream stages must still
    resolve the request honestly, never hang on or silently drop it."""
    b = max(0.0, float(budget))
    reg = chaos.active
    if reg is not None and chaos.should("overload.deadline_skew"):
        b *= 2.0 * reg.uniform()        # 0x..2x: early or late, seeded
    return time.monotonic() + b
