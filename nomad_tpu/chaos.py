"""Seeded chaos layer: deterministic fault injection for the commit
pipeline and control plane.

A `ChaosRegistry` holds per-fault-point firing rates driven by one seeded
RNG, so a soak run with a fixed seed draws a reproducible fault schedule.
The registry is installed process-wide (`install()`) or picked up from the
`NOMAD_TPU_CHAOS` environment variable at import.

Spec grammar (semicolon-separated `key=value` pairs):

    NOMAD_TPU_CHAOS="seed=42;rpc.drop=0.05;rpc.delay=0.02;delay_ms=5"

where `seed` (int) seeds the RNG, `delay_ms` (float) sets the injected
latency for `rpc.delay`, and every other key must be one of the named
fault points below with a rate in [0, 1].

Phased schedules extend the grammar with named time windows so one run
can interleave calm -> storm -> calm:

    NOMAD_TPU_CHAOS="seed=7;phase=storm:10-20;raft.partition=0.3@storm"

`phase=<name>:<start>-<end>` declares a window in seconds relative to
the registry's arm time; `<point>=<rate>@<phase>` applies the rate only
while that phase is open.  A point may carry one base rate plus any
number of phased rates; the effective rate at a check is the max of the
base rate and every currently-open phase rate.  Phase windows are
inactive until `arm()` anchors the clock (an un-armed registry behaves
as if every phase were closed), so base rates keep the original
whole-run semantics.  Note the draw count then depends on wall time —
with phases a seed reproduces the schedule in distribution, not draw
for draw.

Fault points and their injection sites:

    rpc.drop                  rpc/tcp.py, raft/transport.py — connection
                              dropped before the request is sent
    rpc.delay                 same sites — `delay_ms` of extra latency
    raft.partition            raft/transport.py — raft traffic
                              (vote/append/snapshot) fails Unreachable
    plan.crash_before_commit  core/plan_apply.py — applier dies after
                              evaluation, before the store/raft write
    plan.crash_after_commit   core/plan_apply.py — applier dies after the
                              write lands, before futures resolve
    broker.lease_expire       core/broker.py — a dequeue lease expires
                              immediately (worker's ack/plan goes stale)
    native.fail               native/__init__.py — a native kernel call
                              raises (drives the circuit breaker)
    disk.torn_write           raft/log.py — a power-loss crash leaves a
                              partial record at the WAL tail (load must
                              truncate it and warn)
    disk.fsync_fail           raft/log.py, raft/meta.py — an fsync fails;
                              the WAL retries, the vote/term meta store
                              refuses to acknowledge (a vote must never
                              be granted on non-durable state)
    disk.corrupt_read         raft/log.py, raft/snapshot.py — a read
                              returns flipped bits; the CRC catches it
                              and the reader retries from disk
    snapshot.partial_write    raft/snapshot.py — crash mid-snapshot: a
                              truncated record lands under the final
                              name (latest() must skip it and fall back)
    world.scatter_fail        parallel/world.py — the device half of a
                              rank-1 scatter (or a dirty-row diff) is
                              lost, as if the device dropped the update:
                              resident state is invalidated and the next
                              update() re-uploads from the host snapshot
                              (which always applies), so recovery is
                              deterministic and nothing raises mid-commit
    engine.complete_delay     parallel/engine.py — batched ticket release
                              (complete_many) stalls `delay_ms` before
                              taking the overlay lock, widening the
                              window where commits race dispatch
    read.lease_expire         raft/node.py — a leader's read lease is
                              voided at read time, forcing the full
                              heartbeat quorum confirmation round (the
                              slow path every lease read elides)
    read.index_stall          raft/node.py — the leadership-confirmation
                              round stalls `delay_ms` before probing,
                              stretching read_index latency so batched
                              readers pile onto one round
    stream.subscriber_stall   serving/stream.py — the NDJSON event
                              streamer stalls `delay_ms` mid-write, as
                              if a consumer stopped reading: the broker
                              must bound the queue and evict/catch-up,
                              never grow without limit
    node.churn_kill           core/heartbeat.py — a client heartbeat is
                              swallowed before the TTL re-arm, so the
                              node expires through the real miss path
                              (down/disconnected + node-update eval)
    deploy.health_flap        scenarios.py — the health reporter flips
                              one alloc's health report to unhealthy,
                              driving the deployment watcher toward
                              failure/auto-revert mid-update
    scale.burst               scenarios.py — an autoscaling wave is
                              amplified to the policy bound, stacking
                              scale evals on top of in-flight ones
    member.join_stall         core/membership.py — a joining server's
                              first gossip round is delayed, so autopilot
                              sees it late and the stabilization window
                              restarts
    raft.config_conflict      raft/node.py — a membership change is
                              rejected as if another were in flight,
                              forcing the caller's retry path
    transfer.timeout          raft/node.py — the TimeoutNow message is
                              dropped after catch-up, so the old leader
                              resumes and the transfer falls back to a
                              normal election timeout
    region.partition          federation/router.py — a cross-region
                              forward is severed as if the WAN link were
                              cut, exercising the router's fail-fast
                              Unreachable path and the multiregion
                              rollout's halt-at-region-boundary behavior
    quota.apply_stall         core/plan_apply.py — the propose-side quota
                              admission check stalls `delay_ms`, widening
                              the window where a leader change can route
                              a second within-budget plan at the same
                              namespace budget (the FSM-side check must
                              still drop the combined overflow)
    broker.unfair_burst       core/broker.py — the fair-share namespace
                              pick is bypassed for one dequeue (the
                              global priority order is used instead), as
                              if a burst slipped past the stride
                              accounting; the starvation bound must hold
                              regardless
    plan.commit_stall         core/plan_apply.py — the raft append +
                              fsync of a commit batch stalls `delay_ms`
                              while the pipelined next wave evaluates
                              against the optimistic overlay, widening
                              the speculative window the double-buffer
                              invariants must survive
    worker.settle_drop        core/worker.py — a pipelined worker's
                              deferred eval settlement (status update +
                              broker ack after the commit future lands)
                              is dropped, as if the worker died between
                              commit and ack: the lease must expire and
                              redelivery must no-op via plan dedup
    snapshot.chunk_drop       raft/node.py — one frame of a chunked
                              InstallSnapshot stream is lost in flight;
                              the follower's next-expected-offset ack
                              must re-synchronize the stream instead of
                              restarting it from byte zero
    snapshot.stream_abort     raft/node.py — the sending side of a
                              snapshot stream dies mid-transfer (leader
                              kill, stream teardown); the next
                              replication tick restarts the stream,
                              which must resume from the follower's
                              acked offset
    heartbeat.batch_stall     core/heartbeat.py — the leader's batched
                              heartbeat/node-status FSM flush stalls
                              `delay_ms` (or skips a round), widening
                              the window where TTL expiry, revival and
                              liveness stamps pile into one batch entry
    overload.ingress_flood    agent/http.py — the HTTP front door sheds
                              this request as if the tenant's admission
                              bucket were empty: an explicit 503 with
                              Retry-After, exercising every client's
                              deny-handling path under synthetic flood
    overload.applier_stall    core/plan_apply.py — the plan applier's
                              drain loop stalls `delay_ms` per round, so
                              pending plans age toward their deadlines
                              and the pre-raft expiry rejection (rather
                              than a doomed append+fsync) must fire
    overload.deadline_skew    deadline.py — a hop's decoded deadline
                              budget is scaled by a seeded 0x..2x
                              factor, simulating clock-rate skew between
                              nodes; correctness must not depend on
                              budgets agreeing across hops
    fsm.apply_skip            raft/node.py — ONE targeted replica's FSM
                              silently skips applying a committed entry
                              while last_applied still advances: the log
                              says it happened, the state says it didn't
                              — invisible to raft, detectable only by
                              the integrity plane's digest checkpoints
    store.bitflip             raft/node.py — a targeted replica's state
                              store silently corrupts one replicated
                              record (StateStore.chaos_bitflip) right
                              after an apply: no index bump, no notify,
                              no dirty mark — the runtime analogue of a
                              memory bitflip
    disk.silent_corrupt       raft/node.py — the state restored from an
                              installed snapshot is silently corrupted
                              post-restore (a bad disk read that still
                              unpickles); digest-verified re-admission
                              must refuse to clear quarantine and the
                              leader must retry the repair stream

`REQUIRED_SITES` pins points to the hot-path functions that must carry
them; the chaos-coverage linter fails if a refactor drops one.

Divergence points are *targeted*, not rate-drawn: corruption drills need
exactly one victim replica, while rates are process-global (every
replica in an in-process cluster shares the registry and would fire
together, destroying the healthy majority the vote needs).
`registry.target(point, where, count)` arms a point to fire `count`
times at the injection site whose `where` tag (the node name) matches;
`should(point, where=...)` consumes it.  Points with no armed target
keep the seeded-rate path unchanged.

Zero-overhead-when-disabled contract: `active` is None unless a registry
is installed; every injection site guards with `if chaos.active is not
None` (one module-attribute load) before doing any work.  The module
draws from its own `random.Random` — installing chaos never perturbs the
global `random` stream.
"""
from __future__ import annotations

import os
import random
import threading
import time
from collections import defaultdict
from typing import Dict, Optional, Tuple

from nomad_tpu import knobs

FAULT_POINTS = (
    "rpc.drop",
    "rpc.delay",
    "raft.partition",
    "plan.crash_before_commit",
    "plan.crash_after_commit",
    "broker.lease_expire",
    "native.fail",
    "disk.torn_write",
    "disk.fsync_fail",
    "disk.corrupt_read",
    "snapshot.partial_write",
    "world.scatter_fail",
    "engine.complete_delay",
    "read.lease_expire",
    "read.index_stall",
    "stream.subscriber_stall",
    "node.churn_kill",
    "deploy.health_flap",
    "scale.burst",
    "member.join_stall",
    "raft.config_conflict",
    "transfer.timeout",
    "region.partition",
    "quota.apply_stall",
    "broker.unfair_burst",
    "plan.commit_stall",
    "worker.settle_drop",
    "snapshot.chunk_drop",
    "snapshot.stream_abort",
    "heartbeat.batch_stall",
    "overload.ingress_flood",
    "overload.applier_stall",
    "overload.deadline_skew",
    "fsm.apply_skip",
    "store.bitflip",
    "disk.silent_corrupt",
)

# Points that must be injected in these specific functions (enforced by
# the chaos-coverage linter): the PR 6 scatter/commit hot paths and the
# PR 8 serving-plane read/stream paths.
REQUIRED_SITES = {
    "world.scatter_fail": ("DeviceWorld.apply_rank1",
                           "DeviceWorld._update_one"),
    "engine.complete_delay": ("PlacementEngine.complete_many",),
    "read.lease_expire": ("RaftNode.read_index",),
    "read.index_stall": ("RaftNode._confirm_leadership",),
    "stream.subscriber_stall": ("EventStreamer.run",),
    "node.churn_kill": ("HeartbeatTracker.heartbeat",),
    "deploy.health_flap": ("HealthReporter.tick",),
    "scale.burst": ("AutoscaleDriver.tick",),
    "member.join_stall": ("Membership.join",),
    "raft.config_conflict": ("RaftNode._append_config",),
    "transfer.timeout": ("RaftNode.transfer_leadership",),
    "region.partition": ("RegionRouter.route",),
    "quota.apply_stall": ("PlanApplier._evaluate",),
    "broker.unfair_burst": ("EvalBroker._pick_locked",),
    "plan.commit_stall": ("PlanApplier._commit_batch_and_resolve",),
    "worker.settle_drop": ("Worker._settle_eval",),
    "snapshot.chunk_drop": ("RaftNode._send_snapshot",),
    "snapshot.stream_abort": ("RaftNode._send_snapshot",),
    "heartbeat.batch_stall": ("HeartbeatBatcher.flush",),
    "overload.ingress_flood": ("HTTPServer._route",),
    "overload.applier_stall": ("PlanApplier.run_loop",),
    "overload.deadline_skew": ("from_wire",),
    "fsm.apply_skip": ("RaftNode._run_apply",),
    "store.bitflip": ("RaftNode._run_apply",),
    "disk.silent_corrupt": ("RaftNode._install_snapshot_blob",),
}


class ChaosError(RuntimeError):
    """An injected fault (never raised by real failures)."""

    def __init__(self, point: str):
        super().__init__(f"chaos: injected fault at {point!r}")
        self.point = point


class ChaosRegistry:
    """Per-point firing rates over one seeded RNG.

    `should(point)` draws once from the RNG iff the point has a non-zero
    rate, so runs with the same seed and the same rate map produce the
    same decision sequence per point-check order.  Thread interleaving
    can reorder which caller gets which draw; the schedule stays
    reproducible in distribution, which is what the soak asserts on.
    """

    def __init__(self, seed: int = 0,
                 rates: Optional[Dict[str, float]] = None,
                 delay_ms: float = 2.0,
                 phases: Optional[Dict[str, Tuple[float, float]]] = None,
                 phased: Optional[Dict[str, Dict[str, float]]] = None):
        rates = dict(rates or {})
        for point, rate in rates.items():
            if point not in FAULT_POINTS:
                raise ValueError(f"unknown chaos fault point {point!r} "
                                 f"(known: {', '.join(FAULT_POINTS)})")
            if not 0.0 <= float(rate) <= 1.0:
                raise ValueError(f"chaos rate for {point!r} must be in "
                                 f"[0, 1], got {rate!r}")
        self.seed = int(seed)
        self.delay_ms = float(delay_ms)
        self.rates = {p: float(rates.get(p, 0.0)) for p in FAULT_POINTS}
        # phase name -> (start_s, end_s) relative to arm()
        self.phases: Dict[str, Tuple[float, float]] = {}
        for name, window in (phases or {}).items():
            start, end = float(window[0]), float(window[1])
            if not name or any(c in name for c in ":;=@"):
                raise ValueError(f"bad chaos phase name {name!r}")
            if start < 0.0 or end <= start:
                raise ValueError(f"chaos phase {name!r} window must have "
                                 f"0 <= start < end, got {start}-{end}")
            self.phases[name] = (start, end)
        # point -> {phase name -> rate}; active only while armed and the
        # phase window is open
        self.phased: Dict[str, Dict[str, float]] = {}
        for point, sched in (phased or {}).items():
            if point not in FAULT_POINTS:
                raise ValueError(f"unknown chaos fault point {point!r} "
                                 f"(known: {', '.join(FAULT_POINTS)})")
            for phase, rate in sched.items():
                if phase not in self.phases:
                    raise ValueError(
                        f"chaos rate {point}={rate!r}@{phase} references "
                        f"undeclared phase {phase!r} (declare it with "
                        f"phase={phase}:<start>-<end>)")
                if not 0.0 <= float(rate) <= 1.0:
                    raise ValueError(f"chaos rate for {point!r}@{phase} "
                                     f"must be in [0, 1], got {rate!r}")
            self.phased[point] = {ph: float(r) for ph, r in sched.items()}
        self._t0: Optional[float] = None
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = defaultdict(int)
        # point -> {where tag -> remaining fire count}; armed by
        # target(), consumed by should(point, where=...)
        self._targets: Dict[str, Dict[str, int]] = {}

    def arm(self, now: Optional[float] = None) -> None:
        """Anchor the phase clock: phase windows are measured from here.
        Idempotent-by-intent — re-arming restarts the schedule."""
        self._t0 = time.monotonic() if now is None else float(now)

    def elapsed(self) -> Optional[float]:
        """Seconds since arm(), or None if not armed."""
        if self._t0 is None:
            return None
        return time.monotonic() - self._t0

    def phase_now(self) -> Tuple[str, ...]:
        """Names of the phases open at this instant (empty if un-armed)."""
        t = self.elapsed()
        if t is None:
            return ()
        return tuple(name for name, (a, b) in self.phases.items()
                     if a <= t < b)

    def effective_rate(self, point: str) -> float:
        """Base rate maxed with every currently-open phase rate."""
        rate = self.rates.get(point, 0.0)
        sched = self.phased.get(point)
        if sched and self._t0 is not None:
            t = time.monotonic() - self._t0
            for phase, prate in sched.items():
                a, b = self.phases[phase]
                if a <= t < b and prate > rate:
                    rate = prate
        return rate

    @classmethod
    def from_spec(cls, spec: str) -> "ChaosRegistry":
        """Parse the `NOMAD_TPU_CHAOS` grammar (see module docstring)."""
        seed = 0
        delay_ms = 2.0
        rates: Dict[str, float] = {}
        phases: Dict[str, Tuple[float, float]] = {}
        phased: Dict[str, Dict[str, float]] = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"bad chaos spec element {part!r}: want key=value")
            key, _, value = part.partition("=")
            key, value = key.strip(), value.strip()
            if key == "seed":
                seed = int(value)
            elif key == "delay_ms":
                delay_ms = float(value)
            elif key == "phase":
                # phase=<name>:<start>-<end>
                name, sep, window = value.partition(":")
                start_s, dash, end_s = window.partition("-")
                if not sep or not dash or not name.strip():
                    raise ValueError(
                        f"bad chaos phase {value!r}: want "
                        f"phase=<name>:<start>-<end>")
                phases[name.strip()] = (float(start_s), float(end_s))
            elif "@" in value:
                # <point>=<rate>@<phase>
                rate_s, _, phase = value.partition("@")
                phase = phase.strip()
                if not phase:
                    raise ValueError(f"bad chaos spec element {part!r}: "
                                     f"empty phase after '@'")
                phased.setdefault(key, {})[phase] = float(rate_s)
            else:
                rates[key] = float(value)   # key validated by __init__
        return cls(seed=seed, rates=rates, delay_ms=delay_ms,
                   phases=phases, phased=phased)

    def spec(self) -> str:
        """Round-trip back to the env-var grammar."""
        parts = [f"seed={self.seed}", f"delay_ms={self.delay_ms:g}"]
        parts += [f"phase={n}:{a:g}-{b:g}"
                  for n, (a, b) in self.phases.items()]
        parts += [f"{p}={r:g}" for p, r in self.rates.items() if r > 0.0]
        parts += [f"{p}={r:g}@{ph}"
                  for p, sched in self.phased.items()
                  for ph, r in sched.items()]
        return ";".join(parts)

    def target(self, point: str, where: str, count: int = 1) -> None:
        """Arm `point` to fire exactly `count` times at the injection
        site tagged `where` (a node name).  While a point has any armed
        target it fires ONLY by tag match — never by rate — so a drill
        can corrupt one victim replica without the process-global rate
        touching its healthy peers.  `count <= 0` disarms the
        (point, where) target (a drill re-arming elsewhere must revoke
        the old one, or a restarted victim could fire it later)."""
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown chaos fault point {point!r} "
                             f"(known: {', '.join(FAULT_POINTS)})")
        with self._lock:
            if count <= 0:
                tmap = self._targets.get(point)
                if tmap is not None:
                    tmap.pop(str(where), None)
                    if not tmap:
                        del self._targets[point]
                return
            self._targets.setdefault(point, {})[str(where)] = int(count)

    def pending_target(self, point: str, where: str) -> int:
        """Remaining armed fire count for (point, where) — drills poll
        this to learn whether the injection actually landed (the victim
        may have been replaced before its apply loop hit the site)."""
        with self._lock:
            return self._targets.get(point, {}).get(str(where), 0)

    def should(self, point: str, where: Optional[str] = None) -> bool:
        with self._lock:
            tmap = self._targets.get(point)
            if tmap:
                left = tmap.get(where, 0)
                if left <= 0:
                    return False
                if left == 1:
                    del tmap[where]
                    if not tmap:
                        del self._targets[point]
                else:
                    tmap[where] = left - 1
                self.stats[point] += 1
                return True
        rate = self.effective_rate(point)
        if rate <= 0.0:
            return False
        with self._lock:
            hit = self._rng.random() < rate
            if hit:
                self.stats[point] += 1
        return hit

    def uniform(self) -> float:
        """Seeded parameter draw for a fault that already fired (e.g. how
        much of a torn record survives); shares the registry RNG so the
        whole fault schedule stays a function of the seed."""
        with self._lock:
            return self._rng.random()


# The installed registry; None = chaos disabled (the common case).
# Injection sites read this module attribute directly so the disabled
# fast path is a load + identity check.
active: Optional[ChaosRegistry] = None


def install(registry: Optional[ChaosRegistry]) -> Optional[ChaosRegistry]:
    """Install (or, with None, remove) the process-wide registry.
    Returns the previous one so callers can restore it."""
    global active
    prev = active
    active = registry
    return prev


def uninstall() -> Optional[ChaosRegistry]:
    return install(None)


def arm(now: Optional[float] = None) -> None:
    """Anchor the active registry's phase clock (no-op when disabled)."""
    reg = active
    if reg is not None:
        reg.arm(now)


def should(point: str, where: Optional[str] = None) -> bool:
    reg = active
    return reg is not None and reg.should(point, where)


def fire(point: str) -> None:
    """Raise ChaosError if `point` fires.  Call sites that need a
    domain-specific exception type use should() and raise their own."""
    reg = active
    if reg is not None and reg.should(point):
        raise ChaosError(point)


def maybe_delay(point: str = "rpc.delay") -> None:
    reg = active
    if reg is not None and reg.should(point):
        time.sleep(reg.delay_ms / 1000.0)   # analysis: allow(wait-graph) — chaos fault injection sleeps on purpose


_env_spec = knobs.get_str("NOMAD_TPU_CHAOS")
if _env_spec:
    active = ChaosRegistry.from_spec(_env_spec)
