"""Native host-runtime kernels: build + ctypes binding.

Compiles native/nomad_native.cpp with g++ on first use (cached by source
mtime under native/build/), exposing:

  allocs_fit(capacity, used, demand) -> bool[N]
  score_fit(capacity, used, demand, spread=False) -> f32[N]
  ports_check(port_words, row, ports, freed) -> bool
  ports_set(port_words, row, ports, value)
  scatter_add(used, rows, deltas)
  scatter_add_rank1(used, rows, counts, demand)
  validate_plan(...) -> bool[G]     (the EvaluatePool equivalent)
  expand_pairs(rows, counts, scores) -> (i32[K], f32[K])
  format_uuids(n) -> list[str]      (batch generate_uuid)

Falls back to numpy implementations when no C++ toolchain is available
(`NATIVE_AVAILABLE` tells you which path is live).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu import chaos, knobs

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.normpath(os.path.join(_HERE, "..", "..", "native",
                                     "nomad_native.cpp"))
_BUILD_DIR = os.path.join(os.path.dirname(_SRC), "build")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
NATIVE_AVAILABLE = False

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> Optional[str]:
    """Compile the native library, cached by source *content hash* (an
    mtime check could silently prefer a stale or foreign-toolchain binary
    after a checkout).  NOMAD_TPU_NATIVE_LIB overrides with a prebuilt
    .so (the sanitizer CI leg points this at an ASan/UBSan build)."""
    override = knobs.get_str("NOMAD_TPU_NATIVE_LIB")
    if override:
        return override if os.path.exists(override) else None
    if not os.path.exists(_SRC):
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    with open(_SRC, "rb") as fh:
        digest = hashlib.sha256(fh.read()).hexdigest()[:16]
    lib_path = os.path.join(_BUILD_DIR, f"libnomad_native-{digest}.so")
    if os.path.exists(lib_path):
        return lib_path
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", lib_path + ".tmp", _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    os.replace(lib_path + ".tmp", lib_path)
    # prune superseded digests so the build dir doesn't grow unboundedly
    for name in os.listdir(_BUILD_DIR):
        if name.startswith("libnomad_native") and name.endswith(".so") \
                and name != os.path.basename(lib_path):
            try:
                os.remove(os.path.join(_BUILD_DIR, name))
            except OSError:
                pass
    return lib_path


def _load() -> Optional[ctypes.CDLL]:
    global _lib, NATIVE_AVAILABLE
    with _lock:
        if _lib is not None:
            return _lib
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.nomad_native_abi_version.restype = ctypes.c_int32
        got = lib.nomad_native_abi_version()
        if got != 2:
            # a wrong-ABI library silently misreading argument layouts is
            # far worse than no library: fail loudly, never fall back
            raise RuntimeError(
                f"nomad_native ABI mismatch: {path} reports version "
                f"{got}, bindings require 2 — rebuild the library "
                f"(delete {_BUILD_DIR}) or fix NOMAD_TPU_NATIVE_LIB")
        lib.allocs_fit_dense.restype = None
        lib.allocs_fit_dense.argtypes = [
            _f32p, _f32p, _f32p, ctypes.c_int, ctypes.c_int, _u8p]
        lib.score_fit_dense.restype = None
        lib.score_fit_dense.argtypes = [
            _f32p, _f32p, _f32p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, _f32p]
        lib.ports_check.restype = ctypes.c_int32
        lib.ports_check.argtypes = [
            _u32p, ctypes.c_int, ctypes.c_int, _i32p, ctypes.c_int,
            _i32p, ctypes.c_int]
        lib.ports_set.restype = None
        lib.ports_set.argtypes = [
            _u32p, ctypes.c_int, ctypes.c_int, _i32p, ctypes.c_int,
            ctypes.c_int]
        lib.scatter_add.restype = None
        lib.scatter_add.argtypes = [
            _f32p, ctypes.c_int, _i32p, _f32p, ctypes.c_int]
        lib.validate_plan.restype = None
        lib.validate_plan.argtypes = [
            _f32p, _f32p, _u32p, ctypes.c_int, ctypes.c_int,
            _i32p, _f32p, _f32p, _i32p, _i32p, _i32p, _i32p,
            ctypes.c_int, _u8p]
        lib.expand_pairs.restype = ctypes.c_int32
        lib.expand_pairs.argtypes = [
            _i32p, _i32p, _f32p, ctypes.c_int, _i32p, _f32p,
            ctypes.c_int32]
        lib.format_uuids.restype = None
        lib.format_uuids.argtypes = [
            _u8p, ctypes.c_int, ctypes.c_char_p]
        lib.scatter_add_rank1.restype = None
        lib.scatter_add_rank1.argtypes = [
            _f32p, ctypes.c_int, _i32p, _i32p, _f32p, ctypes.c_int]
        _lib = lib
        NATIVE_AVAILABLE = True
        return lib


class CircuitBreaker:
    """Trips to the Python fallback after `threshold` consecutive native
    failures — a bad build or ABI drift fails on every call, and one trip
    beats paying an exception (or a crash risk) per call.  `reset()`
    closes the circuit again (e.g. after a rebuild)."""

    def __init__(self, threshold: int = 3):
        self.threshold = max(1, int(threshold))
        self._lock = threading.Lock()
        self._consecutive = 0
        self.open = False
        self.stats = {"failures": 0, "trips": 0}

    def record_ok(self) -> None:
        if self._consecutive:
            with self._lock:
                self._consecutive = 0

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            self.stats["failures"] += 1
            if not self.open and self._consecutive >= self.threshold:
                self.open = True
                self.stats["trips"] += 1

    def reset(self) -> None:
        with self._lock:
            self._consecutive = 0
            self.open = False


breaker = CircuitBreaker(knobs.get_int("NOMAD_TPU_NATIVE_BREAKER"))


def _native_lib() -> Optional[ctypes.CDLL]:
    """The library iff the circuit is closed; every native call site goes
    through here so an open breaker routes everything to Python."""
    if breaker.open:
        return None
    return _load()


_EMPTY_I32 = np.zeros(0, np.int32)


def allocs_fit(capacity: np.ndarray, used: np.ndarray,
               demand: np.ndarray) -> np.ndarray:
    """bool[N]: demand fits in capacity-used per row
    (structs.AllocsFit over the node axis)."""
    capacity = np.ascontiguousarray(capacity, np.float32)
    used = np.ascontiguousarray(used, np.float32)
    demand = np.ascontiguousarray(demand, np.float32)
    lib = _native_lib()
    if lib is not None:
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            out = np.empty(capacity.shape[0], np.uint8)
            lib.allocs_fit_dense(capacity, used, demand,
                                 capacity.shape[0], capacity.shape[1], out)
            breaker.record_ok()
            return out.astype(bool)
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    return np.all(used + demand <= capacity + 1e-6, axis=1)


def score_fit(capacity: np.ndarray, used: np.ndarray,
              demand: np.ndarray, spread: bool = False) -> np.ndarray:
    """f32[N] binpack/spread score (structs.ScoreFitBinPack/Spread)."""
    capacity = np.ascontiguousarray(capacity, np.float32)
    used = np.ascontiguousarray(used, np.float32)
    demand = np.ascontiguousarray(demand, np.float32)
    lib = _native_lib()
    if lib is not None:
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            out = np.empty(capacity.shape[0], np.float32)
            lib.score_fit_dense(capacity, used, demand, capacity.shape[0],
                                capacity.shape[1], int(spread), out)
            breaker.record_ok()
            return out
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    cap = np.maximum(capacity[:, :2], 1e-9)
    free = np.clip((cap - (used[:, :2] + demand[:2])) / cap, 0.0, 1.0)
    exp = 1.0 - free if spread else free
    total = np.power(10.0, exp).sum(axis=1)
    total = np.where((capacity[:, :2] <= 0).any(axis=1), 40.0, total)
    return np.clip((20.0 - total) / 18.0, 0.0, 1.0).astype(np.float32)


def ports_check(port_words: np.ndarray, row: int,
                ports: Sequence[int],
                freed: Sequence[int] = ()) -> bool:
    """All `ports` free on `row` (ports in `freed` count as free)?"""
    ports_a = np.asarray(list(ports), np.int32)
    freed_a = np.asarray(list(freed), np.int32)
    lib = _native_lib()
    if lib is not None:
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            pw = np.ascontiguousarray(port_words, np.uint32)
            ok = bool(lib.ports_check(pw, pw.shape[1], row,
                                      ports_a, len(ports_a),
                                      freed_a, len(freed_a)))
            breaker.record_ok()
            return ok
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    seen = set()
    for p in ports_a:
        p = int(p)
        if p in seen:
            return False
        seen.add(p)
        if p < 0 or (p >> 5) >= port_words.shape[1]:
            return False
        if (port_words[row, p >> 5] >> np.uint32(p & 31)) & 1:
            if p not in set(int(x) for x in freed_a):
                return False
    return True


def ports_set(port_words: np.ndarray, row: int,
              ports: Sequence[int], value: bool) -> None:
    ports_a = np.asarray(list(ports), np.int32)
    lib = _native_lib()
    if lib is not None and port_words.flags["C_CONTIGUOUS"]:
        # per-port bit sets are idempotent, so retrying the whole batch in
        # Python after a mid-call native failure is safe
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            lib.ports_set(port_words, port_words.shape[1], row,
                          ports_a, len(ports_a), int(value))
            breaker.record_ok()
            return
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    for p in ports_a:
        p = int(p)
        if p < 0 or (p >> 5) >= port_words.shape[1]:
            continue
        if value:
            port_words[row, p >> 5] |= np.uint32(1 << (p & 31))
        else:
            port_words[row, p >> 5] &= ~np.uint32(1 << (p & 31))


def scatter_add(used: np.ndarray, rows: Sequence[int],
                deltas: np.ndarray) -> None:
    """used[rows[k]] += deltas[k] in place."""
    rows_a = np.asarray(list(rows), np.int32)
    deltas = np.ascontiguousarray(deltas, np.float32)
    lib = _native_lib()
    if lib is not None and used.flags["C_CONTIGUOUS"]:
        # += is not idempotent, so failures must surface before the native
        # call touches `used`: ctypes argtype errors and injected faults
        # both raise pre-entry
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            lib.scatter_add(used, used.shape[1], rows_a, deltas,
                            len(rows_a))
            breaker.record_ok()
            return
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    np.add.at(used, rows_a, deltas)


def validate_plan(capacity: np.ndarray, used: np.ndarray,
                  port_words: np.ndarray,
                  rows: Sequence[int],
                  demand: np.ndarray, freed: np.ndarray,
                  group_ports: List[Sequence[int]],
                  group_freed_ports: List[Sequence[int]]) -> np.ndarray:
    """bool[G]: per placement-group validation (fit + ports), the
    EvaluatePool fan-out as one native call."""
    g = len(rows)
    rows_a = np.asarray(list(rows), np.int32)
    demand = np.ascontiguousarray(demand, np.float32)
    freed = np.ascontiguousarray(freed, np.float32)
    ports_off = np.zeros(g + 1, np.int32)
    freed_off = np.zeros(g + 1, np.int32)
    flat_ports: List[int] = []
    flat_freed: List[int] = []
    for i in range(g):
        flat_ports.extend(int(p) for p in group_ports[i])
        flat_freed.extend(int(p) for p in group_freed_ports[i])
        ports_off[i + 1] = len(flat_ports)
        freed_off[i + 1] = len(flat_freed)
    ports_a = np.asarray(flat_ports, np.int32) if flat_ports else _EMPTY_I32
    freed_a = np.asarray(flat_freed, np.int32) if flat_freed else _EMPTY_I32
    lib = _native_lib()
    if lib is not None:
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            cap_c = np.ascontiguousarray(capacity, np.float32)
            used_c = np.ascontiguousarray(used, np.float32)
            pw_c = np.ascontiguousarray(port_words, np.uint32)
            out = np.empty(g, np.uint8)
            lib.validate_plan(cap_c, used_c, pw_c, pw_c.shape[1],
                              cap_c.shape[1], rows_a, demand, freed,
                              ports_a, ports_off, freed_a, freed_off, g,
                              out)
            breaker.record_ok()
            return out.astype(bool)
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    out = np.zeros(g, bool)
    for i in range(g):
        r = int(rows_a[i])
        if r < 0:
            continue
        fits = np.all(used[r] + demand[i] - freed[i]
                      <= capacity[r] + 1e-6)
        out[i] = fits and ports_check(
            port_words, r, group_ports[i], group_freed_ports[i])
    return out


def expand_pairs(rows: np.ndarray, counts: np.ndarray,
                 scores: Optional[np.ndarray] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten resolved sparse bulk output — (row, count, score)
    triples — into per-alloc (rows i32[K], scores f32[K]) arrays in
    placement order; K = counts.clip(0).sum().  The bulk materializer's
    one-call-per-dispatch expansion."""
    rows_a = np.ascontiguousarray(rows, np.int32)
    counts_a = np.ascontiguousarray(counts, np.int32)
    if scores is None:
        scores_a = np.zeros(rows_a.shape[0], np.float32)
    else:
        scores_a = np.ascontiguousarray(scores, np.float32)
    total = int(np.clip(counts_a, 0, None).sum())
    lib = _native_lib()
    if lib is not None and total > 0:
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            out_rows = np.empty(total, np.int32)
            out_scores = np.empty(total, np.float32)
            w = lib.expand_pairs(rows_a, counts_a, scores_a,
                                 rows_a.shape[0], out_rows, out_scores,
                                 total)
            breaker.record_ok()
            if w == total:              # defensive; cap == exact total
                return out_rows, out_scores
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    keep = counts_a > 0
    return (np.repeat(rows_a[keep], counts_a[keep]),
            np.repeat(scores_a[keep], counts_a[keep]))


def format_uuids(n: int) -> List[str]:
    """n fresh uuid strings in one call, byte-identical in format to
    utils.generate_uuid (hex of os.urandom(16), 8-4-4-4-12)."""
    if n <= 0:
        return []
    rnd = np.frombuffer(os.urandom(16 * n), np.uint8)
    lib = _native_lib()
    if lib is not None:
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            out = ctypes.create_string_buffer(36 * n)
            lib.format_uuids(np.ascontiguousarray(rnd), n, out)
            raw = out.raw
            breaker.record_ok()
            return [raw[i * 36:(i + 1) * 36].decode("ascii")
                    for i in range(n)]
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    h = rnd.tobytes().hex()
    return [f"{s[:8]}-{s[8:12]}-{s[12:16]}-{s[16:20]}-{s[20:]}"
            for s in (h[i * 32:(i + 1) * 32] for i in range(n))]


def scatter_add_rank1(used: np.ndarray, rows: np.ndarray,
                      counts: np.ndarray, demand: np.ndarray) -> None:
    """used[rows[k]] += counts[k] * demand in place, without building
    the [K, dims] delta matrix."""
    rows_a = np.ascontiguousarray(rows, np.int32)
    counts_a = np.ascontiguousarray(counts, np.int32)
    demand_a = np.ascontiguousarray(demand, np.float32)
    lib = _native_lib()
    if lib is not None and used.flags["C_CONTIGUOUS"] \
            and used.dtype == np.float32:
        try:
            if chaos.active is not None:
                chaos.fire("native.fail")
            lib.scatter_add_rank1(used, used.shape[1], rows_a, counts_a,
                                  demand_a, rows_a.shape[0])
            breaker.record_ok()
            return
        except Exception:                          # noqa: BLE001
            breaker.record_failure()
    np.add.at(used, rows_a,
              counts_a[:, None].astype(used.dtype) * demand_a)
