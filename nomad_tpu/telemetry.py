"""In-process telemetry registry (reference: armon/go-metrics wired in
command/agent/command.go:1034-1140, exposed at /v1/metrics and documented
in website/content/docs/operations/metrics-reference.mdx).

Canonical names mirror the reference's scheduler metrics:
  nomad.plan.evaluate / nomad.plan.submit      (plan_apply.go:185)
  nomad.worker.invoke_scheduler.<type>         (worker.go:554)
  nomad.broker.total_ready / total_unacked     (eval_broker metrics)
plus whatever callers emit.  Counters, gauges, and timing samples with
mean/max/p99; JSON snapshot for /v1/metrics and Prometheus text
exposition for /v1/metrics?format=prometheus.
"""
from __future__ import annotations

import random
import threading
import time
from collections import defaultdict
from typing import Dict, List


class _Sample:
    __slots__ = ("count", "total", "max", "values", "_rng")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.values: List[float] = []          # bounded reservoir
        # seeded so summaries are reproducible across runs; per-instance
        # so concurrent series don't share generator state
        self._rng = random.Random(0x5EED)

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.max = max(self.max, v)
        if len(self.values) < 1024:
            self.values.append(v)
        else:
            # Vitter's Algorithm R: keep the new value with probability
            # 1024/count at a uniform slot, so every observation — not
            # just the last 1024 — has equal weight in the percentiles.
            # (The old `count % 1024` ring overwrote oldest-first, which
            # biased p50/p99 toward the most recent window.)
            j = self._rng.randrange(self.count)
            if j < 1024:
                self.values[j] = v

    def summary(self) -> dict:
        vals = sorted(self.values)
        p50 = vals[min(len(vals) - 1, int(len(vals) * 0.50))] if vals else 0.0
        p99 = vals[min(len(vals) - 1, int(len(vals) * 0.99))] if vals else 0.0
        return {"count": self.count,
                "mean": self.total / self.count if self.count else 0.0,
                "max": self.max, "p50": p50, "p99": p99}


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._samples: Dict[str, _Sample] = defaultdict(_Sample)

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def set_gauges(self, values: Dict[str, float],
                   prefix: str = "") -> None:
        """Bulk gauge publish under ONE lock acquisition — a snapshot
        reader never sees half of a related set (e.g. the recompile
        budget's per-kernel counts) from two different instants."""
        with self._lock:
            for name, value in values.items():
                self._gauges[prefix + name] = value

    def add_sample(self, name: str, value: float) -> None:
        with self._lock:
            self._samples[name].add(value)

    def measure_since(self, name: str, start: float) -> None:
        self.add_sample(name, (time.time() - start) * 1000.0)  # ms

    def take_sample(self, name: str) -> dict:
        """Summary of one timing series, then reset it — per-window
        measurement (bench scenarios, tests)."""
        with self._lock:
            s = self._samples.pop(name, None)
        return s.summary() if s is not None else _Sample().summary()

    class _Timer:
        __slots__ = ("reg", "name", "start")

        def __init__(self, reg, name):
            self.reg = reg
            self.name = name

        def __enter__(self):
            self.start = time.time()
            return self

        def __exit__(self, *exc):
            self.reg.measure_since(self.name, self.start)
            return False

    def timer(self, name: str) -> "_Timer":
        return self._Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "Counters": [{"Name": k, "Count": v}
                             for k, v in sorted(self._counters.items())],
                "Gauges": [{"Name": k, "Value": v}
                           for k, v in sorted(self._gauges.items())],
                "Samples": [dict(Name=k, **s.summary())
                            for k, s in sorted(self._samples.items())],
            }

    def prometheus(self) -> str:
        """Prometheus text exposition: every family gets HELP + TYPE,
        counters carry the conventional `_total` suffix, and when two
        raw names sanitize to the same exposition name only the first is
        exported (scrapers hard-fail on duplicate TYPE blocks; the
        skipped name is noted in a comment so the collision is
        visible)."""
        def san(n):
            return n.replace(".", "_").replace("-", "_")
        lines: List[str] = []
        seen: Dict[str, str] = {}   # exposition name -> raw name

        def family(raw: str, name: str, kind: str) -> bool:
            if name in seen:
                lines.append(f"# collision: {raw!r} sanitizes to "
                             f"{name} (already exported for "
                             f"{seen[name]!r}); skipped")
                return False
            seen[name] = raw
            lines.append(f"# HELP {name} nomad_tpu {kind} {raw}")
            lines.append(f"# TYPE {name} {kind}")
            return True

        with self._lock:
            for k, v in sorted(self._counters.items()):
                name = san(k) + "_total"
                if family(k, name, "counter"):
                    lines.append(f"{name} {v}")
            for k, v in sorted(self._gauges.items()):
                name = san(k)
                if family(k, name, "gauge"):
                    lines.append(f"{name} {v}")
            for k, s in sorted(self._samples.items()):
                m = s.summary()
                base = san(k)
                if family(k, base, "summary"):
                    lines.append(f'{base}{{quantile="0.5"}} {m["p50"]}')
                    lines.append(f'{base}{{quantile="0.99"}} {m["p99"]}')
                    lines.append(f"{base}_sum {s.total}")
                    lines.append(f"{base}_count {m['count']}")
        return "\n".join(lines) + "\n"


# process-global default registry (the reference's metrics.Default())
global_metrics = MetricsRegistry()
