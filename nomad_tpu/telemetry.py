"""In-process telemetry registry (reference: armon/go-metrics wired in
command/agent/command.go:1034-1140, exposed at /v1/metrics and documented
in website/content/docs/operations/metrics-reference.mdx).

Canonical names mirror the reference's scheduler metrics:
  nomad.plan.evaluate / nomad.plan.submit      (plan_apply.go:185)
  nomad.worker.invoke_scheduler.<type>         (worker.go:554)
  nomad.broker.total_ready / total_unacked     (eval_broker metrics)
plus whatever callers emit.  Counters, gauges, and timing samples with
mean/max/p99; JSON snapshot for /v1/metrics and Prometheus text
exposition for /v1/metrics?format=prometheus.
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, List


class _Sample:
    __slots__ = ("count", "total", "max", "values")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.values: List[float] = []          # bounded reservoir

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.max = max(self.max, v)
        if len(self.values) < 1024:
            self.values.append(v)
        else:                                   # reservoir replacement
            self.values[self.count % 1024] = v

    def summary(self) -> dict:
        vals = sorted(self.values)
        p50 = vals[min(len(vals) - 1, int(len(vals) * 0.50))] if vals else 0.0
        p99 = vals[min(len(vals) - 1, int(len(vals) * 0.99))] if vals else 0.0
        return {"count": self.count,
                "mean": self.total / self.count if self.count else 0.0,
                "max": self.max, "p50": p50, "p99": p99}


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._samples: Dict[str, _Sample] = defaultdict(_Sample)

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def set_gauges(self, values: Dict[str, float],
                   prefix: str = "") -> None:
        """Bulk gauge publish under ONE lock acquisition — a snapshot
        reader never sees half of a related set (e.g. the recompile
        budget's per-kernel counts) from two different instants."""
        with self._lock:
            for name, value in values.items():
                self._gauges[prefix + name] = value

    def add_sample(self, name: str, value: float) -> None:
        with self._lock:
            self._samples[name].add(value)

    def measure_since(self, name: str, start: float) -> None:
        self.add_sample(name, (time.time() - start) * 1000.0)  # ms

    def take_sample(self, name: str) -> dict:
        """Summary of one timing series, then reset it — per-window
        measurement (bench scenarios, tests)."""
        with self._lock:
            s = self._samples.pop(name, None)
        return s.summary() if s is not None else _Sample().summary()

    class _Timer:
        __slots__ = ("reg", "name", "start")

        def __init__(self, reg, name):
            self.reg = reg
            self.name = name

        def __enter__(self):
            self.start = time.time()
            return self

        def __exit__(self, *exc):
            self.reg.measure_since(self.name, self.start)
            return False

    def timer(self, name: str) -> "_Timer":
        return self._Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "Counters": [{"Name": k, "Count": v}
                             for k, v in sorted(self._counters.items())],
                "Gauges": [{"Name": k, "Value": v}
                           for k, v in sorted(self._gauges.items())],
                "Samples": [dict(Name=k, **s.summary())
                            for k, s in sorted(self._samples.items())],
            }

    def prometheus(self) -> str:
        """Prometheus text exposition (metric names sanitized)."""
        def san(n):
            return n.replace(".", "_").replace("-", "_")
        lines = []
        with self._lock:
            for k, v in sorted(self._counters.items()):
                lines.append(f"# TYPE {san(k)} counter")
                lines.append(f"{san(k)} {v}")
            for k, v in sorted(self._gauges.items()):
                lines.append(f"# TYPE {san(k)} gauge")
                lines.append(f"{san(k)} {v}")
            for k, s in sorted(self._samples.items()):
                m = s.summary()
                base = san(k)
                lines.append(f"# TYPE {base} summary")
                lines.append(f'{base}{{quantile="0.5"}} {m["p50"]}')
                lines.append(f'{base}{{quantile="0.99"}} {m["p99"]}')
                lines.append(f"{base}_sum {s.total}")
                lines.append(f"{base}_count {m['count']}")
        return "\n".join(lines) + "\n"


# process-global default registry (the reference's metrics.Default())
global_metrics = MetricsRegistry()
