"""Artifact getter (reference client/allocrunner/taskrunner/getter/
getter.go — go-getter behind a sandboxed API: source URL + options
(checksum), client modes any/file/dir, destination relative to the task
dir, archive auto-extraction).

This environment has no network egress, so the wire schemes are
`file://` URLs, bare local paths, and plain `http(s)://` for
link-local/test servers (urllib, short timeout).  Everything else the
reference getter does — env interpolation of source/destination,
checksum verification before install, tar/zip unpacking in "any" mode,
and refusing destinations that escape the task sandbox (the reference's
helper/escapingfs guard) — is kept.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import urllib.parse
import urllib.request
import zipfile
from typing import Dict, Optional

from nomad_tpu.client.taskenv import interpolate


class ArtifactError(Exception):
    """Fetch/verify failure; recoverable (the task restarts per policy),
    matching the reference's GetError.Recoverable()."""


_ARCHIVE_EXTS = (".tar.gz", ".tgz", ".tar.bz2", ".tar", ".zip")


def _inside(root: str, path: str) -> str:
    """Resolve `path` and require it stays under `root` (escapingfs)."""
    real = os.path.realpath(path)
    if not (real + os.sep).startswith(os.path.realpath(root) + os.sep):
        raise ArtifactError(f"destination escapes task dir: {path}")
    return real


def _verify_checksum(path: str, spec: str) -> None:
    """spec: '<algo>:<hexdigest>' (md5/sha1/sha256/sha512), the
    go-getter checksum option format."""
    try:
        algo, want = spec.split(":", 1)
        h = hashlib.new(algo)
    except Exception as e:                          # noqa: BLE001
        raise ArtifactError(f"bad checksum spec {spec!r}: {e}") from e
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    got = h.hexdigest()
    if got.lower() != want.strip().lower():
        raise ArtifactError(
            f"checksum mismatch for {os.path.basename(path)}: "
            f"got {algo}:{got}, want {spec}")


def _fetch_to(src: str, dst_file: str) -> None:
    parsed = urllib.parse.urlparse(src)
    if parsed.scheme in ("http", "https"):
        try:
            with urllib.request.urlopen(src, timeout=30) as resp, \
                    open(dst_file, "wb") as out:
                shutil.copyfileobj(resp, out)
        except Exception as e:                      # noqa: BLE001
            raise ArtifactError(f"fetch {src}: {e}") from e
        return
    if parsed.scheme == "file":
        src = parsed.path
    if not os.path.exists(src):
        raise ArtifactError(f"artifact source not found: {src}")
    if os.path.isdir(src):
        raise ArtifactError(f"source is a directory (use mode=dir): {src}")
    shutil.copy(src, dst_file)       # copy, not copyfile: keep exec bits


def _source_path(src: str) -> Optional[str]:
    """Local filesystem path for file:// / bare-path sources, else None."""
    parsed = urllib.parse.urlparse(src)
    if parsed.scheme == "file":
        return parsed.path
    if parsed.scheme in ("http", "https"):
        return None
    return src


def _extract(archive: str, dest_dir: str) -> None:
    try:
        if archive.endswith(".zip"):
            with zipfile.ZipFile(archive) as z:
                for m in z.namelist():
                    _inside(dest_dir, os.path.join(dest_dir, m))
                z.extractall(dest_dir)
        else:
            with tarfile.open(archive) as t:
                for m in t.getmembers():
                    _inside(dest_dir, os.path.join(dest_dir, m.name))
                t.extractall(dest_dir, filter="data")
    except ArtifactError:
        raise
    except Exception as e:                          # noqa: BLE001
        raise ArtifactError(f"extract {archive}: {e}") from e


def fetch_artifact(artifact: dict, task_dir: str,
                   env: Optional[Dict[str, str]] = None,
                   node=None, meta: Optional[Dict[str, str]] = None) -> str:
    """Fetch one artifact into the task dir; returns the install path.

    artifact keys (jobspec `artifact` block): source, destination
    (default "local/"), mode ("any"|"file"|"dir"), options{checksum}.
    source/destination take the full taskenv interpolation set
    (${env.X}/${meta.X}/${attr.X}/${NOMAD_*}), same as templates.
    """
    env = env or {}
    source = interpolate(str(artifact.get("source", "")), env, node, meta)
    if not source:
        raise ArtifactError("artifact has no source")
    dest_rel = interpolate(str(artifact.get("destination", "local/")),
                           env, node, meta)
    mode = str(artifact.get("mode", "any") or "any")
    options = artifact.get("options") or {}
    checksum = options.get("checksum", "")

    dest = _inside(task_dir, os.path.join(task_dir, dest_rel))
    local_src = _source_path(source)

    if mode == "dir" or (mode == "any" and local_src
                         and os.path.isdir(local_src)):
        if not local_src or not os.path.isdir(local_src):
            raise ArtifactError(f"mode=dir needs a local dir: {source}")
        if checksum:
            # silently skipping verification would be worse than failing
            raise ArtifactError(
                "checksum is not supported for directory artifacts")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copytree(local_src, dest, dirs_exist_ok=True)
        return dest

    base = os.path.basename(
        urllib.parse.urlparse(source).path or source) or "artifact"
    # "file" mode: destination IS the file path (go-getter ClientModeFile)
    if mode == "file" and not dest_rel.endswith(("/", os.sep)):
        target = dest
        os.makedirs(os.path.dirname(target), exist_ok=True)
    else:
        os.makedirs(dest, exist_ok=True)
        target = _inside(task_dir, os.path.join(dest, base))

    _fetch_to(source, target)
    if checksum:
        _verify_checksum(target, checksum)
    if mode == "any" and target.endswith(_ARCHIVE_EXTS):
        dest_dir = dest if os.path.isdir(dest) else os.path.dirname(dest)
        _extract(target, dest_dir)
        os.unlink(target)
        return dest_dir
    return target
