"""Client-side nomad-native service registration hook (reference
client/serviceregistration/nsd/nsd.go + the alloc runner's group_service
hook, client/allocrunner/groupservice_hook.go).

When an allocation's tasks are running, every `provider = "nomad"`
service declared on the task group or its tasks registers with the
servers (Service.Upsert); on stop/destroy the allocation's registrations
delete (Service.DeleteByAlloc).  A lightweight check runner keeps each
registration's health current: a check passes while its owning task is
running — the simulator analog of nsd's tcp/http probes — and flips the
registration to "critical" otherwise, which the deployment watcher
consumes for `health_check = "checks"` task groups.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from nomad_tpu.structs.service import ServiceRegistration, registration_id


class ServiceHook:
    """Per-alloc registration lifecycle.  `rpc` is the client->server
    callable; None disables the hook (server-side simulations that never
    run a client)."""

    def __init__(self, alloc, node, rpc: Optional[Callable],
                 poll_interval: float = 0.2):
        self.alloc = alloc
        self.node = node
        self.rpc = rpc
        self.poll_interval = poll_interval
        self._regs: Dict[str, ServiceRegistration] = {}
        self._health: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ build

    def _build(self, task_states) -> List[ServiceRegistration]:
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        if tg is None:
            return []
        ports = {}
        for net in self.alloc.allocated_resources.shared_networks:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                if p.label:
                    ports[p.label] = p.value
        out = []

        def add(svc, task_name: str):
            if getattr(svc, "provider", "nomad") not in ("nomad", ""):
                return   # consul-provider services need a Consul agent
            rid = registration_id(self.alloc.id, task_name, svc.name,
                                  svc.port_label)
            out.append(ServiceRegistration(
                id=rid, service_name=svc.name,
                namespace=self.alloc.namespace,
                node_id=self.node.id if self.node else "",
                datacenter=getattr(self.node, "datacenter", ""),
                job_id=self.alloc.job_id, alloc_id=self.alloc.id,
                tags=list(svc.tags),
                address=getattr(self.node, "http_addr", "") or "127.0.0.1",
                port=ports.get(svc.port_label, 0),
                health=self._svc_health(svc, task_name, task_states)))

        for svc in tg.services:
            add(svc, "")
        for t in tg.tasks:
            for svc in getattr(t, "services", []) or []:
                add(svc, t.name)
        return out

    def _svc_health(self, svc, task_name: str, task_states) -> str:
        """Check verdict for one service: its checks pass while the
        owning task (or any main task, for group services) is running."""
        if task_name:
            st = task_states.get(task_name)
            running = st is not None and st.state == "running"
        else:
            running = any(s.state == "running"
                          for s in task_states.values())
        if not svc.checks:
            return "passing"
        return "passing" if running else "critical"

    # ------------------------------------------------------------ lifecycle

    def start(self, task_states_fn: Callable[[], dict]) -> None:
        """Begin registration + health polling once tasks launch."""
        if self.rpc is None:
            return

        def run():
            while not self._stop.is_set():
                try:
                    regs = self._build(task_states_fn())
                    changed = []
                    for r in regs:
                        if self._health.get(r.id) != r.health:
                            self._health[r.id] = r.health
                            changed.append(r)
                            self._regs[r.id] = r
                    if changed:
                        self.rpc("Service.Upsert", {"services": changed})
                except Exception:               # noqa: BLE001
                    pass
                if self._stop.wait(self.poll_interval):
                    return

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="service-hook")
        self._thread.start()

    def all_passing(self) -> bool:
        """Every built registration currently passing (deployment
        health_check='checks' feed).  True when the alloc declares no
        nomad services."""
        if not self._health:
            return True
        return all(h == "passing" for h in self._health.values())

    def has_services(self) -> bool:
        return bool(self._health)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(1.0)
        if self.rpc is not None and self._regs:
            try:
                self.rpc("Service.DeleteByAlloc",
                         {"alloc_id": self.alloc.id})
            except Exception:                   # noqa: BLE001
                pass
            self._regs.clear()
