"""Host fingerprinting (reference: client/fingerprint/ — arch, cpu,
memory, kernel, hostname, storage, nomad-version fingerprinters populating
Node.attributes and Node.node_resources).
"""
from __future__ import annotations

import os
import platform
import shutil
import socket
from typing import Dict, Tuple

from nomad_tpu.structs.node import (
    NodeCpuResources,
    NodeResources,
)


def fingerprint_arch() -> Dict[str, str]:
    m = platform.machine()
    return {"cpu.arch": {"x86_64": "amd64", "aarch64": "arm64"}.get(m, m),
            "arch": {"x86_64": "amd64", "aarch64": "arm64"}.get(m, m)}


def fingerprint_kernel() -> Dict[str, str]:
    return {"kernel.name": platform.system().lower(),
            "kernel.version": platform.release(),
            "os.name": platform.system().lower(),
            "os.version": platform.release()}


def fingerprint_host() -> Dict[str, str]:
    host = socket.gethostname()
    return {"unique.hostname": host,
            "unique.network.ip-address": "127.0.0.1"}


def fingerprint_cpu() -> Tuple[Dict[str, str], NodeCpuResources]:
    cores = os.cpu_count() or 1
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except (OSError, ValueError):
        pass
    total = int(cores * mhz)
    attrs = {"cpu.numcores": str(cores), "cpu.frequency": str(int(mhz)),
             "cpu.totalcompute": str(total)}
    return attrs, NodeCpuResources(cpu_shares=total,
                                   total_core_count=cores,
                                   reservable_cores=list(range(cores)))


def fingerprint_memory() -> Tuple[Dict[str, str], int]:
    total_mb = 1024
    try:
        with open("/proc/meminfo") as fh:
            for line in fh:
                if line.startswith("MemTotal"):
                    total_mb = int(line.split()[1]) // 1024
                    break
    except (OSError, ValueError):
        pass
    return {"memory.totalbytes": str(total_mb * 1024 * 1024)}, total_mb


def fingerprint_storage(path: str = "/") -> Tuple[Dict[str, str], int]:
    try:
        usage = shutil.disk_usage(path)
        free_mb = usage.free // (1024 * 1024)
    except OSError:
        free_mb = 10 * 1024
    return {"unique.storage.volume": path,
            "unique.storage.bytesfree": str(free_mb * 1024 * 1024)}, free_mb


def fingerprint_node(node, drivers: Dict[str, dict],
                     version: str = "0.1.0") -> None:
    """Populate a Node in place with host attributes + resources
    (the fingerprint_manager run, client/fingerprint_manager.go)."""
    attrs = {}
    attrs.update(fingerprint_arch())
    attrs.update(fingerprint_kernel())
    attrs.update(fingerprint_cloud())
    attrs.update(fingerprint_host())
    cpu_attrs, cpu_res = fingerprint_cpu()
    attrs.update(cpu_attrs)
    mem_attrs, mem_mb = fingerprint_memory()
    attrs.update(mem_attrs)
    sto_attrs, disk_mb = fingerprint_storage()
    attrs.update(sto_attrs)
    attrs["nomad.version"] = version
    for name, health in drivers.items():
        if health.get("detected"):
            attrs[f"driver.{name}"] = "1"
    node.attributes.update(attrs)
    node.node_resources = NodeResources(
        cpu=cpu_res, memory_mb=mem_mb, disk_mb=disk_mb)
    node.drivers = dict(drivers)


# --------------------------------------------------------------- cloud env

def fingerprint_cloud() -> Dict[str, str]:
    """Cloud-environment fingerprints (reference client/fingerprint/
    env_aws.go, env_gce.go, env_azure.go, env_digitalocean.go).  The
    reference queries each platform's metadata service with a short
    timeout; in network-restricted environments the detection falls back
    to platform environment markers and DMI vendor strings, yielding no
    attributes when nothing identifies a platform."""
    attrs: Dict[str, str] = {}
    vendor = ""
    for path in ("/sys/class/dmi/id/sys_vendor",
                 "/sys/class/dmi/id/product_name"):
        try:
            with open(path) as f:
                vendor += f.read().strip().lower() + " "
        except OSError:
            pass
    if "amazon" in vendor or os.environ.get("AWS_EXECUTION_ENV"):
        attrs["unique.platform.aws.hostname"] = os.uname().nodename
        attrs["platform.aws.detected"] = "true"
    if "google" in vendor or os.environ.get("GCE_METADATA_HOST"):
        attrs["unique.platform.gce.hostname"] = os.uname().nodename
        attrs["platform.gce.detected"] = "true"
    if "microsoft" in vendor:
        attrs["unique.platform.azure.name"] = os.uname().nodename
        attrs["platform.azure.detected"] = "true"
    if "digitalocean" in vendor:
        attrs["unique.platform.digitalocean.name"] = os.uname().nodename
        attrs["platform.digitalocean.detected"] = "true"
    return attrs
