"""Client (node agent) layer.

Reference: client/ — the agent that fingerprints the host, registers the
node, heartbeats, watches for assigned allocations, and executes them
through AllocRunner -> TaskRunner -> task driver pipelines
(client/client.go:169, allocrunner/, taskrunner/, drivers/).
"""
from nomad_tpu.client.client import Client, ClientConfig
from nomad_tpu.client.drivers import (
    DriverRegistry,
    MockDriver,
    RawExecDriver,
    TaskHandle,
)

__all__ = ["Client", "ClientConfig", "DriverRegistry", "MockDriver",
           "RawExecDriver", "TaskHandle"]
