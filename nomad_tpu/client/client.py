"""Client core (reference: client/client.go:169 — node registration
(:1602), heartbeating with TTL jitter, blocking-query allocation watching
(:2056), runAllocs diff (:2286), state restore, and alloc GC (gc.go)).

The client speaks to servers through an `rpc(method, args)` callable —
in-process for the dev agent, or a TCP transport client in a cluster —
the same boundary as the reference's msgpack-RPC.
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from nomad_tpu.client.allocrunner import AllocRunner
from nomad_tpu.client.drivers import DriverRegistry
from nomad_tpu.client.fingerprint import fingerprint_node
from nomad_tpu.client.state import ClientStateDB
from nomad_tpu.structs import Node
from nomad_tpu.structs.alloc import AllocClientStatus, AllocDesiredStatus
from nomad_tpu.structs.node import NodeStatus

log = logging.getLogger(__name__)


@dataclass
class ClientConfig:
    node_name: str = "client-1"
    datacenter: str = "dc1"
    node_class: str = ""
    region: str = "global"
    data_dir: str = ""                     # default: tempdir
    drivers: List[str] = field(
        default_factory=lambda: ["mock_driver", "raw_exec", "exec", "mock"])
    meta: Dict[str, str] = field(default_factory=dict)
    max_allocs_gc: int = 50                # GC threshold (gc.go)
    watch_interval: float = 0.2
    # device plugin fingerprint stream (reference plugins/device/
    # device.go:25-37): a callable returning the CURRENT [NodeDevice]
    # list (with per-instance health); polled periodically, node
    # re-registers on change so the servers see device health updates
    device_fingerprint: Optional[Callable[[], list]] = None
    device_poll_interval: float = 1.0
    # device plugin specs (client/devicemanager): each dict builds a
    # FakeDevicePlugin (vendor/type/name + count|instance_ids) that the
    # node fingerprints and the client reserves instances from
    device_plugins: List[dict] = field(default_factory=list)


class Client:
    def __init__(self, config: ClientConfig,
                 rpc: Callable[[str, dict], object]):
        self.config = config
        self.rpc = rpc
        self.registry = DriverRegistry(config.drivers)
        self.data_dir = config.data_dir or tempfile.mkdtemp(
            prefix="nomad-client-")
        self.alloc_dir_root = os.path.join(self.data_dir, "allocs")
        self.state_db = ClientStateDB(
            os.path.join(self.data_dir, "client_state.db"))
        from nomad_tpu.client.devices import (DeviceManager,
                                              FakeDevicePlugin)
        self.device_manager = DeviceManager(
            [FakeDevicePlugin(s) for s in config.device_plugins])
        self.node = self._build_node()
        self.alloc_runners: Dict[str, AllocRunner] = {}
        self._ar_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._heartbeat_ttl = 10.0
        self._pending_updates: Dict[str, object] = {}
        self._updates_lock = threading.Lock()
        self._last_alloc_index = 0

    # ------------------------------------------------------------ node

    def _build_node(self) -> Node:
        node = Node(
            id=str(uuid.uuid4()),
            name=self.config.node_name,
            datacenter=self.config.datacenter,
            node_class=self.config.node_class,
            status=NodeStatus.INIT,
        )
        node.meta = dict(self.config.meta)
        fingerprint_node(node, self.registry.fingerprints())
        node.node_resources.devices = self.device_manager.fingerprint()
        from nomad_tpu.structs.node import compute_node_class
        node.computed_class = compute_node_class(node)
        return node

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._restore()
        if self.config.device_fingerprint is not None:
            # seed the device set so the FIRST registration already
            # carries the fingerprint
            self._apply_device_fingerprint(register=False)
        resp = self.rpc("Node.Register", {"node": self.node})
        self._heartbeat_ttl = resp.get("heartbeat_ttl", 10.0)
        self.node.status = NodeStatus.READY
        self.rpc("Node.UpdateStatus",
                 {"node_id": self.node.id, "status": "ready"})
        loops = [(self._heartbeat_loop, "hb"),
                 (self._heartbeat_stop_loop, "hb-stop"),
                 (self._watch_allocations, "alloc-watch"),
                 (self._update_pusher, "alloc-update"),
                 (self._log_janitor_loop, "log-janitor")]
        if self.config.device_fingerprint is not None:
            loops.append((self._device_monitor_loop, "device-fp"))
        for target, name in loops:
            t = threading.Thread(target=target, daemon=True,
                                 name=f"client-{name}")
            t.start()
            self._threads.append(t)

    # -------------------------------------------------------- device health

    def _device_snapshot(self):
        return [(d.id, tuple(d.instance_ids), tuple(sorted(d.unhealthy_ids)))
                for d in self.node.node_resources.devices]

    def _apply_device_fingerprint(self, register: bool = True) -> bool:
        """Poll the device fingerprint stream; on change, update the node
        and (optionally) re-register so servers see the new health."""
        try:
            devices = self.config.device_fingerprint()
        except Exception:                       # noqa: BLE001
            return False
        before = self._device_snapshot()
        self.node.node_resources.devices = \
            self.device_manager.fingerprint() + list(devices)
        changed = self._device_snapshot() != before
        if changed and register:
            # delta path first: the fingerprint change rides the
            # leader's batched write path (one NodeFingerprintBatch
            # entry per flush tick across the whole fleet) instead of
            # a full Node.Register raft entry per change.  Fall back
            # to re-register if the server doesn't know us (or is too
            # old to know the RPC).
            try:
                resp = self.rpc("Node.UpdateFingerprint", {
                    "node_id": self.node.id,
                    "devices": list(self.node.node_resources.devices)})
                if resp.get("known", False):
                    return changed
            except Exception:                   # noqa: BLE001
                pass
            try:
                self.rpc("Node.Register", {"node": self.node})
            except Exception:                   # noqa: BLE001
                pass
        return changed

    def _log_janitor_loop(self) -> None:
        """Rotate oversized task log files written by direct-append
        drivers (logmon.rotate_copytruncate; the exec executor rotates
        its own in-process)."""
        from nomad_tpu.client.logmon import (DEFAULT_MAX_FILE_SIZE,
                                             DEFAULT_MAX_FILES,
                                             rotate_copytruncate)
        import os as _os
        while not self._stop.wait(10.0):
            with self._ar_lock:
                runners = list(self.alloc_runners.values())
            for ar in runners:
                tg = ar.task_group()
                for task in (tg.tasks if tg else []):
                    # only direct-append drivers: the exec executor owns
                    # its rotation in-process, and racing it would
                    # clobber fragments
                    if task.driver != "raw_exec":
                        continue
                    try:
                        lcfg = (task.config or {}).get("logs") or {}
                        max_size = int(lcfg.get("max_file_size_mb", 0)) \
                            * 1024 * 1024 or DEFAULT_MAX_FILE_SIZE
                        max_files = int(lcfg.get("max_files", 0)) \
                            or DEFAULT_MAX_FILES
                        logs_dir = ar.alloc_dir.logs_dir()
                        for kind in ("stdout", "stderr"):
                            rotate_copytruncate(
                                _os.path.join(logs_dir,
                                              f"{task.name}.{kind}"),
                                max_size, max_files)
                    except Exception:                # noqa: BLE001
                        continue    # one bad logs config must not kill
                                    # rotation for the whole node

    def _device_monitor_loop(self) -> None:
        while not self._stop.is_set():
            if self._stop.wait(self.config.device_poll_interval):
                return
            self._apply_device_fingerprint()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(2.0)
        with self._ar_lock:
            runners = list(self.alloc_runners.values())
        for ar in runners:
            ar.stop(0.5)
        self.state_db.close()

    # ------------------------------------------------------------ heartbeat

    def _heartbeat_loop(self) -> None:
        """registerAndHeartbeat (client.go:1602): beat at ~TTL/2 with
        jitter; re-register on unknown-node errors."""
        import random
        while not self._stop.is_set():
            wait = self._heartbeat_ttl * (0.45 + 0.1 * random.random())
            if self._stop.wait(wait):
                return
            try:
                resp = self.rpc("Node.UpdateStatus",
                                {"node_id": self.node.id,
                                 "heartbeat": True})
                self._heartbeat_ttl = resp.get("heartbeat_ttl",
                                               self._heartbeat_ttl)
                self._disconnected_since = None
            except Exception:                       # noqa: BLE001
                # server unreachable: keep trying; the server marks us
                # down/disconnected on TTL expiry (heartbeat.go:135)
                if getattr(self, "_disconnected_since", None) is None:
                    self._disconnected_since = time.time()
                log.debug("heartbeat failed", exc_info=True)
                try:
                    self.rpc("Node.Register", {"node": self.node})
                except Exception:                   # noqa: BLE001
                    pass

    def _heartbeat_stop_loop(self) -> None:
        """heartbeatstop (client/heartbeatstop.go:158): while the client
        cannot reach a server, allocations whose task group sets
        stop_after_client_disconnect are stopped locally once that
        duration elapses past the last successful heartbeat."""
        while not self._stop.is_set():
            if self._stop.wait(1.0):
                return
            since = getattr(self, "_disconnected_since", None)
            if since is None:
                continue
            behind = time.time() - since
            with self._ar_lock:
                runners = list(self.alloc_runners.values())
            for ar in runners:
                tg = ar.task_group()
                if tg is None or tg.stop_after_client_disconnect_s is None:
                    continue
                if behind <= tg.stop_after_client_disconnect_s:
                    continue
                if ar.client_status in ("complete", "failed", "lost"):
                    continue
                log.info("stopping alloc %s: client disconnected > %.0fs",
                         ar.alloc.id[:8], tg.stop_after_client_disconnect_s)
                ar.stop_for_disconnect()

    # ------------------------------------------------------------ allocs

    def _watch_allocations(self) -> None:
        """Blocking-query watch (client.go:2056 watchAllocations →
        Node.GetClientAllocs)."""
        while not self._stop.is_set():
            try:
                resp = self.rpc("Node.GetClientAllocs",
                                {"node_id": self.node.id,
                                 "min_index": self._last_alloc_index,
                                 "timeout": 2.0})
            except Exception:                       # noqa: BLE001
                if self._stop.wait(1.0):
                    return
                continue
            if resp is None:
                continue
            self._last_alloc_index = resp.get("index",
                                              self._last_alloc_index)
            self._run_allocs(resp.get("allocs") or [])
            self._stop.wait(self.config.watch_interval)

    def _run_allocs(self, allocs) -> None:
        """Diff assigned vs running (client.go:2286 runAllocs)."""
        assigned = {a.id: a for a in allocs}
        with self._ar_lock:
            existing = dict(self.alloc_runners)
        # removed (GC'd server-side): destroy
        for alloc_id, ar in existing.items():
            if alloc_id not in assigned:
                ar.destroy()
                with self._ar_lock:
                    self.alloc_runners.pop(alloc_id, None)
        for alloc_id, alloc in assigned.items():
            ar = existing.get(alloc_id)
            if ar is None:
                if alloc.server_terminal_status() or \
                        alloc.client_terminal_status():
                    continue
                self._start_alloc(alloc)
            else:
                self._update_alloc(ar, alloc)
        self._maybe_gc()

    def _start_alloc(self, alloc) -> None:
        alloc = alloc.copy() if hasattr(alloc, "copy") else alloc
        if alloc.job is None:
            try:
                alloc.job = self.rpc("Job.GetJob",
                                     {"namespace": alloc.namespace,
                                      "job_id": alloc.job_id})
            except Exception:                       # noqa: BLE001
                pass
        prev_dir = None
        if alloc.previous_allocation:
            with self._ar_lock:
                prev = self.alloc_runners.get(alloc.previous_allocation)
            if prev is not None:
                prev_dir = prev.alloc_dir
        ar = AllocRunner(alloc, self.registry, self.alloc_dir_root,
                         node=self.node, on_update=self._on_alloc_update,
                         state_db=self.state_db,
                         prev_alloc_dir=prev_dir, rpc=self.rpc,
                         device_manager=self.device_manager)
        with self._ar_lock:
            self.alloc_runners[alloc.id] = ar
        self.state_db.put_alloc(alloc.id, {
            "namespace": alloc.namespace, "job_id": alloc.job_id,
            "task_group": alloc.task_group, "name": alloc.name,
            "eval_id": alloc.eval_id,
            "deployment_id": alloc.deployment_id})
        ar.run()

    def _update_alloc(self, ar: AllocRunner, alloc) -> None:
        if alloc.desired_status in (AllocDesiredStatus.STOP,
                                    AllocDesiredStatus.EVICT) and \
                ar.client_status in (AllocClientStatus.PENDING,
                                     AllocClientStatus.RUNNING):
            ar.alloc.desired_status = alloc.desired_status
            ar.stop()
            return
        # in-place update: new job version and/or deployment membership
        # without a task restart (alloc_runner.go Update)
        new_version = (alloc.job is not None and ar.alloc.job is not None
                       and alloc.job.version != ar.alloc.job.version)
        if new_version or alloc.deployment_id != ar.alloc.deployment_id:
            # copy before the runner aliases/mutates it: with in-process
            # RPC the server hands us live store objects (_start_alloc
            # copies for the same reason)
            ar.update(alloc.copy() if hasattr(alloc, "copy") else alloc)
        ar.alloc.desired_transition = alloc.desired_transition

    def _maybe_gc(self) -> None:
        """Destroy oldest terminal allocrunners over the cap (gc.go)."""
        with self._ar_lock:
            terminal = [(aid, ar) for aid, ar in self.alloc_runners.items()
                        if ar.client_status in (AllocClientStatus.COMPLETE,
                                                AllocClientStatus.FAILED)]
            excess = len(self.alloc_runners) - self.config.max_allocs_gc
        if excess > 0:
            for aid, ar in terminal[:excess]:
                ar.destroy()
                with self._ar_lock:
                    self.alloc_runners.pop(aid, None)

    # ------------------------------------------------------------ updates

    def _on_alloc_update(self, ar: AllocRunner) -> None:
        """Queue a client-status push (allocSync batching,
        client.go allocSync / Node.UpdateAlloc)."""
        u = ar.alloc.copy()
        u.client_status = ar.client_status
        u.client_description = ar.client_description
        u.task_states = {n: s for n, s in ar.task_states().items()}
        u.job = None                        # strip for wire size
        if ar.deployment_healthy is not None:
            u.deployment_status = {"healthy": ar.deployment_healthy,
                                   "timestamp": time.time()}
        with self._updates_lock:
            self._pending_updates[u.id] = u

    def _update_pusher(self) -> None:
        while not self._stop.wait(0.2):
            self.push_updates()
        self.push_updates()

    def push_updates(self) -> None:
        with self._updates_lock:
            updates = list(self._pending_updates.values())
            self._pending_updates.clear()
        if not updates:
            return
        try:
            self.rpc("Node.UpdateAlloc", {"allocs": updates})
        except Exception:                           # noqa: BLE001
            with self._updates_lock:
                for u in updates:
                    self._pending_updates.setdefault(u.id, u)

    # ------------------------------------------------------------ restore

    def _restore(self) -> None:
        """Recover alloc runners persisted by a previous process
        (client.go restoreState; drivers RecoverTask)."""
        saved = self.state_db.get_allocs()
        for alloc_id, summary in saved.items():
            try:
                alloc = self.rpc("Alloc.GetAlloc", {"alloc_id": alloc_id})
            except Exception:                       # noqa: BLE001
                alloc = None
            if alloc is None or alloc.terminal_status():
                self.state_db.delete_alloc(alloc_id)
                continue
            if alloc.job is None:
                alloc.job = self.rpc("Job.GetJob",
                                     {"namespace": alloc.namespace,
                                      "job_id": alloc.job_id})
            ar = AllocRunner(alloc, self.registry, self.alloc_dir_root,
                             node=self.node,
                             on_update=self._on_alloc_update,
                             state_db=self.state_db, rpc=self.rpc,
                             device_manager=self.device_manager)
            with self._ar_lock:
                self.alloc_runners[alloc.id] = ar
            ar.restore()

    # ------------------------------------------------------------ stats

    def num_allocs(self) -> int:
        with self._ar_lock:
            return len(self.alloc_runners)
