"""Client-side CSI volume manager (reference
client/pluginmanager/csimanager/ + plugins/csi/).

A `CSIPluginClient` speaks the CSI node-service verbs the reference
drives over gRPC (NodeStageVolume / NodePublishVolume and their inverse);
`FakeCSIPlugin` is the in-process implementation used by tests and the
dev client (reference plugins/csi/fake), materializing a bind-mount as a
directory under the alloc dir.  The `CSIHook` runs in the alloc runner's
prerun/postrun phases (reference alloc_runner_hooks.go csi_hook.go):
stage + publish every CSI volume of the task group before tasks start,
unpublish + unstage after they stop.
"""
from __future__ import annotations

import os
import shutil
from typing import Dict, List, Optional


class CSIPluginClient:
    """Node-service surface of a CSI plugin (plugins/csi/client.go)."""

    def node_stage_volume(self, volume_id: str, staging_path: str,
                          attachment_mode: str, access_mode: str) -> None:
        raise NotImplementedError

    def node_unstage_volume(self, volume_id: str, staging_path: str) -> None:
        raise NotImplementedError

    def node_publish_volume(self, volume_id: str, staging_path: str,
                            target_path: str, read_only: bool) -> None:
        raise NotImplementedError

    def node_unpublish_volume(self, volume_id: str, target_path: str) -> None:
        raise NotImplementedError


class FakeCSIPlugin(CSIPluginClient):
    """In-process plugin: staging/publish become real directories (the
    reference's fake client records calls; making directories additionally
    gives tasks a live mount path to write into)."""

    def __init__(self):
        self.calls: List[tuple] = []

    def node_stage_volume(self, volume_id, staging_path, attachment_mode,
                          access_mode) -> None:
        os.makedirs(staging_path, exist_ok=True)
        self.calls.append(("stage", volume_id, staging_path))

    def node_unstage_volume(self, volume_id, staging_path) -> None:
        shutil.rmtree(staging_path, ignore_errors=True)
        self.calls.append(("unstage", volume_id, staging_path))

    def node_publish_volume(self, volume_id, staging_path, target_path,
                            read_only) -> None:
        os.makedirs(target_path, exist_ok=True)
        marker = os.path.join(target_path, ".csi_published")
        with open(marker, "w") as f:
            f.write(f"{volume_id} ro={read_only}\n")
        self.calls.append(("publish", volume_id, target_path))

    def node_unpublish_volume(self, volume_id, target_path) -> None:
        shutil.rmtree(target_path, ignore_errors=True)
        self.calls.append(("unpublish", volume_id, target_path))


class CSIHook:
    """Per-alloc stage/publish lifecycle (client/allocrunner/csi_hook.go)."""

    def __init__(self, alloc, alloc_dir_path: str,
                 plugins: Optional[Dict[str, CSIPluginClient]] = None):
        self.alloc = alloc
        self.base = alloc_dir_path
        self.plugins = plugins if plugins is not None else {}
        self.mounts: Dict[str, str] = {}    # volume alias -> publish path

    def _requests(self):
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        if tg is None:
            return {}
        return {alias: req for alias, req in tg.volumes.items()
                if req.type == "csi"}

    def prerun(self) -> Dict[str, str]:
        """Stage+publish all CSI volumes; returns alias -> mount path."""
        for alias, req in self._requests().items():
            plugin = self.plugins.get("*") or \
                self.plugins.get(req.source)
            if plugin is None:
                plugin = self.plugins.setdefault("*", FakeCSIPlugin())
            staging = os.path.join(self.base, "csi", "staging", req.source)
            target = os.path.join(self.base, "csi", "per-alloc",
                                  self.alloc.id, alias)
            plugin.node_stage_volume(req.source, staging,
                                     req.attachment_mode, req.access_mode)
            plugin.node_publish_volume(req.source, staging, target,
                                       req.read_only)
            self.mounts[alias] = target
        return dict(self.mounts)

    def postrun(self) -> None:
        """Unpublish + unstage (csi_hook.go Postrun)."""
        for alias, req in self._requests().items():
            plugin = self.plugins.get("*") or self.plugins.get(req.source)
            if plugin is None:
                continue
            target = self.mounts.get(alias)
            if target:
                plugin.node_unpublish_volume(req.source, target)
            staging = os.path.join(self.base, "csi", "staging", req.source)
            plugin.node_unstage_volume(req.source, staging)
        self.mounts.clear()
