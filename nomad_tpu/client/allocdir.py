"""Allocation directory tree (reference: client/allocdir/ — the sandbox
layout every task sees: a shared alloc/ dir and per-task local/secrets
dirs)."""
from __future__ import annotations

import os
import shutil


class AllocDir:
    """<root>/<alloc_id>/
         alloc/          shared between tasks
           data/ logs/ tmp/
         <task>/
           local/ secrets/ tmp/
    (reference client/allocdir/alloc_dir.go)."""

    def __init__(self, root: str, alloc_id: str):
        self.root = root
        self.alloc_id = alloc_id
        self.dir = os.path.join(root, alloc_id)
        self.shared_dir = os.path.join(self.dir, "alloc")

    def build(self) -> None:
        for sub in ("data", "logs", "tmp"):
            os.makedirs(os.path.join(self.shared_dir, sub), exist_ok=True)

    def build_task_dir(self, task_name: str) -> str:
        task_dir = os.path.join(self.dir, task_name)
        for sub in ("local", "secrets", "tmp"):
            os.makedirs(os.path.join(task_dir, sub), exist_ok=True)
        return task_dir

    def task_dir(self, task_name: str) -> str:
        return os.path.join(self.dir, task_name)

    def logs_dir(self) -> str:
        return os.path.join(self.shared_dir, "logs")

    def destroy(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)

    def move_from(self, other: "AllocDir") -> None:
        """Ephemeral-disk migration from a previous alloc's shared data
        dir (reference client/allocwatcher migration)."""
        src = os.path.join(other.shared_dir, "data")
        dst = os.path.join(self.shared_dir, "data")
        if os.path.isdir(src):
            shutil.rmtree(dst, ignore_errors=True)
            shutil.copytree(src, dst)
