"""Client state database (reference: client/state/state_database.go —
BoltDB persistence of alloc/task-runner state and driver task handles so
a restarted client can recover running tasks via RecoverTask).

sqlite3 (stdlib, a real embedded native DB) replaces BoltDB.  Schema
versioned for upgrade handling (client/state/upgrade.go).

Corruption recovery: a client whose state DB is damaged (torn page,
truncated file) must still boot — the servers hold desired state, and
running tasks re-register or restart.  On `sqlite3.DatabaseError` at
open, the damaged files move aside to ``<path>.corrupt`` (plus the WAL/
SHM sidecars) and a fresh DB is created; `close()` checkpoints the
sqlite WAL back into the main file so a clean shutdown leaves one
self-contained db file behind.
"""
from __future__ import annotations

import json
import logging
import os
import sqlite3
import threading
from dataclasses import asdict
from typing import Dict, List, Optional, Tuple

from nomad_tpu.client.drivers import TaskHandle

log = logging.getLogger(__name__)

SCHEMA_VERSION = 1


class ClientStateDB:
    """Thread-safe persistent store for alloc + task runner state."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._closed = False
        self.path = path
        try:
            self._db = self._open(path)
        except sqlite3.DatabaseError:
            # corrupt DB: losing local runner state is recoverable (the
            # control plane re-sends desired allocs); crashing the client
            # on boot is not.  Keep the evidence for forensics.
            log.warning("client state db %s is corrupt; moving it aside "
                        "to %s.corrupt and starting fresh", path, path)
            self._move_aside(path)
            self._db = self._open(path)

    @staticmethod
    def _open(path: str) -> sqlite3.Connection:
        db = sqlite3.connect(path, check_same_thread=False)
        try:
            db.execute("PRAGMA journal_mode=WAL")
            ClientStateDB._init_schema(db)
        except sqlite3.DatabaseError:
            db.close()
            raise
        return db

    @staticmethod
    def _move_aside(path: str) -> None:
        for suffix in ("", "-wal", "-shm"):
            src = path + suffix
            if os.path.exists(src):
                os.replace(src, path + ".corrupt" + suffix)

    @staticmethod
    def _init_schema(db: sqlite3.Connection) -> None:
        with db:
            db.execute("""CREATE TABLE IF NOT EXISTS meta
                (key TEXT PRIMARY KEY, value TEXT)""")
            db.execute("""CREATE TABLE IF NOT EXISTS allocs
                (alloc_id TEXT PRIMARY KEY, blob TEXT NOT NULL)""")
            db.execute("""CREATE TABLE IF NOT EXISTS task_state
                (alloc_id TEXT, task TEXT, state TEXT, failed INTEGER,
                 restarts INTEGER, handle TEXT,
                 PRIMARY KEY (alloc_id, task))""")
            cur = db.execute(
                "SELECT value FROM meta WHERE key='schema_version'")
            row = cur.fetchone()
            if row is None:
                db.execute(
                    "INSERT INTO meta VALUES ('schema_version', ?)",
                    (str(SCHEMA_VERSION),))
            elif int(row[0]) > SCHEMA_VERSION:
                raise RuntimeError(
                    f"client state schema {row[0]} is newer than "
                    f"supported {SCHEMA_VERSION}")

    # ------------------------------------------------------------ allocs

    def put_alloc(self, alloc_id: str, summary: dict) -> None:
        with self._lock:
            if self._closed:
                return
            with self._db:
                self._db.execute(
                    "INSERT OR REPLACE INTO allocs VALUES (?, ?)",
                    (alloc_id, json.dumps(summary)))

    def get_allocs(self) -> Dict[str, dict]:
        with self._lock:
            if self._closed:
                return {}
            cur = self._db.execute("SELECT alloc_id, blob FROM allocs")
            return {aid: json.loads(blob) for aid, blob in cur.fetchall()}

    def delete_alloc(self, alloc_id: str) -> None:
        with self._lock:
            if self._closed:
                return
            with self._db:
                self._db.execute("DELETE FROM allocs WHERE alloc_id=?",
                                 (alloc_id,))
                self._db.execute("DELETE FROM task_state WHERE alloc_id=?",
                                 (alloc_id,))

    # ------------------------------------------------------------ tasks

    def put_task_state(self, alloc_id: str, task: str, state: str,
                       failed: bool, restarts: int,
                       handle: Optional[TaskHandle]) -> None:
        with self._lock:
            # writer threads (task runners, heartbeats) may race close()
            # during client shutdown; a write after close is a no-op, not
            # an unhandled thread exception
            if self._closed:
                return
            with self._db:
                self._db.execute(
                    "INSERT OR REPLACE INTO task_state VALUES (?,?,?,?,?,?)",
                    (alloc_id, task, state, int(failed), restarts,
                     json.dumps(asdict(handle)) if handle else None))

    def get_task_states(self, alloc_id: str) \
            -> Dict[str, Tuple[str, bool, int, Optional[TaskHandle]]]:
        with self._lock:
            if self._closed:
                return {}
            cur = self._db.execute(
                "SELECT task, state, failed, restarts, handle "
                "FROM task_state WHERE alloc_id=?", (alloc_id,))
            out = {}
            for task, state, failed, restarts, handle in cur.fetchall():
                th = None
                if handle:
                    th = TaskHandle(**json.loads(handle))
                out[task] = (state, bool(failed), restarts, th)
            return out

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                try:
                    # fold the sqlite WAL back into the main file so a
                    # clean shutdown leaves one self-contained db behind
                    self._db.execute("PRAGMA wal_checkpoint(TRUNCATE)")
                except sqlite3.DatabaseError:
                    pass
                self._db.close()
