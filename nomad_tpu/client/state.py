"""Client state database (reference: client/state/state_database.go —
BoltDB persistence of alloc/task-runner state and driver task handles so
a restarted client can recover running tasks via RecoverTask).

sqlite3 (stdlib, a real embedded native DB) replaces BoltDB.  Schema
versioned for upgrade handling (client/state/upgrade.go).
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
from dataclasses import asdict
from typing import Dict, List, Optional, Tuple

from nomad_tpu.client.drivers import TaskHandle

SCHEMA_VERSION = 1


class ClientStateDB:
    """Thread-safe persistent store for alloc + task runner state."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._closed = False
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._init_schema()

    def _init_schema(self) -> None:
        with self._lock, self._db:
            self._db.execute("""CREATE TABLE IF NOT EXISTS meta
                (key TEXT PRIMARY KEY, value TEXT)""")
            self._db.execute("""CREATE TABLE IF NOT EXISTS allocs
                (alloc_id TEXT PRIMARY KEY, blob TEXT NOT NULL)""")
            self._db.execute("""CREATE TABLE IF NOT EXISTS task_state
                (alloc_id TEXT, task TEXT, state TEXT, failed INTEGER,
                 restarts INTEGER, handle TEXT,
                 PRIMARY KEY (alloc_id, task))""")
            cur = self._db.execute(
                "SELECT value FROM meta WHERE key='schema_version'")
            row = cur.fetchone()
            if row is None:
                self._db.execute(
                    "INSERT INTO meta VALUES ('schema_version', ?)",
                    (str(SCHEMA_VERSION),))
            elif int(row[0]) > SCHEMA_VERSION:
                raise RuntimeError(
                    f"client state schema {row[0]} is newer than "
                    f"supported {SCHEMA_VERSION}")

    # ------------------------------------------------------------ allocs

    def put_alloc(self, alloc_id: str, summary: dict) -> None:
        with self._lock:
            if self._closed:
                return
            with self._db:
                self._db.execute(
                    "INSERT OR REPLACE INTO allocs VALUES (?, ?)",
                    (alloc_id, json.dumps(summary)))

    def get_allocs(self) -> Dict[str, dict]:
        with self._lock:
            if self._closed:
                return {}
            cur = self._db.execute("SELECT alloc_id, blob FROM allocs")
            return {aid: json.loads(blob) for aid, blob in cur.fetchall()}

    def delete_alloc(self, alloc_id: str) -> None:
        with self._lock:
            if self._closed:
                return
            with self._db:
                self._db.execute("DELETE FROM allocs WHERE alloc_id=?",
                                 (alloc_id,))
                self._db.execute("DELETE FROM task_state WHERE alloc_id=?",
                                 (alloc_id,))

    # ------------------------------------------------------------ tasks

    def put_task_state(self, alloc_id: str, task: str, state: str,
                       failed: bool, restarts: int,
                       handle: Optional[TaskHandle]) -> None:
        with self._lock:
            # writer threads (task runners, heartbeats) may race close()
            # during client shutdown; a write after close is a no-op, not
            # an unhandled thread exception
            if self._closed:
                return
            with self._db:
                self._db.execute(
                    "INSERT OR REPLACE INTO task_state VALUES (?,?,?,?,?,?)",
                    (alloc_id, task, state, int(failed), restarts,
                     json.dumps(asdict(handle)) if handle else None))

    def get_task_states(self, alloc_id: str) \
            -> Dict[str, Tuple[str, bool, int, Optional[TaskHandle]]]:
        with self._lock:
            if self._closed:
                return {}
            cur = self._db.execute(
                "SELECT task, state, failed, restarts, handle "
                "FROM task_state WHERE alloc_id=?", (alloc_id,))
            out = {}
            for task, state, failed, restarts, handle in cur.fetchall():
                th = None
                if handle:
                    th = TaskHandle(**json.loads(handle))
                out[task] = (state, bool(failed), restarts, th)
            return out

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._db.close()
