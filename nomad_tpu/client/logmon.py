"""Log rotation + fs read helpers (reference: client/logmon/logmon.go —
the per-task process that pumps stdout/stderr through rotating files
under alloc/logs; client/lib/fifo is the transport there, an os.pipe
here; and client/fs_endpoint.go's file read/stream primitives).

Writers, two disciplines by process model:
- the exec driver's detached executor pumps the child's pipe through a
  RotatingFile in-process (the executor survives client restarts, so
  the pump does too);
- raw_exec children append straight to the log file, and the client's
  log janitor rotates oversized files out-of-band via
  rotate_copytruncate (an in-client pipe pump would die with the
  client and SIGPIPE recovered tasks).
The active file keeps the flat reference name (`<task>.stdout`) so
existing paths stay valid; rotations move it to `<task>.stdout.1`,
`.2`, ... (oldest pruned past max_files, with a `.pruned` byte ledger
keeping logical offsets absolute).

Readers: `log_files()` lists a task's log fragments oldest-first;
`read_log()` returns bytes at a logical offset spanning fragments —
the fs endpoint's cat/logs/follow primitives build on it."""
from __future__ import annotations

import os
import re
import threading
from typing import List, Optional, Tuple

DEFAULT_MAX_FILE_SIZE = 10 * 1024 * 1024    # logmon's 10MB default
DEFAULT_MAX_FILES = 10


class RotatingFile:
    """Append-only writer with size-based rotation."""

    def __init__(self, path: str,
                 max_size: int = DEFAULT_MAX_FILE_SIZE,
                 max_files: int = DEFAULT_MAX_FILES):
        self.path = path
        self.max_size = max(1, max_size)
        self.max_files = max(1, max_files)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._fh = open(path, "ab")
        self._size = self._fh.tell()
        self._lock = threading.Lock()

    def write(self, data: bytes) -> None:
        with self._lock:
            self._fh.write(data)
            # flush per chunk: tail -f readers must see lines as the
            # task emits them, not at rotation boundaries
            self._fh.flush()
            self._size += len(data)
            if self._size >= self.max_size:
                self._rotate()

    def _rotate(self) -> None:
        self._fh.close()
        nums = _fragment_indexes(self.path)
        nxt = (nums[-1] + 1) if nums else 1
        os.replace(self.path, f"{self.path}.{nxt}")
        nums.append(nxt)
        # prune oldest beyond max_files (the active file counts as one),
        # recording the dropped byte count so logical offsets stay
        # absolute — without the ledger a follower's offset silently
        # skips data whenever a fragment is pruned
        pruned = _pruned_bytes(self.path)
        while len(nums) + 1 > self.max_files:
            old = nums.pop(0)
            frag = f"{self.path}.{old}"
            try:
                pruned += os.path.getsize(frag)
                os.unlink(frag)
            except OSError:
                pass
        _write_pruned(self.path, pruned)
        self._fh = open(self.path, "ab")
        self._size = 0

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except Exception:                        # noqa: BLE001
                pass


def rotate_copytruncate(path: str,
                        max_size: int = DEFAULT_MAX_FILE_SIZE,
                        max_files: int = DEFAULT_MAX_FILES) -> bool:
    """Out-of-band rotation for files whose writer holds an O_APPEND fd
    (raw_exec children write the log directly — a restart-safe, zero-
    process design; an in-process pipe pump would die with the client
    and SIGPIPE recovered tasks).  logrotate's copytruncate discipline:
    copy the live file to the next fragment, truncate it in place (the
    writer's next append lands at the new EOF).  Bytes written between
    the copy and the truncate can be lost — the standard copytruncate
    trade-off.  Returns True when a rotation happened."""
    try:
        if os.path.getsize(path) < max_size:
            return False
    except OSError:
        return False
    nums = _fragment_indexes(path)
    nxt = (nums[-1] + 1) if nums else 1
    import shutil
    try:
        shutil.copyfile(path, f"{path}.{nxt}")
        with open(path, "ab") as fh:
            fh.truncate(0)
    except OSError:
        return False
    nums.append(nxt)
    pruned = _pruned_bytes(path)
    while len(nums) + 1 > max_files:
        old = nums.pop(0)
        frag = f"{path}.{old}"
        try:
            pruned += os.path.getsize(frag)
            os.unlink(frag)
        except OSError:
            pass
    _write_pruned(path, pruned)
    return True


def open_log_pipe(path: str,
                  max_size: int = DEFAULT_MAX_FILE_SIZE,
                  max_files: int = DEFAULT_MAX_FILES) -> int:
    """Create the write end of a logmon pipeline: returns an fd the
    child process writes to; a daemon pump thread drains it into a
    RotatingFile at `path`.  The pump exits when the child closes its
    end (process exit).  Only for callers that outlive the task (the
    detached executor); client-side callers use rotate_copytruncate."""
    r, w = os.pipe()
    rf = RotatingFile(path, max_size, max_files)

    def pump():
        try:
            while True:
                chunk = os.read(r, 65536)
                if not chunk:
                    return
                rf.write(chunk)
        except OSError:
            pass
        finally:
            os.close(r)
            rf.close()

    threading.Thread(target=pump, daemon=True,
                     name=f"logmon-{os.path.basename(path)}").start()
    return w


# ---------------------------------------------------------------- readers


def _pruned_bytes(path: str) -> int:
    try:
        with open(path + ".pruned") as fh:
            return int(fh.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def _write_pruned(path: str, n: int) -> None:
    try:
        with open(path + ".pruned", "w") as fh:
            fh.write(str(n))
    except OSError:
        pass


def _fragment_indexes(path: str) -> List[int]:
    d = os.path.dirname(path)
    base = os.path.basename(path)
    pat = re.compile(re.escape(base) + r"\.(\d+)$")
    out = []
    try:
        for name in os.listdir(d):
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
    except OSError:
        pass
    return sorted(out)


def log_files(logs_dir: str, task: str, kind: str) -> List[str]:
    """A task's stdout/stderr fragments, oldest first, active last."""
    base = os.path.join(logs_dir, f"{task}.{kind}")
    paths = [f"{base}.{n}" for n in _fragment_indexes(base)]
    if os.path.exists(base):
        paths.append(base)
    return paths


def log_size(logs_dir: str, task: str, kind: str) -> int:
    """Logical size since log start — INCLUDES pruned bytes, so offsets
    stay absolute across rotation pruning."""
    base = os.path.join(logs_dir, f"{task}.{kind}")
    return _pruned_bytes(base) + sum(
        os.path.getsize(p) for p in log_files(logs_dir, task, kind))


def read_log(logs_dir: str, task: str, kind: str, offset: int = 0,
             limit: Optional[int] = None) -> Tuple[bytes, int]:
    """Read from the logical concatenation of a task's log fragments.
    -> (data, next_offset).  Negative offset = from the end (tail).
    Offsets are absolute since log start; offsets pointing into pruned
    history resume at the oldest surviving byte."""
    total = log_size(logs_dir, task, kind)
    if offset < 0:
        offset = max(0, total + offset)
    out = bytearray()
    pos = _pruned_bytes(os.path.join(logs_dir, f"{task}.{kind}"))
    offset = max(offset, pos)
    want = limit if limit is not None else total
    for p in log_files(logs_dir, task, kind):
        try:
            size = os.path.getsize(p)
        except OSError:
            continue
        if pos + size <= offset:
            pos += size
            continue
        start = max(0, offset - pos)
        with open(p, "rb") as fh:
            fh.seek(start)
            chunk = fh.read(want - len(out))
        out.extend(chunk)
        pos += size
        if len(out) >= want:
            break
    return bytes(out), offset + len(out)
