"""AllocRunner (reference: client/allocrunner/alloc_runner.go — the
per-allocation state machine: hook pipeline (alloc_runner_hooks.go:111),
lifecycle-ordered task runners (task_hook_coordinator.go), alloc health
watching (allochealth/), and client-status aggregation).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.taskrunner import TaskRunner
from nomad_tpu.structs.alloc import AllocClientStatus


class AllocRunner:
    def __init__(self, alloc, driver_registry, root_dir: str,
                 node=None, on_update: Optional[Callable] = None,
                 state_db=None, prev_alloc_dir: Optional[AllocDir] = None,
                 csi_plugins=None, rpc=None, device_manager=None):
        self.alloc = alloc
        self.registry = driver_registry
        self.node = node
        self.on_update = on_update or (lambda ar: None)
        self.state_db = state_db
        self.alloc_dir = AllocDir(root_dir, alloc.id)
        self.prev_alloc_dir = prev_alloc_dir
        self.task_runners: Dict[str, TaskRunner] = {}
        self.client_status = AllocClientStatus.PENDING
        self.client_description = ""
        self._lock = threading.Lock()
        self._destroyed = False
        self._thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        self._health_gen = 0
        self.deployment_healthy: Optional[bool] = None
        from nomad_tpu.client.csi import CSIHook
        self.csi_hook = CSIHook(alloc, self.alloc_dir.dir,
                                plugins=csi_plugins)
        from nomad_tpu.client.services import ServiceHook
        self.service_hook = ServiceHook(alloc, node, rpc)
        self.rpc = rpc
        self.device_manager = device_manager

    def task_group(self):
        job = self.alloc.job
        return job.lookup_task_group(self.alloc.task_group) if job else None

    def _reserve_devices(self):
        """-> {task_name: env} or None after failing the alloc."""
        out: Dict[str, Dict[str, str]] = {}
        if self.device_manager is None:
            return out
        tasks = getattr(self.alloc.allocated_resources, "tasks", None) or {}
        try:
            for tname, tres in tasks.items():
                if tres.devices:
                    out[tname] = self.device_manager.reserve(
                        self.alloc.id, tres.devices)
        except Exception as e:                       # noqa: BLE001
            self.device_manager.free(self.alloc.id)
            self._set_status(AllocClientStatus.FAILED,
                             f"device reservation failed: {e}")
            return None
        return out

    # ------------------------------------------------------------ lifecycle

    def run(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"alloc-{self.alloc.id[:8]}")
        self._thread.start()

    def _run(self) -> None:
        csi_staged = False
        try:
            # --- alloc prerun hooks (alloc_runner_hooks.go:111):
            # allocdir -> previous-alloc disk migration -> (network,
            # services: no-op in the sim) -> health watcher
            self.alloc_dir.build()
            # CSI volumes stage+publish before any task starts
            # (alloc_runner_hooks.go csi_hook Prerun)
            csi_staged = True   # before prerun: a mid-prerun failure must
            csi_mounts = self.csi_hook.prerun()   # still unwind in finally
            tg = self.task_group()
            if self.prev_alloc_dir is not None and tg is not None \
                    and tg.ephemeral_disk.migrate:
                self.alloc_dir.move_from(self.prev_alloc_dir)
            if tg is None or not tg.tasks:
                self._set_status(AllocClientStatus.FAILED,
                                 "no task group in alloc job")
                return

            # device reservation before any task starts (devicemanager
            # Reserve; the scheduler picked the instance ids, the client
            # enforces exclusivity and hands the env to the task)
            dev_env = self._reserve_devices()
            if dev_env is None:
                return                               # reservation failed

            ports = self._port_map()
            for task in tg.tasks:
                tr = TaskRunner(
                    self.alloc, task, self.registry.get(task.driver),
                    self.alloc_dir, node=self.node,
                    on_state=self._on_task_state, state_db=self.state_db,
                    ports=ports, volumes=csi_mounts, rpc=self.rpc,
                    extra_env=dev_env.get(task.name))
                self.task_runners[task.name] = tr

            self._start_health_watcher()

            # lifecycle ordering (task_hook_coordinator.go): prestart
            # (non-sidecar) tasks run to completion first, then main +
            # sidecars start; poststart after mains are running; poststop
            # runs after mains exit.
            prestarts = [t for t in tg.tasks if t.lifecycle is not None
                         and t.lifecycle.hook == "prestart"
                         and not t.lifecycle.sidecar]
            prestart_side = [t for t in tg.tasks if t.lifecycle is not None
                             and t.lifecycle.hook == "prestart"
                             and t.lifecycle.sidecar]
            mains = [t for t in tg.tasks if t.lifecycle is None]
            poststarts = [t for t in tg.tasks if t.lifecycle is not None
                          and t.lifecycle.hook == "poststart"]
            poststops = [t for t in tg.tasks if t.lifecycle is not None
                         and t.lifecycle.hook == "poststop"]

            for t in prestart_side:
                self.task_runners[t.name].start()
            for t in prestarts:
                tr = self.task_runners[t.name]
                tr.start()
                tr.join(timeout=600.0)
                if tr.state.failed:
                    self._fail_remaining("prestart task failed")
                    return
            for t in mains:
                self.task_runners[t.name].start()
            # group/task service registration begins once tasks launch
            # (groupservice_hook Prerun -> nsd register)
            self.service_hook.start(self.task_states)
            if poststarts:
                self._wait_any_running([self.task_runners[t.name]
                                        for t in mains])
                for t in poststarts:
                    self.task_runners[t.name].start()

            # wait for main tasks (and poststarts) to finish — service
            # tasks run indefinitely; block until they actually exit or
            # the runner is stopped (no arbitrary deadline)
            for t in mains + poststarts:
                tr = self.task_runners[t.name]
                while tr._thread is not None and tr._thread.is_alive() \
                        and not self._destroyed:
                    tr._thread.join(1.0)
            # kill sidecars once mains are done (leader semantics:
            # any task marked leader dying kills the rest)
            for t in prestart_side:
                self.task_runners[t.name].kill()
            for t in prestart_side:
                self.task_runners[t.name].join(5.0)
            # deregister this alloc's services before poststop tasks run
            # (nsd removes on alloc stop; queries must not see instances
            # of an alloc that is winding down)
            self.service_hook.stop()
            for t in poststops:
                tr = self.task_runners[t.name]
                tr.start()
                tr.join(600.0)
            self._finalize_status()
        except Exception as e:                       # noqa: BLE001
            self._set_status(AllocClientStatus.FAILED, str(e))
        finally:
            # unpublish/unstage regardless of how the alloc ended, so
            # failed allocs don't leak staged CSI mounts
            if csi_staged:
                try:
                    self.csi_hook.postrun()
                except Exception:                    # noqa: BLE001
                    pass

    def _wait_any_running(self, runners: List[TaskRunner],
                          timeout: float = 300.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if any(tr.state.state == "running" for tr in runners):
                return
            if all(tr.state.state == "dead" for tr in runners):
                return
            time.sleep(0.05)

    def _port_map(self) -> Dict[str, int]:
        ports = {}
        for net in self.alloc.allocated_resources.shared_networks:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                if p.label:
                    ports[p.label] = p.value
        return ports

    # ------------------------------------------------------------ status

    def _on_task_state(self, tr: TaskRunner) -> None:
        with self._lock:
            self._aggregate_status()
        self.on_update(self)

    def _aggregate_status(self) -> None:
        """Client status from task states (alloc_runner.go
        getClientStatus)."""
        if getattr(self, "_disconnect_stopped", False):
            self.client_status = AllocClientStatus.LOST
            self.client_description = "stopped after client disconnect"
            return
        states = [tr.state for tr in self.task_runners.values()]
        if not states:
            return
        if any(s.failed for s in states):
            self.client_status = AllocClientStatus.FAILED
            self.client_description = "Failed tasks"
        elif all(s.state == "dead" for s in states):
            self.client_status = AllocClientStatus.COMPLETE
            self.client_description = "All tasks have completed"
        elif any(s.state == "running" for s in states):
            self.client_status = AllocClientStatus.RUNNING
            self.client_description = "Tasks are running"
        else:
            self.client_status = AllocClientStatus.PENDING

    def _free_devices(self) -> None:
        if self.device_manager is not None:
            self.device_manager.free(self.alloc.id)

    def _finalize_status(self) -> None:
        with self._lock:
            self._aggregate_status()
            # mains exited and sidecars were killed+joined; only coerce a
            # still-draining sidecar's "running" to complete when every
            # non-sidecar task is actually dead
            if self.client_status == AllocClientStatus.RUNNING:
                tg = self.task_group()
                mains_dead = all(
                    tr.state.state == "dead"
                    for t in (tg.tasks if tg else [])
                    if t.lifecycle is None
                    for tr in [self.task_runners.get(t.name)] if tr)
                if mains_dead:
                    self.client_status = AllocClientStatus.COMPLETE
        if self.client_status in (AllocClientStatus.COMPLETE,
                                  AllocClientStatus.FAILED,
                                  AllocClientStatus.LOST):
            self._free_devices()
        self.on_update(self)

    def _fail_remaining(self, desc: str) -> None:
        for tr in self.task_runners.values():
            tr.kill()
        self._set_status(AllocClientStatus.FAILED, desc)

    def _set_status(self, status: str, desc: str = "") -> None:
        with self._lock:
            self.client_status = status
            self.client_description = desc
        if status in (AllocClientStatus.COMPLETE, AllocClientStatus.FAILED,
                      AllocClientStatus.LOST):
            # every terminal path releases device instances, or the
            # replacement alloc gets assigned still-held ids
            self._free_devices()
        self.on_update(self)

    def task_states(self):
        return {name: tr.state for name, tr in self.task_runners.items()}

    # ------------------------------------------------------------ health

    def _start_health_watcher(self) -> None:
        """Deployment health: healthy once all tasks are running for
        min_healthy_time (reference client/allocrunner/allochealth/
        tracker.go; feeds the deployment watcher)."""
        if not self.alloc.deployment_id:
            return
        tg = self.task_group()
        update = tg.update if tg else None
        min_healthy = update.min_healthy_time_s if update else 10.0
        deadline = update.healthy_deadline_s if update else 300.0
        # health_check = "checks": tasks running is not enough — every
        # nomad service registration of the alloc must be passing too
        # (reference allochealth/tracker.go watchConsulEvents analog)
        use_checks = bool(update and update.health_check == "checks")
        with self._lock:
            self._health_gen += 1
            gen = self._health_gen

        def watch():
            start = time.time()
            healthy_since = None
            while not self._destroyed and gen == self._health_gen:
                now = time.time()
                states = [tr.state for tr in self.task_runners.values()]
                if any(s.failed for s in states):
                    self._set_health(False, gen)
                    return
                mains_running = states and all(
                    s.state == "running" or (s.state == "dead"
                                             and not s.failed)
                    for s in states) and any(
                    s.state == "running" for s in states)
                if mains_running and use_checks:
                    mains_running = self.service_hook.all_passing()
                if mains_running:
                    if healthy_since is None:
                        healthy_since = now
                    elif now - healthy_since >= min_healthy:
                        self._set_health(True, gen)
                        return
                else:
                    healthy_since = None
                if now - start > deadline:
                    self._set_health(False, gen)
                    return
                time.sleep(0.05)

        self._health_thread = threading.Thread(target=watch, daemon=True)
        self._health_thread.start()

    def _set_health(self, healthy: bool, gen: Optional[int] = None) -> None:
        with self._lock:
            # a watcher superseded by update() must not attribute its
            # verdict to the NEW deployment
            if gen is not None and gen != self._health_gen:
                return
            self.deployment_healthy = healthy
        self.on_update(self)

    def update(self, alloc) -> None:
        """In-place update (alloc_runner.go Update): the server shipped a
        new job version / deployment for a running alloc without
        restarting its tasks.  Swap the alloc (service hook and taskenv
        read it live) and, when the deployment changed, reset health and
        re-arm the watcher so the new deployment's health is proven
        fresh."""
        old_dep = self.alloc.deployment_id
        if alloc.job is None:
            alloc.job = self.alloc.job
        self.alloc = alloc
        self.service_hook.alloc = alloc
        if alloc.deployment_id and alloc.deployment_id != old_dep:
            self.deployment_healthy = None
            self._start_health_watcher()
            self.on_update(self)

    # ------------------------------------------------------------ teardown

    def stop(self, timeout_s: Optional[float] = None) -> None:
        """Kill all tasks (desired_status=stop path)."""
        self.service_hook.stop()
        for tr in self.task_runners.values():
            tr.kill(timeout_s)

    def stop_for_disconnect(self) -> None:
        """stop_after_client_disconnect elapsed while the client could
        not heartbeat (heartbeatstop.go): kill the tasks and mark the
        alloc lost so the server's view converges on reconnect.  The flag
        is sticky: task-death aggregation must not flip the alloc back to
        complete."""
        self._disconnect_stopped = True
        self.stop(1.0)
        self._set_status(AllocClientStatus.LOST,
                         "stopped after client disconnect")

    def destroy(self) -> None:
        self._destroyed = True
        self.stop(0.5)
        for tr in self.task_runners.values():
            tr.join(2.0)
            if tr.handle is not None:
                tr.driver.destroy_task(tr.handle)
        # free only after the processes are down — freeing first would
        # let a new alloc double-use a still-running accelerator
        self._free_devices()
        self.alloc_dir.destroy()
        if self.state_db is not None:
            self.state_db.delete_alloc(self.alloc.id)

    def restore(self) -> None:
        """Reattach task runners from the state DB after client restart
        (client restore path, client.go:1290 restoreState)."""
        if self.state_db is None:
            return
        tg = self.task_group()
        if tg is None:
            return
        self.alloc_dir.build()
        # repopulate device accounting for a still-running alloc so new
        # placements cannot double-book its instances; a failure here
        # (plugin config shrank) already failed the alloc — do NOT
        # recover tasks, or status aggregation would mask it
        dev_env = self._reserve_devices()
        if dev_env is None:
            return
        ports = self._port_map()
        saved = self.state_db.get_task_states(self.alloc.id)
        for task in tg.tasks:
            tr = TaskRunner(
                self.alloc, task, self.registry.get(task.driver),
                self.alloc_dir, node=self.node,
                on_state=self._on_task_state, state_db=self.state_db,
                ports=ports, rpc=self.rpc,
                extra_env=dev_env.get(task.name))
            self.task_runners[task.name] = tr
            if task.name in saved:
                state, failed, restarts, handle = saved[task.name]
                tr.recover(state, failed, restarts, handle)
        with self._lock:
            self._aggregate_status()
