"""Task executor subprocess (reference drivers/shared/executor/ — the
separate supervisor process that go-plugin drivers launch and REATTACH to
over RPC, executor_linux.go for the cgroup/namespace isolation).

Runs as `python -m nomad_tpu.client.executor <spec.json>`, stdlib-only:

- creates a cgroup (v1 cpu+memory or v2) and applies cpu share / memory
  limits from the spec, then starts the task in its own session inside it
- serves a JSON-lines protocol on a unix socket: wait / stop / signal /
  stats / destroy — the driver (and a restarted client's driver, via the
  socket path persisted in the TaskHandle) talks to the task only through
  this boundary, exactly like the reference's gRPC-served executor
- survives the client: killing the nomad client leaves the executor and
  its task running; RecoverTask reconnects to the socket
"""
from __future__ import annotations

import json
import os
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time

CG_ROOT_V1 = "/sys/fs/cgroup"
CG_V2 = "/sys/fs/cgroup/unified"


class Cgroup:
    """Minimal cgroup v1 (cpu, memory) with a v2 fallback; no-op when the
    hierarchy is not writable (non-root / unsupported host)."""

    def __init__(self, name: str, cpu_shares: int = 0, memory_mb: int = 0):
        self.paths = []
        self.enabled = False
        v1_cpu = os.path.join(CG_ROOT_V1, "cpu", "nomad_tpu", name)
        v1_mem = os.path.join(CG_ROOT_V1, "memory", "nomad_tpu", name)
        try:
            os.makedirs(v1_cpu, exist_ok=True)
            os.makedirs(v1_mem, exist_ok=True)
            if cpu_shares > 0:
                _write(os.path.join(v1_cpu, "cpu.shares"),
                       str(max(2, cpu_shares)))
            if memory_mb > 0:
                _write(os.path.join(v1_mem, "memory.limit_in_bytes"),
                       str(memory_mb * 1024 * 1024))
            self.paths = [v1_cpu, v1_mem]
            self.enabled = True
        except OSError:
            self.paths = []

    def add_pid(self, pid: int) -> None:
        for p in self.paths:
            try:
                _write(os.path.join(p, "tasks"), str(pid))
            except OSError:
                pass

    def oom_killed(self) -> bool:
        for p in self.paths:
            if "/memory/" not in p:
                continue
            try:
                with open(os.path.join(p, "memory.oom_control")) as f:
                    for line in f:
                        if line.startswith("oom_kill ") and \
                                int(line.split()[1]) > 0:
                            return True
            except OSError:
                pass
        return False

    def destroy(self) -> None:
        for p in self.paths:
            try:
                os.rmdir(p)
            except OSError:
                pass


def _write(path: str, value: str) -> None:
    with open(path, "w") as f:
        f.write(value)


class Executor:
    def __init__(self, spec: dict):
        self.spec = spec
        self.result = None           # {exit_code, signal, oom_killed}
        self._exit = threading.Event()
        self.cg = Cgroup(spec.get("id", str(os.getpid())),
                         int(spec.get("cpu_shares", 0) or 0),
                         int(spec.get("memory_mb", 0) or 0))
        from nomad_tpu.client.logmon import open_log_pipe
        max_size = int(spec.get("log_max_size",
                                10 * 1024 * 1024))
        max_files = int(spec.get("log_max_files", 10))
        stdout = open_log_pipe(spec["stdout"], max_size, max_files) \
            if spec.get("stdout") else None
        stderr = open_log_pipe(spec["stderr"], max_size, max_files) \
            if spec.get("stderr") else None
        env = dict(spec.get("env") or {})
        cg = self.cg

        def _enter_cgroup():
            # in the child after fork, before exec: the task's very first
            # instruction already runs inside the limits (the reference
            # enters the cgroup via libcontainer pre-exec)
            os.setsid()
            cg.add_pid(os.getpid())

        self.proc = subprocess.Popen(
            [spec["command"], *[str(a) for a in spec.get("args", [])]],
            cwd=spec.get("cwd") or None,
            env={**os.environ, **env},
            stdout=stdout, stderr=stderr,
            preexec_fn=_enter_cgroup)
        if stdout is not None:
            os.close(stdout)
        if stderr is not None:
            os.close(stderr)
        threading.Thread(target=self._reap, daemon=True).start()

    def _reap(self) -> None:
        code = self.proc.wait()
        res = {"exit_code": code if code >= 0 else 128 - code,
               "signal": -code if code < 0 else 0,
               "oom_killed": self.cg.oom_killed()}
        self.result = res
        self._exit.set()

    # ------------------------------------------------------------- ops

    def op_wait(self, req):
        self._exit.wait()
        return self.result

    def op_signal(self, req):
        sig = int(req.get("sig", signal.SIGTERM))
        try:
            os.killpg(os.getpgid(self.proc.pid), sig)
        except (ProcessLookupError, PermissionError):
            pass
        return {"ok": True}

    def op_stop(self, req):
        timeout = float(req.get("timeout", 5.0))
        self.op_signal({"sig": signal.SIGTERM})
        if not self._exit.wait(timeout):
            self.op_signal({"sig": signal.SIGKILL})
            self._exit.wait(5.0)
        return self.result or {"exit_code": -1, "signal": 9,
                               "oom_killed": False}

    def op_stats(self, req):
        mem = 0
        for p in self.cg.paths:
            if "/memory/" in p:
                try:
                    with open(os.path.join(p,
                                           "memory.usage_in_bytes")) as f:
                        mem = int(f.read().strip())
                except OSError:
                    pass
        return {"pid": self.proc.pid, "running": self.result is None,
                "memory_bytes": mem, "cgroup": self.cg.enabled}

    def op_destroy(self, req):
        self.op_stop({"timeout": 0.5})
        self.cg.destroy()
        # unlink the socket first so reattach attempts fail immediately,
        # then exit after the response flushes
        try:
            os.unlink(self.spec["socket"])
        except OSError:
            pass
        threading.Thread(target=lambda: (time.sleep(0.2),
                                         os._exit(0)), daemon=True).start()
        return {"ok": True}

    def op_ping(self, req):
        return {"ok": True, "pid": self.proc.pid,
                "running": self.result is None}


def serve(spec_path: str) -> None:
    with open(spec_path) as f:
        spec = json.load(f)
    ex = Executor(spec)

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            while True:
                line = self.rfile.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    fn = getattr(ex, f"op_{req.get('op')}", None)
                    resp = fn(req) if fn else {"error": "unknown op"}
                except Exception as e:          # noqa: BLE001
                    resp = {"error": str(e)}
                try:
                    self.wfile.write((json.dumps(resp) + "\n").encode())
                    self.wfile.flush()
                except (BrokenPipeError, OSError):
                    return

    class Srv(socketserver.ThreadingUnixStreamServer):
        daemon_threads = True

    sock_path = spec["socket"]
    if os.path.exists(sock_path):
        os.unlink(sock_path)
    srv = Srv(sock_path, Handler)
    # signal readiness: the driver waits for this file
    _write(spec_path + ".ready", str(os.getpid()))
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    # live as long as someone may still wait on the task result
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    serve(sys.argv[1])
