"""Client device plugin manager (reference: client/devicemanager/
manager.go — dispenses device plugins, streams fingerprints, and
reserves instances at task start; plugins/device/device.go:25-37 —
the Fingerprint/Reserve plugin interface).

Plugins here are in-process objects (the framework's plugin registry is
in-process by design); `FakeDevicePlugin` materializes devices from
agent/client config so a node fingerprints and reserves real
client-side state without physical hardware — the reference's
device-plugin e2e tests do the same with its fake device plugin.

Reservation: the scheduler picks concrete instance ids server-side
(scheduler/devices.py) and ships them on the alloc
(AllocatedTaskResources.devices).  The client-side manager is the
enforcement point: it tracks in-use instances, rejects double
reservations (a torn plan or buggy server must not oversubscribe a
local accelerator), and returns the env the task needs to see its
devices (reference device.Reserve -> ContainerReservation envs)."""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from nomad_tpu.structs.resources import NodeDevice


class DeviceReservationError(Exception):
    pass


class DevicePlugin:
    """plugins/device/device.go DevicePlugin: Fingerprint + Reserve."""

    def fingerprint(self) -> List[NodeDevice]:
        raise NotImplementedError

    def reserve(self, instance_ids: List[str]) -> Dict[str, str]:
        """-> env vars the task needs (ContainerReservation.Envs)."""
        raise NotImplementedError


class FakeDevicePlugin(DevicePlugin):
    """Config-built plugin: spec keys vendor/type/name plus either
    `count` (ids generated) or `instance_ids`, optional `attributes`,
    `env_var` (default NOMAD_DEVICE_<TYPE>), `unhealthy_ids`."""

    def __init__(self, spec: dict):
        self.vendor = spec.get("vendor", "nomad")
        self.type = spec.get("type", "gpu")
        self.name = spec.get("name", self.type)
        ids = list(spec.get("instance_ids") or [])
        if not ids:
            ids = [f"{self.name}-{i}" for i in range(int(
                spec.get("count", 1)))]
        self.instance_ids = ids
        self.attributes = dict(spec.get("attributes") or {})
        self.unhealthy_ids = list(spec.get("unhealthy_ids") or [])
        self.env_var = spec.get(
            "env_var", f"NOMAD_DEVICE_{self.type.upper()}")

    def fingerprint(self) -> List[NodeDevice]:
        return [NodeDevice(
            vendor=self.vendor, type=self.type, name=self.name,
            instance_ids=list(self.instance_ids),
            attributes=dict(self.attributes),
            unhealthy_ids=list(self.unhealthy_ids))]

    def reserve(self, instance_ids: List[str]) -> Dict[str, str]:
        unknown = [i for i in instance_ids
                   if i not in self.instance_ids]
        if unknown:
            raise DeviceReservationError(
                f"unknown instances for {self.key()}: {unknown}")
        return {self.env_var: ",".join(sorted(instance_ids))}

    def key(self) -> str:
        return f"{self.vendor}/{self.type}/{self.name}"


class DeviceManager:
    """Fingerprint aggregation + instance accounting for one client."""

    def __init__(self, plugins: Optional[List[DevicePlugin]] = None):
        self.plugins: Dict[str, DevicePlugin] = {}
        for p in plugins or []:
            self.plugins[_plugin_key(p)] = p
        # instance id -> alloc id holding it, per plugin key
        self._in_use: Dict[str, Dict[str, str]] = {}
        self._lock = threading.Lock()

    def fingerprint(self) -> List[NodeDevice]:
        out: List[NodeDevice] = []
        for p in self.plugins.values():
            try:
                out.extend(p.fingerprint())
            except Exception:                        # noqa: BLE001
                continue                 # a broken plugin hides itself
        return out

    def reserve(self, alloc_id: str,
                devices: List[dict]) -> Dict[str, str]:
        """Reserve an alloc's scheduler-assigned instances; returns the
        merged task env.  All-or-nothing: a conflict releases anything
        taken in this call."""
        env: Dict[str, str] = {}
        taken: List[tuple] = []
        with self._lock:
            try:
                for d in devices:
                    key = (f"{d.get('vendor', '')}/{d.get('type', '')}/"
                           f"{d.get('name', '')}")
                    plugin = self.plugins.get(key)
                    if plugin is None:
                        raise DeviceReservationError(
                            f"no device plugin for {key}")
                    used = self._in_use.setdefault(key, {})
                    ids = list(d.get("device_ids") or [])
                    for i in ids:
                        holder = used.get(i)
                        if holder is not None and holder != alloc_id:
                            raise DeviceReservationError(
                                f"instance {i} of {key} already held "
                                f"by alloc {holder[:8]}")
                    env.update(plugin.reserve(ids))
                    for i in ids:
                        used[i] = alloc_id
                        taken.append((key, i))
            except Exception:
                for key, i in taken:
                    self._in_use.get(key, {}).pop(i, None)
                raise
        return env

    def free(self, alloc_id: str) -> int:
        """Release every instance an alloc holds (alloc stop/destroy)."""
        n = 0
        with self._lock:
            for used in self._in_use.values():
                drop = [i for i, a in used.items() if a == alloc_id]
                for i in drop:
                    del used[i]
                n += len(drop)
        return n

    def in_use(self) -> Dict[str, List[str]]:
        with self._lock:
            return {k: sorted(v) for k, v in self._in_use.items() if v}


def _plugin_key(p: DevicePlugin) -> str:
    if hasattr(p, "key"):
        return p.key()
    fps = p.fingerprint()
    return (f"{fps[0].vendor}/{fps[0].type}/{fps[0].name}"
            if fps else repr(p))
