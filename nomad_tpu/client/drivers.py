"""Task drivers (reference: plugins/drivers/driver.go DriverPlugin —
StartTask/WaitTask/StopTask/DestroyTask/RecoverTask/InspectTask — and the
built-in drivers drivers/mock/ and drivers/rawexec/).

In the reference drivers are go-plugin subprocesses speaking gRPC; here
they are in-process plugins behind the same interface, registered in a
DriverRegistry the TaskRunner dispenses from (the reference's
client/pluginmanager/drivermanager).  `RawExecDriver` runs real OS
subprocesses; `MockDriver` is the scriptable test driver
(drivers/mock/driver.go:113 — run_for, exit_code, start_error...).
"""
from __future__ import annotations

import os
import shlex
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class TaskHandle:
    """Opaque recoverable handle to a started task (reference
    drivers.TaskHandle, persisted so RecoverTask can reattach after a
    client restart)."""
    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    driver: str = ""
    task_name: str = ""
    alloc_id: str = ""
    pid: int = 0
    config: Dict[str, object] = field(default_factory=dict)
    started_at: float = 0.0


@dataclass
class ExitResult:
    exit_code: int = 0
    signal: int = 0
    err: str = ""
    oom_killed: bool = False

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.err


class DriverError(Exception):
    pass


class Driver:
    """In-process driver plugin interface (plugins/drivers/driver.go:47)."""

    name = "driver"

    def fingerprint(self) -> dict:
        """Health snapshot for the node's drivers map."""
        return {"detected": True, "healthy": True}

    def start_task(self, handle: TaskHandle, task, env: Dict[str, str],
                   task_dir: str) -> None:
        raise NotImplementedError

    def wait_task(self, handle: TaskHandle) -> ExitResult:
        raise NotImplementedError

    def stop_task(self, handle: TaskHandle, timeout_s: float = 5.0) -> None:
        raise NotImplementedError

    def destroy_task(self, handle: TaskHandle) -> None:
        pass

    def recover_task(self, handle: TaskHandle) -> bool:
        """Reattach to a task from a persisted handle; False if gone."""
        return False

    def inspect_task(self, handle: TaskHandle) -> dict:
        return {}


class MockDriver(Driver):
    """Scriptable fake driver (reference drivers/mock/driver.go).

    task.config knobs: run_for (seconds), exit_code, start_error,
    start_error_recoverable, signal_error, kill_after.
    """

    name = "mock_driver"

    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: Dict[str, dict] = {}

    def start_task(self, handle, task, env, task_dir):
        cfg = task.config or {}
        if cfg.get("start_error"):
            raise DriverError(str(cfg["start_error"]))
        done = threading.Event()
        state = {
            "done": done,
            "exit": ExitResult(exit_code=int(cfg.get("exit_code", 0))),
            "run_for": float(cfg.get("run_for", 0.0)),
            "started": time.time(),
            "killed": False,
        }
        with self._lock:
            self._tasks[handle.id] = state
        handle.pid = os.getpid()
        handle.started_at = state["started"]

        def run():
            finished = done.wait(state["run_for"]) if state["run_for"] > 0 \
                else None
            if state["run_for"] <= 0 and not done.is_set():
                done.wait()                      # run until killed
            done.set()

        t = threading.Thread(target=run, daemon=True,
                             name=f"mock-{task.name}")
        state["thread"] = t
        t.start()

    def wait_task(self, handle) -> ExitResult:
        with self._lock:
            state = self._tasks.get(handle.id)
        if state is None:
            return ExitResult(err="unknown task")
        if state["run_for"] > 0:
            state["done"].wait(state["run_for"] + 5.0)
            state["done"].set()
        else:
            state["done"].wait()
        if state["killed"]:
            return ExitResult(exit_code=137, signal=9)
        return state["exit"]

    def stop_task(self, handle, timeout_s: float = 5.0):
        with self._lock:
            state = self._tasks.get(handle.id)
        if state is not None:
            state["killed"] = state["run_for"] <= 0 or \
                not state["done"].is_set()
            state["done"].set()

    def destroy_task(self, handle):
        with self._lock:
            self._tasks.pop(handle.id, None)

    def recover_task(self, handle) -> bool:
        # in-process state died with the old client; mock tasks are not
        # recoverable (matches mock driver without persistent state)
        return handle.id in self._tasks


class RawExecDriver(Driver):
    """Real subprocess execution without isolation (drivers/rawexec/).

    task.config: command (str), args (list).  stdout/stderr stream to
    `logs/<task>.{stdout,stderr}` under the alloc dir (the reference's
    logmon file rotation, client/logmon/).
    """

    name = "raw_exec"

    def __init__(self):
        self._lock = threading.Lock()
        self._procs: Dict[str, subprocess.Popen] = {}

    def start_task(self, handle, task, env, task_dir):
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise DriverError("raw_exec requires config.command")
        args = [str(command)] + [str(a) for a in cfg.get("args", [])]
        logs_dir = os.path.join(os.path.dirname(task_dir), "alloc", "logs")
        os.makedirs(logs_dir, exist_ok=True)
        # the child appends straight to the log file (O_APPEND): zero
        # extra processes and the stream survives client restarts.
        # Rotation is out-of-band — the client's log janitor
        # copy-truncates oversized files (logmon.rotate_copytruncate),
        # trading logmon.go's dedicated pump process for the logrotate
        # copytruncate discipline
        stdout = open(os.path.join(logs_dir, f"{task.name}.stdout"), "ab")
        stderr = open(os.path.join(logs_dir, f"{task.name}.stderr"), "ab")
        try:
            proc = subprocess.Popen(
                args, env={**os.environ, **env}, cwd=task_dir,
                stdout=stdout, stderr=stderr,
                start_new_session=True)        # own process group for kill
        except OSError as e:
            raise DriverError(f"failed to exec {command}: {e}")
        finally:
            stdout.close()
            stderr.close()
        handle.pid = proc.pid
        handle.started_at = time.time()
        with self._lock:
            self._procs[handle.id] = proc

    def wait_task(self, handle) -> ExitResult:
        with self._lock:
            proc = self._procs.get(handle.id)
        if proc is None:
            return self._wait_recovered(handle)
        code = proc.wait()
        if code < 0:
            return ExitResult(exit_code=128 - code, signal=-code)
        return ExitResult(exit_code=code)

    def stop_task(self, handle, timeout_s: float = 5.0):
        with self._lock:
            proc = self._procs.get(handle.id)
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if proc.poll() is not None:
                return
            time.sleep(0.05)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def destroy_task(self, handle):
        self.stop_task(handle, 0.0)
        with self._lock:
            self._procs.pop(handle.id, None)

    def recover_task(self, handle) -> bool:
        """Reattach by pid (reference executor reattach via go-plugin)."""
        if handle.pid <= 0:
            return False
        try:
            os.kill(handle.pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            pass
        return True

    def _wait_recovered(self, handle) -> ExitResult:
        """Poll a recovered (non-child) pid until it exits."""
        while True:
            try:
                os.kill(handle.pid, 0)
            except ProcessLookupError:
                return ExitResult(exit_code=0)
            except PermissionError:
                pass
            time.sleep(0.2)


class DriverRegistry:
    """Dispenses driver singletons (client/pluginmanager/drivermanager)."""

    def __init__(self, names: Optional[List[str]] = None):
        self._drivers: Dict[str, Driver] = {}
        available = {"mock_driver": MockDriver, "raw_exec": RawExecDriver,
                     # exec = cgroup-isolated execution via the separate
                     # executor process (ExecDriver below); java/docker/
                     # qemu have no runtime in this rig
                     "exec": ExecDriver, "mock": MockDriver}
        for name in names or ["mock_driver", "raw_exec", "exec", "mock"]:
            cls = available.get(name)
            if cls is not None:
                drv = cls()
                drv_name = name
                self._drivers[drv_name] = drv

    def get(self, name: str) -> Driver:
        drv = self._drivers.get(name)
        if drv is None:
            raise DriverError(f"driver {name!r} not available")
        return drv

    def names(self) -> List[str]:
        return sorted(self._drivers)

    def fingerprints(self) -> Dict[str, dict]:
        return {name: drv.fingerprint()
                for name, drv in self._drivers.items()}


class ExecDriver(Driver):
    """Isolated task execution through a separate executor process
    (reference drivers/exec/ + drivers/shared/executor/): the driver
    launches `python -m nomad_tpu.client.executor`, which creates a
    cgroup with the task's cpu/memory limits, starts the task inside it,
    and serves wait/stop/signal/stats/destroy on a unix socket.  The
    socket path rides in the TaskHandle, so a restarted client's driver
    REATTACHES to the still-running executor — the reference's go-plugin
    reattach semantics, with the executor as the process boundary.
    """

    name = "exec"

    def fingerprint(self) -> dict:
        import sys
        healthy = os.access("/sys/fs/cgroup", os.W_OK)
        return {"detected": True, "healthy": healthy,
                "attributes": {"driver.exec.cgroups": "1" if healthy
                               else "0"}}

    # ------------------------------------------------------------- rpc

    def _connect(self, handle, timeout=5.0):
        import socket as _socket
        sock_path = handle.config.get("socket")
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            try:
                s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
                s.settimeout(600.0)
                s.connect(sock_path)
                return s
            except OSError as e:
                last = e
                time.sleep(0.05)
        raise DriverError(f"executor socket unavailable: {last}")

    def _rpc(self, handle, req: dict, timeout=5.0) -> dict:
        import json
        s = self._connect(handle, timeout)
        try:
            s.sendall((json.dumps(req) + "\n").encode())
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(65536)
                if not chunk:
                    raise DriverError("executor closed connection")
                buf += chunk
            return json.loads(buf)
        finally:
            s.close()

    # ------------------------------------------------------------- api

    def start_task(self, handle, task, env, task_dir):
        import json
        import sys
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise DriverError("exec requires config.command")
        logs_dir = os.path.join(os.path.dirname(task_dir), "alloc", "logs")
        os.makedirs(logs_dir, exist_ok=True)
        run_dir = os.path.join(os.path.dirname(task_dir), "exec")
        os.makedirs(run_dir, exist_ok=True)
        lcfg = cfg.get("logs") or {}
        spec = {
            "id": handle.id[:8],
            "command": str(command),
            "args": [str(a) for a in cfg.get("args", [])],
            "env": dict(env),
            "cwd": task_dir,
            "stdout": os.path.join(logs_dir, f"{task.name}.stdout"),
            "stderr": os.path.join(logs_dir, f"{task.name}.stderr"),
            "log_max_size":
                int(lcfg.get("max_file_size_mb", 10)) * 1024 * 1024,
            "log_max_files": int(lcfg.get("max_files", 10)),
            "cpu_shares": task.resources.cpu if task.resources else 0,
            "memory_mb": task.resources.memory_mb if task.resources else 0,
            "socket": os.path.join(run_dir, f"{handle.id[:8]}.sock"),
        }
        spec_path = os.path.join(run_dir, f"{handle.id[:8]}.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "nomad_tpu.client.executor",
                 spec_path],
                start_new_session=True,     # survives the client process
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        except OSError as e:
            raise DriverError(f"failed to launch executor: {e}")
        # readiness: executor writes <spec>.ready once serving (generous
        # deadline: interpreter start stretches under full-machine load)
        deadline = time.time() + 30.0
        while not os.path.exists(spec_path + ".ready"):
            if proc.poll() is not None:
                raise DriverError("executor died during startup")
            if time.time() > deadline:
                raise DriverError("executor startup timeout")
            time.sleep(0.02)
        handle.pid = proc.pid
        handle.started_at = time.time()
        handle.config = {**dict(handle.config or {}),
                         "socket": spec["socket"], "spec": spec_path}

    def wait_task(self, handle) -> ExitResult:
        res = self._rpc(handle, {"op": "wait"}, timeout=10.0)
        return ExitResult(exit_code=int(res.get("exit_code", -1)),
                          signal=int(res.get("signal", 0)),
                          oom_killed=bool(res.get("oom_killed")))

    def stop_task(self, handle, timeout_s: float = 5.0):
        try:
            self._rpc(handle, {"op": "stop", "timeout": timeout_s},
                      timeout=timeout_s + 10.0)
        except DriverError:
            pass

    def destroy_task(self, handle):
        try:
            self._rpc(handle, {"op": "destroy"})
        except DriverError:
            pass

    def signal_task(self, handle, sig: int):
        self._rpc(handle, {"op": "signal", "sig": int(sig)})

    def inspect_task(self, handle) -> dict:
        return self._rpc(handle, {"op": "stats"})

    def recover_task(self, handle) -> bool:
        """Reattach over the unix socket (the executor outlives the
        client, plugins/drivers/driver.go RecoverTask)."""
        try:
            resp = self._rpc(handle, {"op": "ping"}, timeout=1.0)
            return bool(resp.get("ok"))
        except DriverError:
            return False
