"""Task drivers (reference: plugins/drivers/driver.go DriverPlugin —
StartTask/WaitTask/StopTask/DestroyTask/RecoverTask/InspectTask — and the
built-in drivers drivers/mock/ and drivers/rawexec/).

In the reference drivers are go-plugin subprocesses speaking gRPC; here
they are in-process plugins behind the same interface, registered in a
DriverRegistry the TaskRunner dispenses from (the reference's
client/pluginmanager/drivermanager).  `RawExecDriver` runs real OS
subprocesses; `MockDriver` is the scriptable test driver
(drivers/mock/driver.go:113 — run_for, exit_code, start_error...).
"""
from __future__ import annotations

import os
import shlex
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class TaskHandle:
    """Opaque recoverable handle to a started task (reference
    drivers.TaskHandle, persisted so RecoverTask can reattach after a
    client restart)."""
    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    driver: str = ""
    task_name: str = ""
    alloc_id: str = ""
    pid: int = 0
    config: Dict[str, object] = field(default_factory=dict)
    started_at: float = 0.0


@dataclass
class ExitResult:
    exit_code: int = 0
    signal: int = 0
    err: str = ""
    oom_killed: bool = False

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.err


class DriverError(Exception):
    pass


class Driver:
    """In-process driver plugin interface (plugins/drivers/driver.go:47)."""

    name = "driver"

    def fingerprint(self) -> dict:
        """Health snapshot for the node's drivers map."""
        return {"detected": True, "healthy": True}

    def start_task(self, handle: TaskHandle, task, env: Dict[str, str],
                   task_dir: str) -> None:
        raise NotImplementedError

    def wait_task(self, handle: TaskHandle) -> ExitResult:
        raise NotImplementedError

    def stop_task(self, handle: TaskHandle, timeout_s: float = 5.0) -> None:
        raise NotImplementedError

    def destroy_task(self, handle: TaskHandle) -> None:
        pass

    def recover_task(self, handle: TaskHandle) -> bool:
        """Reattach to a task from a persisted handle; False if gone."""
        return False

    def inspect_task(self, handle: TaskHandle) -> dict:
        return {}


class MockDriver(Driver):
    """Scriptable fake driver (reference drivers/mock/driver.go).

    task.config knobs: run_for (seconds), exit_code, start_error,
    start_error_recoverable, signal_error, kill_after.
    """

    name = "mock_driver"

    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: Dict[str, dict] = {}

    def start_task(self, handle, task, env, task_dir):
        cfg = task.config or {}
        if cfg.get("start_error"):
            raise DriverError(str(cfg["start_error"]))
        done = threading.Event()
        state = {
            "done": done,
            "exit": ExitResult(exit_code=int(cfg.get("exit_code", 0))),
            "run_for": float(cfg.get("run_for", 0.0)),
            "started": time.time(),
            "killed": False,
        }
        with self._lock:
            self._tasks[handle.id] = state
        handle.pid = os.getpid()
        handle.started_at = state["started"]

        def run():
            finished = done.wait(state["run_for"]) if state["run_for"] > 0 \
                else None
            if state["run_for"] <= 0 and not done.is_set():
                done.wait()                      # run until killed
            done.set()

        t = threading.Thread(target=run, daemon=True,
                             name=f"mock-{task.name}")
        state["thread"] = t
        t.start()

    def wait_task(self, handle) -> ExitResult:
        with self._lock:
            state = self._tasks.get(handle.id)
        if state is None:
            return ExitResult(err="unknown task")
        if state["run_for"] > 0:
            state["done"].wait(state["run_for"] + 5.0)
            state["done"].set()
        else:
            state["done"].wait()
        if state["killed"]:
            return ExitResult(exit_code=137, signal=9)
        return state["exit"]

    def stop_task(self, handle, timeout_s: float = 5.0):
        with self._lock:
            state = self._tasks.get(handle.id)
        if state is not None:
            state["killed"] = state["run_for"] <= 0 or \
                not state["done"].is_set()
            state["done"].set()

    def destroy_task(self, handle):
        with self._lock:
            self._tasks.pop(handle.id, None)

    def recover_task(self, handle) -> bool:
        # in-process state died with the old client; mock tasks are not
        # recoverable (matches mock driver without persistent state)
        return handle.id in self._tasks


class RawExecDriver(Driver):
    """Real subprocess execution without isolation (drivers/rawexec/).

    task.config: command (str), args (list).  stdout/stderr stream to
    `logs/<task>.{stdout,stderr}` under the alloc dir (the reference's
    logmon file rotation, client/logmon/).
    """

    name = "raw_exec"

    def __init__(self):
        self._lock = threading.Lock()
        self._procs: Dict[str, subprocess.Popen] = {}

    def start_task(self, handle, task, env, task_dir):
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise DriverError("raw_exec requires config.command")
        args = [str(command)] + [str(a) for a in cfg.get("args", [])]
        logs_dir = os.path.join(os.path.dirname(task_dir), "alloc", "logs")
        os.makedirs(logs_dir, exist_ok=True)
        stdout = open(os.path.join(logs_dir, f"{task.name}.stdout"), "ab")
        stderr = open(os.path.join(logs_dir, f"{task.name}.stderr"), "ab")
        try:
            proc = subprocess.Popen(
                args, env={**os.environ, **env}, cwd=task_dir,
                stdout=stdout, stderr=stderr,
                start_new_session=True)        # own process group for kill
        except OSError as e:
            raise DriverError(f"failed to exec {command}: {e}")
        finally:
            stdout.close()
            stderr.close()
        handle.pid = proc.pid
        handle.started_at = time.time()
        with self._lock:
            self._procs[handle.id] = proc

    def wait_task(self, handle) -> ExitResult:
        with self._lock:
            proc = self._procs.get(handle.id)
        if proc is None:
            return self._wait_recovered(handle)
        code = proc.wait()
        if code < 0:
            return ExitResult(exit_code=128 - code, signal=-code)
        return ExitResult(exit_code=code)

    def stop_task(self, handle, timeout_s: float = 5.0):
        with self._lock:
            proc = self._procs.get(handle.id)
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if proc.poll() is not None:
                return
            time.sleep(0.05)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def destroy_task(self, handle):
        self.stop_task(handle, 0.0)
        with self._lock:
            self._procs.pop(handle.id, None)

    def recover_task(self, handle) -> bool:
        """Reattach by pid (reference executor reattach via go-plugin)."""
        if handle.pid <= 0:
            return False
        try:
            os.kill(handle.pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            pass
        return True

    def _wait_recovered(self, handle) -> ExitResult:
        """Poll a recovered (non-child) pid until it exits."""
        while True:
            try:
                os.kill(handle.pid, 0)
            except ProcessLookupError:
                return ExitResult(exit_code=0)
            except PermissionError:
                pass
            time.sleep(0.2)


class DriverRegistry:
    """Dispenses driver singletons (client/pluginmanager/drivermanager)."""

    def __init__(self, names: Optional[List[str]] = None):
        self._drivers: Dict[str, Driver] = {}
        available = {"mock_driver": MockDriver, "raw_exec": RawExecDriver,
                     # exec/java/docker/qemu execute like raw_exec here:
                     # there is no container runtime in the test rig, and
                     # the driver boundary is what matters for parity
                     "exec": RawExecDriver, "mock": MockDriver}
        for name in names or ["mock_driver", "raw_exec", "exec", "mock"]:
            cls = available.get(name)
            if cls is not None:
                drv = cls()
                drv_name = name
                self._drivers[drv_name] = drv

    def get(self, name: str) -> Driver:
        drv = self._drivers.get(name)
        if drv is None:
            raise DriverError(f"driver {name!r} not available")
        return drv

    def names(self) -> List[str]:
        return sorted(self._drivers)

    def fingerprints(self) -> Dict[str, dict]:
        return {name: drv.fingerprint()
                for name, drv in self._drivers.items()}
