"""TaskRunner (reference: client/allocrunner/taskrunner/task_runner.go +
task_runner_hooks.go:49-110 — the per-task lifecycle: hook pipeline,
driver start, wait loop, restart tracking, state events pushed up).

Hook pipeline here: validate -> taskdir -> dispatch_payload -> taskenv ->
artifacts (client/getter.py) -> templates (rendered with env
interpolation) -> driver start.  Restart logic:
client/allocrunner/taskrunner/restarts/.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from nomad_tpu.client.drivers import (
    Driver,
    DriverError,
    ExitResult,
    TaskHandle,
)
from nomad_tpu.client.taskenv import build_task_env, interpolate
from nomad_tpu.structs import RestartPolicy
from nomad_tpu.structs.alloc import TaskState


class RestartTracker:
    """Decides between restart / delay-restart / fail
    (client/allocrunner/taskrunner/restarts/restarts.go)."""

    def __init__(self, policy: RestartPolicy):
        self.policy = policy
        self.count = 0
        self.window_start = 0.0

    def next(self, exit_result: ExitResult, now: Optional[float] = None):
        """-> ("restart", delay_s) | ("exit", None)  for batch-style
        success; failures consult the policy."""
        now = now or time.time()
        if self.window_start == 0.0 or \
                now - self.window_start > self.policy.interval_s:
            self.window_start = now
            self.count = 0
        self.count += 1
        if self.count > self.policy.attempts:
            if self.policy.mode == "delay":
                # wait out the rest of the interval, then a fresh window
                delay = self.policy.interval_s - (now - self.window_start) \
                    + self.policy.delay_s
                self.window_start = 0.0
                self.count = 0
                return ("restart", max(delay, self.policy.delay_s))
            return ("fail", None)
        return ("restart", self.policy.delay_s)


class TaskRunner:
    def __init__(self, alloc, task, driver: Driver, alloc_dir,
                 node=None, on_state: Optional[Callable] = None,
                 state_db=None, ports: Optional[Dict[str, int]] = None,
                 volumes: Optional[Dict[str, str]] = None):
        self.alloc = alloc
        self.task = task
        self.driver = driver
        self.alloc_dir = alloc_dir
        self.node = node
        self.on_state = on_state or (lambda *a: None)
        self.state_db = state_db
        self.ports = ports or {}
        self.volumes = volumes or {}    # CSI alias -> host mount path
        self.state = TaskState()
        self.handle: Optional[TaskHandle] = None
        self.restart_tracker = RestartTracker(
            self._restart_policy())
        self._kill = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.env: Dict[str, str] = {}

    def _restart_policy(self) -> RestartPolicy:
        job = self.alloc.job
        if job is not None:
            tg = job.lookup_task_group(self.alloc.task_group)
            if tg is not None:
                return tg.restart_policy
        return RestartPolicy()

    # ------------------------------------------------------------ events

    def _emit(self, type_: str, detail: str = "") -> None:
        self.state.events.append(
            {"type": type_, "time": time.time(), "detail": detail})
        self._persist()
        self.on_state(self)

    def _set_state(self, state: str, failed: bool = False) -> None:
        self.state.state = state
        self.state.failed = failed
        if state == "running" and not self.state.started_at:
            self.state.started_at = time.time()
        if state == "dead":
            self.state.finished_at = time.time()
        self._persist()
        self.on_state(self)

    def _persist(self) -> None:
        if self.state_db is not None:
            self.state_db.put_task_state(
                self.alloc.id, self.task.name, self.state.state,
                self.state.failed, self.state.restarts, self.handle)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"task-{self.alloc.id[:8]}-{self.task.name}")
        self._thread.start()

    def run(self) -> None:
        try:
            self._run()
        except Exception as e:                       # noqa: BLE001
            self._emit("Task hook failed", str(e))
            self._set_state("dead", failed=True)

    def _run(self) -> None:
        # --- prestart hooks (task_runner_hooks.go:49).  Artifact fetch
        # failures are recoverable (getter GetError.Recoverable): the
        # restart policy applies instead of failing the task outright.
        from nomad_tpu.client.getter import ArtifactError
        self._emit("Received", "Task received by client")
        while not self._kill.is_set():
            try:
                self._prestart()
                break
            except ArtifactError as e:
                self._emit("Failed Artifact Download", str(e))
                verdict, delay = self.restart_tracker.next(
                    ExitResult(exit_code=-1, err=str(e)))
                if verdict == "restart" and not self._kill.is_set():
                    self.state.restarts += 1
                    self._emit("Restarting",
                               f"Task restarting in {delay:.1f}s")
                    if self._kill.wait(delay):
                        self._set_state("dead", failed=False)
                        return
                    continue
                self._set_state("dead", failed=True)
                return
        else:
            self._set_state("dead", failed=False)
            return
        self._run_loop()

    def _prestart(self) -> None:
        task_dir = self.alloc_dir.build_task_dir(self.task.name)
        self._dispatch_payload_hook(task_dir)
        self.env = build_task_env(self.alloc, self.task, self.node,
                                  task_dir, self.ports,
                                  volumes=self.volumes)
        self._artifact_hook(task_dir)
        self._template_hook(task_dir)
        self._task_dir = task_dir

    def _run_loop(self) -> None:
        task_dir = self._task_dir
        while not self._kill.is_set():
            self.handle = TaskHandle(driver=self.driver.name,
                                     task_name=self.task.name,
                                     alloc_id=self.alloc.id,
                                     config=dict(self.task.config or {}))
            try:
                self.driver.start_task(self.handle, self.task, self.env,
                                       task_dir)
            except DriverError as e:
                self._emit("Driver Failure", str(e))
                verdict, delay = self.restart_tracker.next(
                    ExitResult(exit_code=-1, err=str(e)))
                if verdict == "restart" and not self._kill.is_set():
                    self.state.restarts += 1
                    self._emit("Restarting",
                               f"Task restarting in {delay:.1f}s")
                    if self._kill.wait(delay):
                        break
                    continue
                self._set_state("dead", failed=True)
                return
            self._persist()
            self._emit("Started", "Task started by client")
            self._set_state("running")

            result = self.driver.wait_task(self.handle)
            if self._kill.is_set():
                self._emit("Killed", "Task killed by client")
                break
            if result.successful():
                self._emit("Terminated", "Exit Code: 0")
                # batch/sysbatch tasks complete on success; service/system
                # tasks restart per policy even on a clean exit (reference
                # restarts.go:handleWaitResult distinguishes job types)
                job_type = getattr(self.alloc.job, "type", "service") \
                    if self.alloc.job is not None else "service"
                if job_type in ("batch", "sysbatch"):
                    self._set_state("dead", failed=False)
                    return
                verdict, delay = self.restart_tracker.next(result)
                if verdict == "fail":
                    # a service that may not restart is a failure even on
                    # exit 0 (restarts.go TaskNotRestarting SetFailsTask),
                    # so the scheduler reschedules it
                    self._emit("Not Restarting",
                               "Exceeded allowed attempts")
                    self._set_state("dead", failed=True)
                    return
                self.state.restarts += 1
                self._emit("Restarting", f"Task restarting in {delay:.1f}s")
                if self._kill.wait(delay):
                    break
                continue
            self._emit("Terminated",
                       f"Exit Code: {result.exit_code}"
                       + (f", Err: {result.err}" if result.err else ""))
            verdict, delay = self.restart_tracker.next(result)
            if verdict == "fail" or self._kill.is_set():
                self._emit("Not Restarting",
                           "Exceeded allowed attempts")
                self._set_state("dead", failed=True)
                return
            self.state.restarts += 1
            self._emit("Restarting", f"Task restarting in {delay:.1f}s")
            if self._kill.wait(delay):
                break
        self._set_state("dead", failed=False)

    def kill(self, timeout_s: Optional[float] = None) -> None:
        self._kill.set()
        if self.handle is not None:
            self.driver.stop_task(
                self.handle,
                timeout_s if timeout_s is not None
                else self.task.kill_timeout_s)

    def join(self, timeout: float = 10.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def recover(self, prev_state: str, failed: bool, restarts: int,
                handle: Optional[TaskHandle]) -> bool:
        """Reattach to a running task after client restart
        (plugins/drivers RecoverTask; client/state restore)."""
        self.state.restarts = restarts
        if prev_state != "running" or handle is None:
            return False
        if not self.driver.recover_task(handle):
            self._emit("Terminated", "task not recoverable after restart")
            self._set_state("dead", failed=True)
            return False
        self.handle = handle
        self._set_state("running")
        self._thread = threading.Thread(
            target=self._wait_recovered, daemon=True,
            name=f"task-recovered-{self.task.name}")
        self._thread.start()
        return True

    def _wait_recovered(self) -> None:
        """Watch a reattached task; once it exits, apply the SAME restart
        policy as the normal run loop (recovery must not change restart
        semantics)."""
        try:
            result = self.driver.wait_task(self.handle)
            if self._kill.is_set():
                self._emit("Killed", "Task killed by client")
                self._set_state("dead", failed=False)
                return
            job_type = getattr(self.alloc.job, "type", "service") \
                if self.alloc.job is not None else "service"
            if result.successful() and job_type in ("batch", "sysbatch"):
                self._emit("Terminated", "Exit Code: 0")
                self._set_state("dead", failed=False)
                return
            self._emit("Terminated", f"Exit Code: {result.exit_code}")
            verdict, delay = self.restart_tracker.next(result)
            if verdict == "fail":
                self._emit("Not Restarting", "Exceeded allowed attempts")
                self._set_state("dead", failed=True)
                return
            self.state.restarts += 1
            self._emit("Restarting", f"Task restarting in {delay:.1f}s")
            if self._kill.wait(delay):
                self._set_state("dead", failed=False)
                return
            self._prestart()
            self._run_loop()
        except Exception as e:                       # noqa: BLE001
            self._emit("Task hook failed", str(e))
            self._set_state("dead", failed=True)

    # ------------------------------------------------------------ hooks

    def _dispatch_payload_hook(self, task_dir: str) -> None:
        """Write the dispatch payload file (taskrunner dispatch_hook)."""
        dp = self.task.dispatch_payload
        job = self.alloc.job
        if dp is None or job is None or not job.payload:
            return
        dest = os.path.join(task_dir, "local", dp.file or "payload")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(dest, "wb") as fh:
            fh.write(job.payload)

    def _artifact_hook(self, task_dir: str) -> None:
        """Fetch declared artifacts into the task dir before templates
        and driver start (taskrunner artifact_hook.go: emits Downloading
        Artifacts, failure is recoverable -> restart policy applies)."""
        if not self.task.artifacts:
            return
        from nomad_tpu.client.getter import fetch_artifact
        self._emit("Downloading Artifacts",
                   f"{len(self.task.artifacts)} artifact(s)")
        for art in self.task.artifacts:
            fetch_artifact(art, task_dir, self.env,
                           node=self.node, meta=self.task.meta)

    def _template_hook(self, task_dir: str) -> None:
        """Render inline templates with env interpolation (the reference
        uses consul-template; env/meta refs are the subset covered)."""
        for tmpl in self.task.templates or []:
            data = tmpl.get("data", "")
            dest = tmpl.get("destination", "local/template.out")
            rendered = interpolate(data, self.env, self.node,
                                   self.task.meta)
            path = os.path.join(task_dir, dest)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as fh:
                fh.write(rendered)
