"""TaskRunner (reference: client/allocrunner/taskrunner/task_runner.go +
task_runner_hooks.go:49-110 — the per-task lifecycle: hook pipeline,
driver start, wait loop, restart tracking, state events pushed up).

Hook pipeline here: validate -> taskdir -> dispatch_payload -> taskenv ->
artifacts (client/getter.py) -> templates (rendered with env
interpolation) -> driver start.  Restart logic:
client/allocrunner/taskrunner/restarts/.
"""
from __future__ import annotations

import os
import re
import threading
import time
from typing import Callable, Dict, Optional

from nomad_tpu.client.drivers import (
    Driver,
    DriverError,
    ExitResult,
    TaskHandle,
)
from nomad_tpu import knobs
from nomad_tpu.client.taskenv import build_task_env, interpolate
from nomad_tpu.structs import RestartPolicy
from nomad_tpu.structs.alloc import TaskState


class RestartTracker:
    """Decides between restart / delay-restart / fail
    (client/allocrunner/taskrunner/restarts/restarts.go)."""

    def __init__(self, policy: RestartPolicy):
        self.policy = policy
        self.count = 0
        self.window_start = 0.0

    def next(self, exit_result: ExitResult, now: Optional[float] = None):
        """-> ("restart", delay_s) | ("exit", None)  for batch-style
        success; failures consult the policy."""
        now = now or time.time()
        if self.window_start == 0.0 or \
                now - self.window_start > self.policy.interval_s:
            self.window_start = now
            self.count = 0
        self.count += 1
        if self.count > self.policy.attempts:
            if self.policy.mode == "delay":
                # wait out the rest of the interval, then a fresh window
                delay = self.policy.interval_s - (now - self.window_start) \
                    + self.policy.delay_s
                self.window_start = 0.0
                self.count = 0
                return ("restart", max(delay, self.policy.delay_s))
            return ("fail", None)
        return ("restart", self.policy.delay_s)


class TaskRunner:
    def __init__(self, alloc, task, driver: Driver, alloc_dir,
                 node=None, on_state: Optional[Callable] = None,
                 state_db=None, ports: Optional[Dict[str, int]] = None,
                 volumes: Optional[Dict[str, str]] = None, rpc=None,
                 extra_env: Optional[Dict[str, str]] = None):
        self.alloc = alloc
        self.task = task
        self.driver = driver
        self.alloc_dir = alloc_dir
        self.node = node
        self.on_state = on_state or (lambda *a: None)
        self.state_db = state_db
        self.ports = ports or {}
        self.volumes = volumes or {}    # CSI alias -> host mount path
        self.rpc = rpc                  # client->server (vault/templates)
        self.extra_env = extra_env or {}   # device reservations etc.
        self.state = TaskState()
        self.handle: Optional[TaskHandle] = None
        self.restart_tracker = RestartTracker(
            self._restart_policy())
        self._kill = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.env: Dict[str, str] = {}
        self.vault_token: str = ""
        self._vault_thread: Optional[threading.Thread] = None
        self._tmpl_thread: Optional[threading.Thread] = None
        # template index -> {secret path: version} it rendered; shared by
        # the prestart render, the renew-loop re-render, and the watcher
        # so one rotation triggers exactly one change_mode application
        self._tmpl_versions: Dict[int, Dict[str, int]] = {}
        # set by the vault/template watchers: restart WITHOUT counting
        # against the restart policy (reference template/vault change_mode
        # restarts are not policy failures)
        self._restart_requested = threading.Event()

    def _restart_policy(self) -> RestartPolicy:
        job = self.alloc.job
        if job is not None:
            tg = job.lookup_task_group(self.alloc.task_group)
            if tg is not None:
                return tg.restart_policy
        return RestartPolicy()

    # ------------------------------------------------------------ events

    def _emit(self, type_: str, detail: str = "") -> None:
        self.state.events.append(
            {"type": type_, "time": time.time(), "detail": detail})
        self._persist()
        self.on_state(self)

    def _set_state(self, state: str, failed: bool = False) -> None:
        self.state.state = state
        self.state.failed = failed
        if state == "running" and not self.state.started_at:
            self.state.started_at = time.time()
        if state == "dead":
            self.state.finished_at = time.time()
        self._persist()
        self.on_state(self)

    def _persist(self) -> None:
        if self.state_db is not None:
            self.state_db.put_task_state(
                self.alloc.id, self.task.name, self.state.state,
                self.state.failed, self.state.restarts, self.handle)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"task-{self.alloc.id[:8]}-{self.task.name}")
        self._thread.start()

    def run(self) -> None:
        try:
            self._run()
        except Exception as e:                       # noqa: BLE001
            self._emit("Task hook failed", str(e))
            self._set_state("dead", failed=True)

    def _run(self) -> None:
        # --- prestart hooks (task_runner_hooks.go:49).  Artifact fetch
        # failures are recoverable (getter GetError.Recoverable): the
        # restart policy applies instead of failing the task outright.
        from nomad_tpu.client.getter import ArtifactError
        from nomad_tpu.rpc.endpoints import RpcError
        self._emit("Received", "Task received by client")
        while True:
            if self._kill.is_set():
                self._set_state("dead", failed=False)
                return
            try:
                self._prestart()
                break
            # artifact fetch AND vault/template RPC failures (leader
            # election, secret not yet written) are recoverable — the
            # restart policy applies, the task is not failed outright
            except (ArtifactError, RpcError) as e:
                self._emit("Failed Artifact Download"
                           if isinstance(e, ArtifactError)
                           else "Prestart Hook Failed", str(e))
                verdict, delay = self.restart_tracker.next(
                    ExitResult(exit_code=-1, err=str(e)))
                if self._kill.is_set():
                    # a deliberate stop mid-retry is not a failure
                    self._set_state("dead", failed=False)
                    return
                if verdict != "restart":
                    self._set_state("dead", failed=True)
                    return
                self.state.restarts += 1
                self._emit("Restarting",
                           f"Task restarting in {delay:.1f}s")
                if self._kill.wait(delay):
                    self._set_state("dead", failed=False)
                    return
        self._run_loop()

    def _prestart(self) -> None:
        task_dir = self.alloc_dir.build_task_dir(self.task.name)
        self._dispatch_payload_hook(task_dir)
        self.env = build_task_env(self.alloc, self.task, self.node,
                                  task_dir, self.ports,
                                  volumes=self.volumes)
        self.env.update(self.extra_env)
        self._vault_hook(task_dir)
        self._artifact_hook(task_dir)
        self._template_hook(task_dir)
        self._task_dir = task_dir

    def _run_loop(self) -> None:
        task_dir = self._task_dir
        while not self._kill.is_set():
            self.handle = TaskHandle(driver=self.driver.name,
                                     task_name=self.task.name,
                                     alloc_id=self.alloc.id,
                                     config=dict(self.task.config or {}))
            try:
                self.driver.start_task(self.handle, self.task, self.env,
                                       task_dir)
            except DriverError as e:
                self._emit("Driver Failure", str(e))
                verdict, delay = self.restart_tracker.next(
                    ExitResult(exit_code=-1, err=str(e)))
                if verdict == "restart" and not self._kill.is_set():
                    self.state.restarts += 1
                    self._emit("Restarting",
                               f"Task restarting in {delay:.1f}s")
                    if self._kill.wait(delay):
                        break
                    continue
                self._set_state("dead", failed=True)
                return
            self._persist()
            self._emit("Started", "Task started by client")
            self._set_state("running")

            result = self.driver.wait_task(self.handle)
            if self._kill.is_set():
                self._emit("Killed", "Task killed by client")
                break
            if self._restart_requested.is_set() and result.signal != 0:
                # vault/template change_mode restart: not a failure, does
                # not count against the restart policy.  Gated on a
                # signal exit (our stop_task) so a genuine crash racing
                # the watcher still goes through the policy below.
                self._restart_requested.clear()
                self.state.restarts += 1
                self._emit("Restarting",
                           "Template with change_mode restart re-rendered")
                continue
            self._restart_requested.clear()
            if result.successful():
                self._emit("Terminated", "Exit Code: 0")
                # batch/sysbatch tasks complete on success; service/system
                # tasks restart per policy even on a clean exit (reference
                # restarts.go:handleWaitResult distinguishes job types)
                job_type = getattr(self.alloc.job, "type", "service") \
                    if self.alloc.job is not None else "service"
                if job_type in ("batch", "sysbatch"):
                    self._set_state("dead", failed=False)
                    return
                verdict, delay = self.restart_tracker.next(result)
                if verdict == "fail":
                    # a service that may not restart is a failure even on
                    # exit 0 (restarts.go TaskNotRestarting SetFailsTask),
                    # so the scheduler reschedules it
                    self._emit("Not Restarting",
                               "Exceeded allowed attempts")
                    self._set_state("dead", failed=True)
                    return
                self.state.restarts += 1
                self._emit("Restarting", f"Task restarting in {delay:.1f}s")
                if self._kill.wait(delay):
                    break
                continue
            self._emit("Terminated",
                       f"Exit Code: {result.exit_code}"
                       + (f", Err: {result.err}" if result.err else ""))
            verdict, delay = self.restart_tracker.next(result)
            if verdict == "fail" or self._kill.is_set():
                self._emit("Not Restarting",
                           "Exceeded allowed attempts")
                self._set_state("dead", failed=True)
                return
            self.state.restarts += 1
            self._emit("Restarting", f"Task restarting in {delay:.1f}s")
            if self._kill.wait(delay):
                break
        self._set_state("dead", failed=False)

    def kill(self, timeout_s: Optional[float] = None) -> None:
        self._kill.set()
        if self.handle is not None:
            self.driver.stop_task(
                self.handle,
                timeout_s if timeout_s is not None
                else self.task.kill_timeout_s)

    def join(self, timeout: float = 10.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def recover(self, prev_state: str, failed: bool, restarts: int,
                handle: Optional[TaskHandle]) -> bool:
        """Reattach to a running task after client restart
        (plugins/drivers RecoverTask; client/state restore)."""
        self.state.restarts = restarts
        if prev_state != "running" or handle is None:
            return False
        if not self.driver.recover_task(handle):
            self._emit("Terminated", "task not recoverable after restart")
            self._set_state("dead", failed=True)
            return False
        self.handle = handle
        self._set_state("running")
        # a recovered task never re-runs _prestart, so re-arm the vault
        # renewal + template watcher here (best-effort: the task is
        # already running with its old token/templates on disk)
        try:
            task_dir = self.alloc_dir.task_dir(self.task.name)
            self.env = build_task_env(self.alloc, self.task, self.node,
                                      task_dir, self.ports,
                                      volumes=self.volumes)
            self.env.update(self.extra_env)
            self._vault_hook(task_dir)
            self._template_hook(task_dir)
            self._task_dir = task_dir
        except Exception as e:                       # noqa: BLE001
            self._emit("Hook Recovery Failed", str(e))
        self._thread = threading.Thread(
            target=self._wait_recovered, daemon=True,
            name=f"task-recovered-{self.task.name}")
        self._thread.start()
        return True

    def _wait_recovered(self) -> None:
        """Watch a reattached task; once it exits, apply the SAME restart
        policy as the normal run loop (recovery must not change restart
        semantics)."""
        try:
            result = self.driver.wait_task(self.handle)
            if self._kill.is_set():
                self._emit("Killed", "Task killed by client")
                self._set_state("dead", failed=False)
                return
            job_type = getattr(self.alloc.job, "type", "service") \
                if self.alloc.job is not None else "service"
            if result.successful() and job_type in ("batch", "sysbatch"):
                self._emit("Terminated", "Exit Code: 0")
                self._set_state("dead", failed=False)
                return
            self._emit("Terminated", f"Exit Code: {result.exit_code}")
            verdict, delay = self.restart_tracker.next(result)
            if verdict == "fail":
                self._emit("Not Restarting", "Exceeded allowed attempts")
                self._set_state("dead", failed=True)
                return
            self.state.restarts += 1
            self._emit("Restarting", f"Task restarting in {delay:.1f}s")
            if self._kill.wait(delay):
                self._set_state("dead", failed=False)
                return
            self._prestart()
            self._run_loop()
        except Exception as e:                       # noqa: BLE001
            self._emit("Task hook failed", str(e))
            self._set_state("dead", failed=True)

    # ------------------------------------------------------------ hooks

    def _dispatch_payload_hook(self, task_dir: str) -> None:
        """Write the dispatch payload file (taskrunner dispatch_hook)."""
        dp = self.task.dispatch_payload
        job = self.alloc.job
        if dp is None or job is None or not job.payload:
            return
        dest = os.path.join(task_dir, "local", dp.file or "payload")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(dest, "wb") as fh:
            fh.write(job.payload)

    def _artifact_hook(self, task_dir: str) -> None:
        """Fetch declared artifacts into the task dir before templates
        and driver start (taskrunner artifact_hook.go: emits Downloading
        Artifacts, failure is recoverable -> restart policy applies)."""
        if not self.task.artifacts:
            return
        from nomad_tpu.client.getter import fetch_artifact
        self._emit("Downloading Artifacts",
                   f"{len(self.task.artifacts)} artifact(s)")
        for art in self.task.artifacts:
            fetch_artifact(art, task_dir, self.env,
                           node=self.node, meta=self.task.meta)

    # ------------------------------------------------------ vault/templates

    def _vault_hook(self, task_dir: str) -> None:
        """Derive a per-task secrets token and keep it renewed
        (reference taskrunner/vault_hook.go: token to secrets/
        vault_token + VAULT_TOKEN env; renewal at half-TTL; on renewal
        failure re-derive and apply the vault change_mode)."""
        if not self.task.vault or self.rpc is None:
            return
        grant = self.rpc("Secrets.Derive", self._derive_args())
        self._install_token(task_dir, grant)
        if self._vault_thread is None or not self._vault_thread.is_alive():
            self._vault_thread = threading.Thread(
                target=self._vault_renew_loop,
                args=(task_dir, float(grant.get("ttl_s", 3600.0))),
                daemon=True, name=f"vault-{self.task.name}")
            self._vault_thread.start()

    def _derive_args(self) -> dict:
        """Secrets.Derive payload: the node's identity rides along so the
        server can verify the caller really hosts the alloc."""
        return {"alloc_id": self.alloc.id, "task": self.task.name,
                "node_id": getattr(self.node, "id", ""),
                "node_secret_id": getattr(self.node, "secret_id", "")}

    def _install_token(self, task_dir: str, grant: dict) -> None:
        self.vault_token = grant["token"]
        self.env["VAULT_TOKEN"] = self.vault_token
        path = os.path.join(task_dir, "secrets", "vault_token")
        with open(path, "w") as fh:
            fh.write(self.vault_token)
        os.chmod(path, 0o600)

    def _vault_renew_loop(self, task_dir: str, ttl_s: float) -> None:
        interval = max(min(ttl_s / 2.0, 60.0), 0.05)
        misses = 0
        while not self._kill.wait(interval):
            if self.state.state == "dead":
                return                               # task is gone
            try:
                self.rpc("Secrets.Renew", {"token": self.vault_token})
                misses = 0
                continue
            except Exception:                        # noqa: BLE001
                # one blip (leader election, transient RPC) is not a
                # lost lease — the reference retries before re-deriving
                misses += 1
                if misses < 3:
                    continue
            # lease lost: re-derive, reinstall, re-render dependent
            # templates, then apply change_mode (default restart)
            try:
                grant = self.rpc("Secrets.Derive", self._derive_args())
            except Exception:                        # noqa: BLE001
                continue                             # server will retry us
            misses = 0
            try:
                self._install_token(task_dir, grant)
                self._render_templates(task_dir)
                self._apply_change_mode(
                    self.task.vault.get("change_mode", "restart"),
                    self.task.vault.get("change_signal", "SIGHUP"),
                    "Vault token re-derived")
            except Exception as e:                   # noqa: BLE001
                self._emit("Vault Re-derive Failed", str(e))

    def _apply_change_mode(self, mode: str, sig: str, why: str) -> None:
        if mode == "noop" or self.handle is None:
            return
        if mode == "signal":
            import signal as _signal
            signum = getattr(_signal, sig, _signal.SIGHUP)
            fn = getattr(self.driver, "signal_task", None)
            if fn is not None:
                self._emit("Signaling", f"{why}: {sig}")
                try:
                    fn(self.handle, int(signum))
                    return
                except Exception:                    # noqa: BLE001
                    pass                             # fall through: restart
        self._restart_requested.set()
        self.driver.stop_task(self.handle, self.task.kill_timeout_s)

    _SECRET_RE = re.compile(
        r'\{\{\s*(?:with\s+)?secret\s+"([^"]+)"\s+"([^"]+)"\s*\}\}')

    def _render_one(self, tmpl: dict, task_dir: str) -> Dict[str, int]:
        """Render a template; returns {secret_path: version} it read."""
        data = tmpl.get("data", "")
        dest = tmpl.get("destination", "local/template.out")
        versions: Dict[str, int] = {}

        def sub(m: "re.Match") -> str:
            path, field_ = m.group(1), m.group(2)
            if self.rpc is None:
                return ""
            got = self.rpc("Secrets.Read",
                           {"path": path, "token": self.vault_token})
            versions[path] = got["version"]
            return str(got["data"].get(field_, ""))

        rendered = self._SECRET_RE.sub(sub, data)
        rendered = interpolate(rendered, self.env, self.node,
                               self.task.meta)
        out = os.path.join(task_dir, dest)
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as fh:
            fh.write(rendered)
        return versions

    def _render_templates(self, task_dir: str) -> None:
        """(Re-)render every template, refreshing the shared version
        map so the watcher doesn't double-fire on the same rotation."""
        for i, tmpl in enumerate(self.task.templates or []):
            versions = self._render_one(tmpl, task_dir)
            if versions:
                self._tmpl_versions[i] = versions

    def _template_hook(self, task_dir: str) -> None:
        """Render inline templates (reference taskrunner/template/
        template.go via consul-template): env/meta/attr interpolation
        plus `{{ secret "path" "field" }}` reads through the task's
        vault token.  Templates that read secrets are watched — a
        version bump re-renders and applies the template change_mode
        (restart | signal | noop, reference TemplateChangeMode*)."""
        self._render_templates(task_dir)
        if self._tmpl_versions and self.rpc is not None and (
                self._tmpl_thread is None
                or not self._tmpl_thread.is_alive()):
            self._tmpl_thread = threading.Thread(
                target=self._template_watch_loop, args=(task_dir,),
                daemon=True, name=f"tmpl-{self.task.name}")
            self._tmpl_thread.start()

    def _template_watch_loop(self, task_dir: str) -> None:
        poll = knobs.get_float("NOMAD_TPU_TEMPLATE_POLL_S")
        while not self._kill.wait(poll):
            if self.state.state == "dead":
                return                               # task is gone
            for i in list(self._tmpl_versions):
                versions = self._tmpl_versions[i]
                tmpl = (self.task.templates or [])[i]
                changed = False
                for path, ver in versions.items():
                    try:
                        got = self.rpc("Secrets.Version",
                                       {"path": path,
                                        "token": self.vault_token})
                    except Exception:                # noqa: BLE001
                        continue                     # token mid-rotation
                    if got["version"] != ver:
                        changed = True
                if not changed:
                    continue
                try:
                    self._tmpl_versions[i] = self._render_one(
                        tmpl, task_dir)
                except Exception:                    # noqa: BLE001
                    continue
                self._emit("Template Re-rendered",
                           tmpl.get("destination", ""))
                self._apply_change_mode(
                    tmpl.get("change_mode", "restart"),
                    tmpl.get("change_signal", "SIGHUP"),
                    "Template re-rendered")
