"""Task environment builder (reference: client/taskenv/ — the env-var
builder that exposes NOMAD_* variables and interpolates ${...} references
in task config/env/templates)."""
from __future__ import annotations

import re
from typing import Dict, Optional


def build_task_env(alloc, task, node, task_dir: str = "",
                   ports: Optional[Dict[str, int]] = None,
                   volumes: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The NOMAD_* environment (client/taskenv/env.go Builder)."""
    job = alloc.job
    env = {
        "NOMAD_ALLOC_ID": alloc.id,
        "NOMAD_SHORT_ALLOC_ID": alloc.id[:8],
        "NOMAD_ALLOC_NAME": alloc.name,
        "NOMAD_ALLOC_INDEX": str(_alloc_index(alloc.name)),
        "NOMAD_TASK_NAME": task.name,
        "NOMAD_GROUP_NAME": alloc.task_group,
        "NOMAD_JOB_ID": alloc.job_id,
        "NOMAD_JOB_NAME": job.name if job else alloc.job_id,
        "NOMAD_NAMESPACE": alloc.namespace,
        "NOMAD_REGION": job.region if job else "global",
        "NOMAD_DC": node.datacenter if node else "dc1",
        "NOMAD_CPU_LIMIT": str(task.resources.cpu),
        "NOMAD_MEMORY_LIMIT": str(task.resources.memory_mb),
    }
    if node is not None:
        env["NOMAD_NODE_ID"] = node.id
        env["NOMAD_NODE_NAME"] = node.name
    if task_dir:
        env["NOMAD_TASK_DIR"] = f"{task_dir}/local"
        env["NOMAD_SECRETS_DIR"] = f"{task_dir}/secrets"
        env["NOMAD_ALLOC_DIR"] = f"{task_dir}/../alloc"
    for label, value in (ports or {}).items():
        up = label.upper().replace("-", "_")
        env[f"NOMAD_PORT_{up}"] = str(value)
        env[f"NOMAD_HOST_PORT_{up}"] = str(value)
        env[f"NOMAD_ADDR_{up}"] = f"127.0.0.1:{value}"
    # CSI volume mount paths per alias (the csi_hook's published targets)
    for alias, path in (volumes or {}).items():
        up = alias.upper().replace("-", "_")
        env[f"NOMAD_VOLUME_{up}"] = path
    # job/group/task meta as NOMAD_META_<key> (uppercased)
    metas = {}
    if job is not None:
        metas.update(job.meta or {})
        tg = job.lookup_task_group(alloc.task_group)
        if tg is not None:
            metas.update(tg.meta or {})
    metas.update(task.meta or {})
    for k, v in metas.items():
        env[f"NOMAD_META_{k.upper().replace('-', '_')}"] = str(v)
        env[f"NOMAD_META_{k}"] = str(v)
    # user-declared env wins, after interpolation against the base env
    for k, v in (task.env or {}).items():
        env[k] = interpolate(str(v), env, node, metas)
    return env


_REF_RE = re.compile(r"\$\{([^}]+)\}")


def interpolate(s: str, env: Dict[str, str], node=None,
                meta: Optional[Dict[str, str]] = None) -> str:
    """Resolve ${env.X} / ${meta.X} / ${attr.X} / ${node.X} / ${NOMAD_*}
    references (reference client/taskenv/env.go ReplaceEnv)."""
    def sub(m: re.Match) -> str:
        ref = m.group(1).strip()
        if ref.startswith("env."):
            return env.get(ref[4:], "")
        if ref.startswith("meta."):
            return str((meta or {}).get(ref[5:], ""))
        if node is not None:
            if ref.startswith("attr."):
                return str(node.attributes.get(ref[5:], ""))
            if ref.startswith("node."):
                key = ref[5:]
                return str({
                    "unique.id": node.id, "unique.name": node.name,
                    "datacenter": node.datacenter, "class": node.node_class,
                    "region": "global",
                }.get(key, getattr(node, key, "")))
        if ref in env:
            return env[ref]
        return m.group(0)            # leave unknown refs literal
    return _REF_RE.sub(sub, s)


def _alloc_index(name: str) -> int:
    """'job.group[3]' -> 3 (reference structs AllocIndex)."""
    m = re.search(r"\[(\d+)\]$", name or "")
    return int(m.group(1)) if m else 0
