"""HCL2 expression evaluation: variables, locals, and a function set
(reference: jobspec2/parse.go ParseWithConfig — variable blocks with
type/default, -var/-var-file overrides, locals, and the cty stdlib
function table in jobspec2/functions.go).

The parser (jobspec/hcl.py) leaves `var.x` / `local.y` references and
`fn(...)` calls as Ref/Call nodes and keeps `${...}` text inside
strings; `evaluate()` resolves both across the whole tree before struct
mapping.  Interpolation segments whose root the evaluator does not own
(env., attr., node., meta., NOMAD_*, secret, ...) stay literal — they
belong to the client's taskenv/template layer, same split as the
reference (parse-time cty evaluation vs runtime taskenv.ReplaceEnv).
"""
from __future__ import annotations

import base64
import hashlib
import json
import re
from typing import Any, Dict, List, Optional

from nomad_tpu.jobspec.hcl import Call, HclBlock, HclParseError, Ref

# ------------------------------------------------------------- functions


def _fmt(spec: str, *args: Any) -> str:
    """Go-style format verbs reduced to the common set (%s %d %v %f)."""
    out = []
    i = 0
    ai = 0
    while i < len(spec):
        c = spec[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        if i + 1 < len(spec) and spec[i + 1] == "%":
            out.append("%")
            i += 2
            continue
        m = re.match(r"%[-0-9.]*[sdvfq]", spec[i:])
        if m is None or ai >= len(args):
            out.append(c)
            i += 1
            continue
        verb = m.group(0)[-1]
        a = args[ai]
        ai += 1
        if verb == "d":
            out.append(str(int(a)))
        elif verb == "f":
            out.append(str(float(a)))
        elif verb == "q":
            out.append(json.dumps(str(a)))
        else:
            out.append(_to_str(a))
        i += len(m.group(0))
    return "".join(out)


def _to_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    if isinstance(v, (list, dict)):
        return json.dumps(v)
    return str(v)


FUNCTIONS: Dict[str, Any] = {
    # strings
    "format": _fmt,
    "upper": lambda s: str(s).upper(),
    "lower": lambda s: str(s).lower(),
    "title": lambda s: str(s).title(),
    "trimspace": lambda s: str(s).strip(),
    "trimprefix": lambda s, p: str(s)[len(p):]
    if str(s).startswith(p) else str(s),
    "trimsuffix": lambda s, p: str(s)[:-len(p)]
    if p and str(s).endswith(p) else str(s),
    "replace": lambda s, a, b: str(s).replace(a, b),
    "split": lambda sep, s: str(s).split(sep),
    "join": lambda sep, xs: str(sep).join(_to_str(x) for x in xs),
    "substr": lambda s, off, ln: str(s)[off:off + ln]
    if ln >= 0 else str(s)[off:],
    "indent": lambda n, s: ("\n" + " " * n).join(str(s).split("\n")),
    "chomp": lambda s: re.sub(r"\n+$", "", str(s)),
    # collections
    "concat": lambda *ls: [x for l in ls for x in l],
    "length": lambda x: len(x),
    "contains": lambda xs, v: v in xs,
    "element": lambda xs, i: xs[int(i) % len(xs)],
    "index": lambda xs, v: list(xs).index(v),
    "keys": lambda m: sorted(m.keys()),
    "values": lambda m: [m[k] for k in sorted(m.keys())],
    "lookup": lambda m, k, *d: m.get(k, d[0] if d else None),
    "merge": lambda *ms: {k: v for m in ms for k, v in m.items()},
    "flatten": lambda xs: [y for x in xs
                           for y in (x if isinstance(x, list) else [x])],
    "distinct": lambda xs: list(dict.fromkeys(xs)),
    "compact": lambda xs: [x for x in xs if x not in ("", None)],
    "reverse": lambda xs: list(reversed(xs)),
    "sort": lambda xs: sorted(xs),
    "range": lambda *a: list(range(*(int(x) for x in a))),
    "coalesce": lambda *xs: next(
        (x for x in xs if x not in (None, "")), None),
    "coalescelist": lambda *ls: next((l for l in ls if l), []),
    # numbers
    "abs": lambda x: abs(x),
    "ceil": lambda x: int(-(-x // 1)),
    "floor": lambda x: int(x // 1),
    "min": lambda *xs: min(xs),
    "max": lambda *xs: max(xs),
    "pow": lambda a, b: a ** b,
    "parseint": lambda s, base=10: int(str(s), int(base)),
    # encoding
    "jsonencode": lambda v: json.dumps(v, separators=(",", ":")),
    "jsondecode": lambda s: json.loads(s),
    "base64encode": lambda s: base64.b64encode(
        str(s).encode()).decode(),
    "base64decode": lambda s: base64.b64decode(str(s)).decode(),
    "md5": lambda s: hashlib.md5(str(s).encode()).hexdigest(),
    "sha1": lambda s: hashlib.sha1(str(s).encode()).hexdigest(),
    "sha256": lambda s: hashlib.sha256(str(s).encode()).hexdigest(),
    # type conversion
    "tostring": _to_str,
    "tonumber": lambda s: float(s) if "." in str(s) else int(s),
    "tobool": lambda s: s if isinstance(s, bool)
    else str(s).lower() == "true",
}


# ------------------------------------------------------------ evaluation


class _Scope:
    def __init__(self, variables: Dict[str, Any], locals_: Dict[str, Any]):
        self.variables = variables
        self.locals = locals_

    def resolve(self, name: str, line: int = 0) -> Any:
        root, _, rest = name.partition(".")
        if root == "var":
            if rest not in self.variables:
                raise HclParseError(f"undefined variable {rest!r}", line)
            return self.variables[rest]
        if root == "local":
            if rest not in self.locals:
                raise HclParseError(f"undefined local {rest!r}", line)
            return self.locals[rest]
        raise HclParseError(f"unknown reference {name!r}", line)


_INTERP_RE = re.compile(r"\$\{([^{}]+)\}")
# roots the parse-time evaluator owns; anything else is runtime
_OWNED_ROOT_RE = re.compile(r"^\s*(var\.|local\.|[a-z_][\w]*\s*\()")


def _eval(v: Any, scope: _Scope) -> Any:
    if isinstance(v, Ref):
        return _eval(scope.resolve(v.name, v.line), scope)
    if isinstance(v, Call):
        fn = FUNCTIONS.get(v.name)
        if fn is None:
            raise HclParseError(f"unknown function {v.name!r}", v.line)
        args = [_eval(a, scope) for a in v.args]
        try:
            return fn(*args)
        except HclParseError:
            raise
        except Exception as e:                       # noqa: BLE001
            raise HclParseError(f"{v.name}(...): {e}", v.line)
    if isinstance(v, str):
        return _eval_interp(v, scope)
    if isinstance(v, list):
        return [_eval(x, scope) for x in v]
    if isinstance(v, dict):
        return {k: _eval(x, scope) for k, x in v.items()}
    return v


def _eval_interp(s: str, scope: _Scope) -> Any:
    """Evaluate ${...} segments the evaluator owns; leave runtime
    segments (${env.X}, ${attr.X}, ${NOMAD_*}, ...) literal."""
    segs = list(_INTERP_RE.finditer(s))
    owned = [m for m in segs if _OWNED_ROOT_RE.match(m.group(1))]
    if not owned:
        return s
    # whole-string single segment keeps its native type (HCL semantics)
    if len(segs) == 1 and segs[0].group(0) == s:
        return _eval_segment(segs[0].group(1), scope)

    def sub(m: "re.Match") -> str:
        if not _OWNED_ROOT_RE.match(m.group(1)):
            return m.group(0)
        return _to_str(_eval_segment(m.group(1), scope))
    return _INTERP_RE.sub(sub, s)


def _eval_segment(text: str, scope: _Scope) -> Any:
    from nomad_tpu.jobspec.hcl import _Parser, _tokenize
    p = _Parser(_tokenize(text.strip()))
    val = p.parse_value()
    return _eval(val, scope)


def _coerce(value: Any, type_: str, name: str) -> Any:
    if type_ in ("", "any", None):
        return value
    try:
        if type_ == "string":
            return _to_str(value)
        if type_ == "number":
            return value if isinstance(value, (int, float)) \
                else (float(value) if "." in str(value) else int(value))
        if type_ == "bool":
            return value if isinstance(value, bool) \
                else str(value).lower() == "true"
        if type_.startswith("list"):
            return list(value) if not isinstance(value, str) \
                else json.loads(value)
        if type_.startswith("map") or type_.startswith("object"):
            return dict(value) if not isinstance(value, str) \
                else json.loads(value)
    except Exception as e:                           # noqa: BLE001
        raise HclParseError(
            f"variable {name!r}: cannot convert to {type_}: {e}", 0)
    return value


def evaluate(root: HclBlock,
             var_values: Optional[Dict[str, Any]] = None) -> None:
    """Resolve variable/locals blocks and every Ref/Call/interpolation
    in `root`, in place.  `var_values`: CLI/API overrides (-var)."""
    overrides = dict(var_values or {})
    variables: Dict[str, Any] = {}
    for vb in root.all("variable"):
        name = vb.labels[0] if vb.labels else ""
        if not name:
            raise HclParseError("variable block needs a name", vb.line)
        type_ = vb.get("type", "")
        if isinstance(type_, Call):      # `type = list(string)`
            type_ = type_.name
        elif isinstance(type_, Ref):
            type_ = type_.name
        if name in overrides:
            variables[name] = _coerce(overrides.pop(name), str(type_),
                                      name)
        elif "default" in vb.attrs:
            variables[name] = vb.attrs["default"]
        else:
            raise HclParseError(
                f"variable {name!r} has no value (set -var {name}=...)",
                vb.line)
    if overrides:
        raise HclParseError(
            f"undeclared variables: {sorted(overrides)}", 0)

    scope = _Scope(variables, {})
    # defaults may themselves use functions/other vars
    for name in list(variables):
        variables[name] = _eval(variables[name], scope)

    # locals: ordered evaluation with dependency retries (HCL allows
    # any order; a small fixpoint pass covers chains without a graph)
    pending: List[tuple] = []
    for lb in root.all("locals"):
        pending.extend(lb.attrs.items())
    for _ in range(len(pending) + 1):
        if not pending:
            break
        still = []
        for name, raw in pending:
            try:
                scope.locals[name] = _eval(raw, scope)
            except HclParseError:
                still.append((name, raw))
        if len(still) == len(pending):
            name, raw = still[0]
            scope.locals[name] = _eval(raw, scope)   # raise for real
        pending = still

    root.blocks = [b for b in root.blocks
                   if b.type not in ("variable", "locals")]
    _eval_block(root, scope)


def _eval_block(block: HclBlock, scope: _Scope) -> None:
    block.labels = [_to_str(_eval(l, scope)) for l in block.labels]
    for k in list(block.attrs):
        block.attrs[k] = _eval(block.attrs[k], scope)
    for child in block.blocks:
        _eval_block(child, scope)
