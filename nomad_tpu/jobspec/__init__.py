"""Job specification parsing (reference: jobspec/ HCL1 + jobspec2/ HCL2).

`nomad_tpu.jobspec.hcl` — a hand-rolled HCL2-subset parser (blocks,
attributes, strings/numbers/bools/lists/objects, comments, heredocs).
`nomad_tpu.jobspec.parse` — HCL AST -> Job structs, the jobspec2/parse.go
equivalent, plus JSON jobspecs.
"""
from nomad_tpu.jobspec.hcl import HclBlock, HclParseError, parse_hcl
from nomad_tpu.jobspec.parse import parse_job, parse_job_file, parse_json_job

__all__ = ["HclBlock", "HclParseError", "parse_hcl", "parse_job",
           "parse_job_file", "parse_json_job"]
