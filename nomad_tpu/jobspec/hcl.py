"""Minimal HCL2-subset parser.

Covers the jobspec language surface (reference: jobspec2/ via
hashicorp/hcl): nested blocks with string labels, attributes with
string/number/bool/list/object values, line (`#`, `//`) and block
(`/* */`) comments, heredocs (`<<EOF` / `<<-EOF`), and `${...}`
interpolations preserved as literal text in strings (the runtime
interpolates them per-task like the reference's taskenv).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple


class HclParseError(ValueError):
    def __init__(self, msg: str, line: int):
        super().__init__(f"line {line}: {msg}")
        self.line = line


class Ref:
    """An unresolved expression reference (`var.x`, `local.y`) — the
    evaluator (jobspec/expr.py) resolves it; reaching struct mapping
    unresolved is an error."""

    __slots__ = ("name", "line")

    def __init__(self, name: str, line: int = 0):
        self.name = name
        self.line = line

    def __repr__(self):
        return f"Ref({self.name!r})"


class Call:
    """An unresolved function call (`format("x-%s", var.y)`)."""

    __slots__ = ("name", "args", "line")

    def __init__(self, name: str, args: List[Any], line: int = 0):
        self.name = name
        self.args = args
        self.line = line

    def __repr__(self):
        return f"Call({self.name!r}, {self.args!r})"


class HclBlock:
    """A block: `type "label1" "label2" { attrs + child blocks }`."""

    __slots__ = ("type", "labels", "attrs", "blocks", "line")

    def __init__(self, type_: str, labels: List[str], line: int = 0):
        self.type = type_
        self.labels = labels
        self.attrs: Dict[str, Any] = {}
        self.blocks: List["HclBlock"] = []
        self.line = line

    def get(self, key: str, default: Any = None) -> Any:
        return self.attrs.get(key, default)

    def first(self, type_: str) -> Optional["HclBlock"]:
        for b in self.blocks:
            if b.type == type_:
                return b
        return None

    def all(self, type_: str) -> List["HclBlock"]:
        return [b for b in self.blocks if b.type == type_]

    def __repr__(self):
        return f"HclBlock({self.type!r}, {self.labels!r})"


_TOKEN_RE = re.compile(r"""
    (?P<ws>[ \t\r]+)
  | (?P<comment>\#[^\n]*|//[^\n]*)
  | (?P<bcomment>/\*.*?\*/)
  | (?P<heredoc><<-?(?P<hd_tag>\w+)\n)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?(?![\w.]))
  | (?P<ident>[A-Za-z_][\w.-]*)
  | (?P<punct>[{}\[\]=,:()\n])
""", re.X | re.S)


def _tokenize(src: str) -> List[Tuple[str, Any, int]]:
    tokens: List[Tuple[str, Any, int]] = []
    pos, line = 0, 1
    n = len(src)
    while pos < n:
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise HclParseError(f"unexpected character {src[pos]!r}", line)
        kind = m.lastgroup
        text = m.group(0)
        if kind == "heredoc":
            # scan to the terminator line
            tag = m.group("hd_tag")
            indent_strip = text.startswith("<<-")
            line += 1
            end_re = re.compile(rf"^[ \t]*{re.escape(tag)}[ \t]*$", re.M)
            em = end_re.search(src, m.end())
            if em is None:
                raise HclParseError(f"heredoc {tag} not terminated", line)
            body = src[m.end():em.start()]
            if indent_strip:
                body = "\n".join(l.lstrip() for l in body.split("\n"))
            if body.endswith("\n"):
                body = body[:-1]
            tokens.append(("string", body, line))
            line += body.count("\n") + 1
            pos = em.end()
            continue
        pos = m.end()
        if kind in ("ws", "comment"):
            continue
        if kind == "bcomment":
            line += text.count("\n")
            continue
        if kind == "punct" and text == "\n":
            tokens.append(("nl", "\n", line))
            line += 1
            continue
        if kind == "string":
            val = _unescape(text[1:-1])
            tokens.append(("string", val, line))
            line += text.count("\n")
        elif kind == "number":
            tokens.append(("number",
                           float(text) if "." in text else int(text), line))
        elif kind == "ident":
            tokens.append(("ident", text, line))
        else:
            tokens.append(("punct", text, line))
    tokens.append(("eof", None, line))
    return tokens


def _unescape(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\",
                        "r": "\r"}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


class _Parser:
    def __init__(self, tokens):
        self.tokens = tokens
        self.i = 0

    def peek(self, skip_nl: bool = True):
        j = self.i
        while skip_nl and self.tokens[j][0] == "nl":
            j += 1
        return self.tokens[j]

    def next(self, skip_nl: bool = True):
        while skip_nl and self.tokens[self.i][0] == "nl":
            self.i += 1
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect(self, kind: str, value=None):
        tok = self.next()
        if tok[0] != kind or (value is not None and tok[1] != value):
            raise HclParseError(
                f"expected {value or kind}, got {tok[1]!r}", tok[2])
        return tok

    # ---- grammar

    def parse_body(self, block: HclBlock, top: bool = False) -> None:
        while True:
            kind, val, line = self.peek()
            if kind == "eof":
                if not top:
                    raise HclParseError("unexpected EOF in block", line)
                return
            if kind == "punct" and val == "}":
                if top:
                    raise HclParseError("unexpected '}'", line)
                self.next()
                return
            if kind not in ("ident", "string"):
                raise HclParseError(f"expected identifier, got {val!r}",
                                    line)
            self.next()
            name = val
            nkind, nval, nline = self.peek(skip_nl=False)
            # skip non-newline lookahead
            if nkind == "punct" and nval == "=":
                self.next()
                block.attrs[name] = self.parse_value()
            else:
                # block: labels then {
                labels = []
                while True:
                    k2, v2, l2 = self.peek()
                    if k2 == "string" or k2 == "ident" and v2 != "{":
                        if k2 == "punct":
                            break
                        labels.append(str(v2))
                        self.next()
                    else:
                        break
                    if len(labels) > 8:
                        raise HclParseError("too many block labels", l2)
                self.expect("punct", "{")
                child = HclBlock(name, labels, line)
                self.parse_body(child)
                block.blocks.append(child)

    def parse_value(self):
        kind, val, line = self.next()
        if kind in ("string", "number"):
            return val
        if kind == "ident":
            if val == "true":
                return True
            if val == "false":
                return False
            if val == "null":
                return None
            nk, nv, _nl = self.peek(skip_nl=False)
            if nk == "punct" and nv == "(":
                # function call: format("x-%s", var.y)
                self.next()
                args = []
                while True:
                    k2, v2, _l2 = self.peek()
                    if k2 == "punct" and v2 == ")":
                        self.next()
                        break
                    args.append(self.parse_value())
                    k3, v3, _l3 = self.peek()
                    if k3 == "punct" and v3 == ",":
                        self.next()
                return Call(val, args, line)
            if val.split(".", 1)[0] in ("var", "local"):
                return Ref(val, line)        # resolved by jobspec/expr.py
            return val                       # bare identifier -> string
        if kind == "punct" and val == "[":
            items = []
            while True:
                k2, v2, l2 = self.peek()
                if k2 == "punct" and v2 == "]":
                    self.next()
                    return items
                items.append(self.parse_value())
                k3, v3, l3 = self.peek()
                if k3 == "punct" and v3 == ",":
                    self.next()
        if kind == "punct" and val == "{":
            obj = {}
            while True:
                k2, v2, l2 = self.peek()
                if k2 == "punct" and v2 == "}":
                    self.next()
                    return obj
                if k2 not in ("ident", "string"):
                    raise HclParseError(f"expected key, got {v2!r}", l2)
                self.next()
                k3, v3, l3 = self.peek()
                if k3 == "punct" and v3 in ("=", ":"):
                    self.next()
                obj[v2] = self.parse_value()
                k4, v4, l4 = self.peek()
                if k4 == "punct" and v4 == ",":
                    self.next()
        raise HclParseError(f"unexpected value token {val!r}", line)


def parse_hcl(src: str) -> HclBlock:
    """Parse HCL source into a root pseudo-block."""
    root = HclBlock("__root__", [])
    p = _Parser(_tokenize(src))
    p.parse_body(root, top=True)
    return root
