"""HCL jobspec -> Job structs (reference: jobspec2/parse.go mapping
HCL2 to api.Job; block/attribute names follow the public jobspec
language documented by the reference's website/).

Also accepts JSON jobspecs (`parse_json_job`) — a dict in the same wire
format the HTTP API uses.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from nomad_tpu.jobspec.hcl import HclBlock, HclParseError, parse_hcl
from nomad_tpu.structs import (
    Affinity,
    Constraint,
    DispatchPayloadConfig,
    EphemeralDisk,
    Job,
    MigrateStrategy,
    Multiregion,
    MultiregionRegion,
    MultiregionStrategy,
    NetworkPort,
    NetworkResource,
    PeriodicConfig,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
    UpdateStrategy,
)
from nomad_tpu.structs import DeviceRequest
from nomad_tpu.structs.job import (
    Lifecycle,
    ParameterizedJobConfig,
    Service,
    VolumeRequest,
)

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h|d)")
_DUR_UNITS = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
              "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration(val: Any, default: float = 0.0) -> float:
    """Go-style duration string ("90s", "1h30m") -> seconds."""
    if val is None:
        return default
    if isinstance(val, (int, float)):
        return float(val)
    total, matched = 0.0, False
    for m in _DUR_RE.finditer(str(val)):
        total += float(m.group(1)) * _DUR_UNITS[m.group(2)]
        matched = True
    if not matched:
        raise HclParseError(f"invalid duration {val!r}", 0)
    return total


def parse_job_file(path: str, var_values: Optional[Dict[str, Any]] = None
                   ) -> Job:
    with open(path) as fh:
        return parse_job(fh.read(), var_values)


def parse_job(src: str, var_values: Optional[Dict[str, Any]] = None) -> Job:
    """Parse an HCL jobspec into a canonicalized Job.  `var_values`
    overrides `variable` block defaults (CLI -var / API Variables;
    reference jobspec2/parse.go ParseWithConfig)."""
    from nomad_tpu.jobspec.expr import evaluate
    root = parse_hcl(src)
    evaluate(root, var_values)
    jb = root.first("job")
    if jb is None:
        raise HclParseError("no 'job' block found", 0)
    return _job_from_block(jb)


def parse_json_job(data: dict) -> Job:
    from nomad_tpu.api.codec import from_wire
    job = from_wire(Job, data.get("Job") or data.get("job") or data)
    job.canonicalize()
    return job


# ---------------------------------------------------------------- blocks

def _job_from_block(b: HclBlock) -> Job:
    job = Job(
        id=b.labels[0] if b.labels else b.get("id", ""),
        name=b.get("name", b.labels[0] if b.labels else ""),
        type=b.get("type", "service"),
        region=b.get("region", "global"),
        namespace=b.get("namespace", "default"),
        priority=int(b.get("priority", 50)),
        all_at_once=bool(b.get("all_at_once", False)),
        datacenters=list(b.get("datacenters", ["dc1"])),
    )
    job.constraints = [_constraint(c) for c in b.all("constraint")]
    job.affinities = [_affinity(a) for a in b.all("affinity")]
    job.spreads = [_spread(s) for s in b.all("spread")]
    if b.first("update") is not None:
        job.update = _update(b.first("update"))
    if b.first("multiregion") is not None:
        job.multiregion = _multiregion(b.first("multiregion"))
    if b.first("periodic") is not None:
        job.periodic = _periodic(b.first("periodic"))
    if b.first("parameterized") is not None:
        job.parameterized = _parameterized(b.first("parameterized"))
    if b.first("meta") is not None:
        job.meta = {k: str(v) for k, v in b.first("meta").attrs.items()}
    for g in b.all("group"):
        job.task_groups.append(_group(g))
    # single top-level task sugar (HCL1 compat): job { task "t" {} }
    if not job.task_groups and b.all("task"):
        tg = TaskGroup(name=job.id or "group")
        for t in b.all("task"):
            tg.tasks.append(_task(t))
        job.task_groups = [tg]
    job.canonicalize()
    return job


def _group(b: HclBlock) -> TaskGroup:
    tg = TaskGroup(
        name=b.labels[0] if b.labels else "group",
        count=int(b.get("count", 1)),
    )
    tg.constraints = [_constraint(c) for c in b.all("constraint")]
    tg.affinities = [_affinity(a) for a in b.all("affinity")]
    tg.spreads = [_spread(s) for s in b.all("spread")]
    if b.first("restart") is not None:
        tg.restart_policy = _restart(b.first("restart"))
    if b.first("reschedule") is not None:
        tg.reschedule_policy = _reschedule(b.first("reschedule"))
    if b.first("migrate") is not None:
        tg.migrate = _migrate(b.first("migrate"))
    if b.first("update") is not None:
        tg.update = _update(b.first("update"))
    if b.first("ephemeral_disk") is not None:
        ed = b.first("ephemeral_disk")
        tg.ephemeral_disk = EphemeralDisk(
            sticky=bool(ed.get("sticky", False)),
            size_mb=int(ed.get("size", 300)),
            migrate=bool(ed.get("migrate", False)))
    for n in b.all("network"):
        tg.networks.append(_network(n))
    for s in b.all("service"):
        tg.services.append(_service(s))
    for v in b.all("volume"):
        name = v.labels[0] if v.labels else "vol"
        tg.volumes[name] = VolumeRequest(
            name=name, type=v.get("type", "host"),
            source=v.get("source", ""),
            read_only=bool(v.get("read_only", False)),
            access_mode=v.get("access_mode", ""),
            attachment_mode=v.get("attachment_mode", ""),
            per_alloc=bool(v.get("per_alloc", False)))
    if b.get("max_client_disconnect") is not None:
        tg.max_client_disconnect_s = parse_duration(
            b.get("max_client_disconnect"))
    if b.get("stop_after_client_disconnect") is not None:
        tg.stop_after_client_disconnect_s = parse_duration(
            b.get("stop_after_client_disconnect"))
    if b.first("meta") is not None:
        tg.meta = {k: str(v) for k, v in b.first("meta").attrs.items()}
    for t in b.all("task"):
        tg.tasks.append(_task(t))
    return tg


def _task(b: HclBlock) -> Task:
    t = Task(
        name=b.labels[0] if b.labels else "task",
        driver=b.get("driver", "mock"),
        kill_timeout_s=parse_duration(b.get("kill_timeout"), 5.0),
        leader=bool(b.get("leader", False)),
    )
    cfg = b.first("config")
    if cfg is not None:
        t.config = _block_to_dict(cfg)
    env = b.first("env")
    if env is not None:
        t.env = {k: str(v) for k, v in env.attrs.items()}
    res = b.first("resources")
    if res is not None:
        t.resources = _resources(res)
    t.constraints = [_constraint(c) for c in b.all("constraint")]
    t.affinities = [_affinity(a) for a in b.all("affinity")]
    lc = b.first("lifecycle")
    if lc is not None:
        t.lifecycle = Lifecycle(hook=lc.get("hook", ""),
                                sidecar=bool(lc.get("sidecar", False)))
    for s in b.all("service"):
        t.services.append(_service(s))
    if b.first("meta") is not None:
        t.meta = {k: str(v) for k, v in b.first("meta").attrs.items()}
    for a in b.all("artifact"):
        t.artifacts.append(_block_to_dict(a))
    for tmpl in b.all("template"):
        t.templates.append(_block_to_dict(tmpl))
    v = b.first("vault")
    if v is not None:
        t.vault = _block_to_dict(v)
    dp = b.first("dispatch_payload")
    if dp is not None:
        t.dispatch_payload = DispatchPayloadConfig(file=dp.get("file", ""))
    return t


def _resources(b: HclBlock) -> Resources:
    r = Resources(
        cpu=int(b.get("cpu", 100)),
        cores=int(b.get("cores", 0)),
        memory_mb=int(b.get("memory", 300)),
        memory_max_mb=int(b.get("memory_max", 0)),
        disk_mb=int(b.get("disk", 0)),
    )
    for n in b.all("network"):
        r.networks.append(_network(n))
    for d in b.all("device"):
        r.devices.append(DeviceRequest(
            name=d.labels[0] if d.labels else "",
            count=int(d.get("count", 1)),
            constraints=[_constraint(c) for c in d.all("constraint")],
            affinities=[_affinity(a) for a in d.all("affinity")]))
    return r


def _network(b: HclBlock) -> NetworkResource:
    net = NetworkResource(mode=b.get("mode", "host"),
                          mbits=int(b.get("mbits", 0)))
    for p in b.all("port"):
        label = p.labels[0] if p.labels else ""
        port = NetworkPort(label=label,
                           value=int(p.get("static", 0)),
                           to=int(p.get("to", 0)),
                           host_network=p.get("host_network", "default"))
        if port.value:
            net.reserved_ports.append(port)
        else:
            net.dynamic_ports.append(port)
    return net


def _service(b: HclBlock) -> Service:
    svc = Service(
        name=b.labels[0] if b.labels else b.get("name", ""),
        provider=b.get("provider", "consul"),
        port_label=str(b.get("port", "")),
        tags=[str(x) for x in b.get("tags", [])],
    )
    for c in b.all("check"):
        svc.checks.append(_block_to_dict(c))
    return svc


def _constraint(b: HclBlock) -> Constraint:
    if b.get("distinct_hosts") is not None:
        return Constraint(operand="distinct_hosts")
    if b.get("distinct_property") is not None:
        return Constraint(ltarget=str(b.get("distinct_property")),
                          rtarget=str(b.get("value", "")),
                          operand="distinct_property")
    return Constraint(
        ltarget=str(b.get("attribute", "")),
        rtarget=str(b.get("value", "")),
        operand=str(b.get("operator", b.get("op", "="))),
    )


def _affinity(b: HclBlock) -> Affinity:
    return Affinity(
        ltarget=str(b.get("attribute", "")),
        rtarget=str(b.get("value", "")),
        operand=str(b.get("operator", b.get("op", "="))),
        weight=int(b.get("weight", 50)),
    )


def _spread(b: HclBlock) -> Spread:
    targets = tuple(
        SpreadTarget(value=str(t.labels[0] if t.labels
                               else t.get("value", "")),
                     percent=int(t.get("percent", 0)))
        for t in b.all("target"))
    return Spread(attribute=str(b.get("attribute", "")),
                  weight=int(b.get("weight", 50)), targets=targets)


def _update(b: HclBlock) -> UpdateStrategy:
    return UpdateStrategy(
        stagger_s=parse_duration(b.get("stagger"), 30.0),
        max_parallel=int(b.get("max_parallel", 1)),
        health_check=b.get("health_check", "checks"),
        min_healthy_time_s=parse_duration(b.get("min_healthy_time"), 10.0),
        healthy_deadline_s=parse_duration(b.get("healthy_deadline"), 300.0),
        progress_deadline_s=parse_duration(b.get("progress_deadline"),
                                           600.0),
        auto_revert=bool(b.get("auto_revert", False)),
        auto_promote=bool(b.get("auto_promote", False)),
        canary=int(b.get("canary", 0)),
    )


def _multiregion(b: HclBlock) -> Multiregion:
    """multiregion { strategy { max_parallel, on_failure }
    region "west" { count, datacenters } ... } (reference
    jobspec2 Multiregion)."""
    mr = Multiregion()
    st = b.first("strategy")
    if st is not None:
        mr.strategy = MultiregionStrategy(
            max_parallel=int(st.get("max_parallel", 1)),
            on_failure=st.get("on_failure", "fail_all"))
    for rb in b.all("region"):
        name = rb.labels[0] if rb.labels else rb.get("name", "")
        if not name:
            raise HclParseError("multiregion region needs a name", 0)
        count = rb.get("count")
        region = MultiregionRegion(
            name=name,
            count=int(count) if count is not None else None,
            datacenters=list(rb.get("datacenters", [])))
        if rb.first("meta") is not None:
            region.meta = {k: str(v) for k, v in
                           rb.first("meta").attrs.items()}
        mr.regions.append(region)
    if not mr.regions:
        raise HclParseError("multiregion block needs at least one "
                            "region", 0)
    return mr


def _periodic(b: HclBlock) -> PeriodicConfig:
    return PeriodicConfig(
        enabled=bool(b.get("enabled", True)),
        spec=b.get("cron", b.get("crons", "")),
        prohibit_overlap=bool(b.get("prohibit_overlap", False)),
        timezone=b.get("time_zone", "UTC"),
    )


def _parameterized(b: HclBlock) -> ParameterizedJobConfig:
    return ParameterizedJobConfig(
        payload=b.get("payload", "optional"),
        meta_required=[str(x) for x in b.get("meta_required", [])],
        meta_optional=[str(x) for x in b.get("meta_optional", [])],
    )


def _restart(b: HclBlock) -> RestartPolicy:
    return RestartPolicy(
        attempts=int(b.get("attempts", 2)),
        interval_s=parse_duration(b.get("interval"), 1800.0),
        delay_s=parse_duration(b.get("delay"), 15.0),
        mode=b.get("mode", "fail"),
    )


def _reschedule(b: HclBlock) -> ReschedulePolicy:
    return ReschedulePolicy(
        attempts=int(b.get("attempts", 0)),
        interval_s=parse_duration(b.get("interval"), 0.0),
        delay_s=parse_duration(b.get("delay"), 30.0),
        delay_function=b.get("delay_function", "exponential"),
        max_delay_s=parse_duration(b.get("max_delay"), 3600.0),
        unlimited=bool(b.get("unlimited", True)),
    )


def _migrate(b: HclBlock) -> MigrateStrategy:
    return MigrateStrategy(
        max_parallel=int(b.get("max_parallel", 1)),
        health_check=b.get("health_check", "checks"),
        min_healthy_time_s=parse_duration(b.get("min_healthy_time"), 10.0),
        healthy_deadline_s=parse_duration(b.get("healthy_deadline"), 300.0),
    )


def _block_to_dict(b: HclBlock) -> Dict[str, Any]:
    out: Dict[str, Any] = dict(b.attrs)
    for child in b.blocks:
        d = _block_to_dict(child)
        if child.labels:
            out.setdefault(child.type, {})[child.labels[0]] = d
        else:
            out.setdefault(child.type, []).append(d)
    return out
