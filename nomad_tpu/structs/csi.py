"""CSI volume + plugin data model (reference nomad/structs/csi.go).

The claim lifecycle mirrors the reference's: a claim is taken when an
allocation using the volume is committed, moves through the release
states as the volume watcher unwinds it (unpublish -> node detach ->
controller detach -> released), and disappears from the claim maps when
released.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# access modes (csi.go CSIVolumeAccessMode*)
ACCESS_UNKNOWN = ""
ACCESS_SINGLE_READER = "single-node-reader-only"
ACCESS_SINGLE_WRITER = "single-node-writer"
ACCESS_MULTI_READER = "multi-node-reader-only"
ACCESS_MULTI_SINGLE_WRITER = "multi-node-single-writer"
ACCESS_MULTI_WRITER = "multi-node-multi-writer"

WRITE_MODES = (ACCESS_SINGLE_WRITER, ACCESS_MULTI_SINGLE_WRITER,
               ACCESS_MULTI_WRITER)

# attachment modes
ATTACH_UNKNOWN = ""
ATTACH_FILE_SYSTEM = "file-system"
ATTACH_BLOCK_DEVICE = "block-device"

# claim modes
CLAIM_READ = "read"
CLAIM_WRITE = "write"

# claim states (csi.go CSIVolumeClaimState*)
CLAIM_STATE_TAKEN = "taken"
CLAIM_STATE_NODE_DETACHED = "node-detached"
CLAIM_STATE_CONTROLLER_DETACHED = "controller-detached"
CLAIM_STATE_READY_TO_FREE = "ready-to-free"
CLAIM_STATE_UNPUBLISHING = "unpublishing"


@dataclass
class CSIVolumeClaim:
    """One allocation's claim on a volume (csi.go CSIVolumeClaim)."""
    alloc_id: str = ""
    node_id: str = ""
    mode: str = CLAIM_READ
    state: str = CLAIM_STATE_TAKEN


@dataclass
class CSIVolume:
    """Reference structs.CSIVolume (csi.go:300+), server-side record."""
    id: str = ""
    namespace: str = "default"
    name: str = ""
    external_id: str = ""
    plugin_id: str = ""
    provider: str = ""
    access_mode: str = ACCESS_UNKNOWN        # current mode (set by claims)
    attachment_mode: str = ATTACH_UNKNOWN
    requested_capabilities: List[Dict[str, str]] = field(default_factory=list)
    topologies: List[Dict[str, str]] = field(default_factory=list)
    capacity_min: int = 0
    capacity_max: int = 0
    # claims: alloc_id -> CSIVolumeClaim
    read_claims: Dict[str, CSIVolumeClaim] = field(default_factory=dict)
    write_claims: Dict[str, CSIVolumeClaim] = field(default_factory=dict)
    past_claims: Dict[str, CSIVolumeClaim] = field(default_factory=dict)
    schedulable: bool = True
    resource_exhausted: float = 0.0          # unix ts; 0 = not exhausted
    controller_required: bool = False
    controllers_healthy: int = 0
    controllers_expected: int = 0
    nodes_healthy: int = 0
    nodes_expected: int = 0
    create_index: int = 0
    modify_index: int = 0

    # --------------------------------------------------- schedulability
    # csi.go:430-505

    def read_schedulable(self) -> bool:
        return self.schedulable and self.resource_exhausted == 0.0

    def write_schedulable(self) -> bool:
        if not (self.schedulable and self.resource_exhausted == 0.0):
            return False
        if self.access_mode in WRITE_MODES:
            return True
        if self.access_mode == ACCESS_UNKNOWN:
            return any(c.get("access_mode") in WRITE_MODES
                       for c in self.requested_capabilities) or \
                not self.requested_capabilities
        return False

    def has_free_read_claims(self) -> bool:
        if self.access_mode == ACCESS_SINGLE_READER:
            return len(self.read_claims) == 0
        if self.access_mode == ACCESS_SINGLE_WRITER:
            return not self.read_claims and not self.write_claims
        return True    # unknown or multi-node modes

    def has_free_write_claims(self) -> bool:
        if self.access_mode in (ACCESS_SINGLE_WRITER,
                                ACCESS_MULTI_SINGLE_WRITER):
            return len(self.write_claims) == 0
        if self.access_mode in (ACCESS_MULTI_WRITER, ACCESS_UNKNOWN):
            return True
        return False   # reader modes never have free write claims

    def in_use(self) -> bool:
        return bool(self.read_claims or self.write_claims)

    # --------------------------------------------------------- claims

    def claim(self, c: CSIVolumeClaim) -> None:
        """Take a claim (csi.go ClaimRead/ClaimWrite): sets the access
        mode on first claim of an unknown-mode volume."""
        if c.mode == CLAIM_WRITE:
            if self.access_mode == ACCESS_UNKNOWN:
                self.access_mode = ACCESS_SINGLE_WRITER \
                    if not self.requested_capabilities else \
                    next((cap["access_mode"] for cap in
                          self.requested_capabilities
                          if cap.get("access_mode") in WRITE_MODES),
                         ACCESS_SINGLE_WRITER)
            self.write_claims[c.alloc_id] = c
            self.read_claims.pop(c.alloc_id, None)
        else:
            if self.access_mode == ACCESS_UNKNOWN:
                self.access_mode = ACCESS_MULTI_READER \
                    if not self.requested_capabilities else \
                    self.requested_capabilities[0].get(
                        "access_mode", ACCESS_MULTI_READER)
            self.read_claims[c.alloc_id] = c
        self.past_claims.pop(c.alloc_id, None)

    def release(self, alloc_id: str) -> None:
        """Fully release a claim; when the last claim drops, the volume
        returns to unknown access mode (csi.go ReleaseClaims)."""
        c = self.read_claims.pop(alloc_id, None) or \
            self.write_claims.pop(alloc_id, None)
        if c is not None:
            c.state = CLAIM_STATE_READY_TO_FREE
            self.past_claims[alloc_id] = c
        if not self.in_use():
            self.access_mode = ACCESS_UNKNOWN

    def stub(self) -> dict:
        return {
            "ID": self.id, "Namespace": self.namespace, "Name": self.name,
            "ExternalID": self.external_id, "PluginID": self.plugin_id,
            "Provider": self.provider, "AccessMode": self.access_mode,
            "AttachmentMode": self.attachment_mode,
            "CurrentReaders": len(self.read_claims),
            "CurrentWriters": len(self.write_claims),
            "Schedulable": self.schedulable,
            "ControllersHealthy": self.controllers_healthy,
            "ControllersExpected": self.controllers_expected,
            "NodesHealthy": self.nodes_healthy,
            "NodesExpected": self.nodes_expected,
            "CreateIndex": self.create_index,
            "ModifyIndex": self.modify_index,
        }


@dataclass
class CSIPlugin:
    """Aggregated plugin health, derived from node fingerprints
    (reference structs.CSIPlugin, maintained by state store node upserts).
    """
    id: str = ""
    provider: str = ""
    version: str = ""
    controller_required: bool = False
    # node_id -> {"healthy": bool, "max_volumes": int}
    controllers: Dict[str, dict] = field(default_factory=dict)
    nodes: Dict[str, dict] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    @property
    def controllers_healthy(self) -> int:
        return sum(1 for c in self.controllers.values() if c.get("healthy"))

    @property
    def nodes_healthy(self) -> int:
        return sum(1 for n in self.nodes.values() if n.get("healthy"))

    def stub(self) -> dict:
        return {
            "ID": self.id, "Provider": self.provider, "Version": self.version,
            "ControllerRequired": self.controller_required,
            "ControllersHealthy": self.controllers_healthy,
            "ControllersExpected": len(self.controllers),
            "NodesHealthy": self.nodes_healthy,
            "NodesExpected": len(self.nodes),
            "CreateIndex": self.create_index,
            "ModifyIndex": self.modify_index,
        }
