"""Plan model (reference: nomad/structs/structs.go Plan:11118, PlanResult:11375,
PlanAnnotations/DesiredUpdates).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.structs.alloc import Allocation, AllocDesiredStatus, AllocClientStatus
from nomad_tpu.structs.job import Job
from nomad_tpu.utils import generate_uuid


@dataclass
class DesiredUpdates:
    """Per-task-group diff annotation for dry-run `plan` output."""
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0


@dataclass
class PlanAnnotations:
    desired_tg_updates: Dict[str, DesiredUpdates] = field(default_factory=dict)
    preempted_allocs: List[dict] = field(default_factory=list)


@dataclass
class Plan:
    """The scheduler's proposed state mutation, submitted to the leader's
    plan applier for optimistic-concurrency validation."""
    eval_id: str = ""
    eval_token: str = ""
    # unique per submission; the applied-results entry carries it so a
    # raft log replay after leader failover commits each plan at most once
    plan_id: str = field(default_factory=generate_uuid)
    priority: int = 50
    job: Optional[Job] = None
    all_at_once: bool = False
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)      # stops/evicts
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)  # placements
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional[object] = None          # Deployment to upsert
    deployment_updates: List[dict] = field(default_factory=list)
    annotations: Optional[PlanAnnotations] = None
    snapshot_index: int = 0
    # in-flight overlay tickets of the PlacementEngine covering this
    # plan's placements; the applier releases them atomically with the
    # commit (closing the committed+overlaid double-count window)
    engine_tickets: List[int] = field(default_factory=list)

    def append_stopped_alloc(self, alloc: Allocation, desired_desc: str,
                             client_status: str = "", followup_eval_id: str = "") -> None:
        """Reference Plan.AppendStoppedAlloc."""
        a = alloc.copy()
        a.desired_status = AllocDesiredStatus.STOP
        a.desired_description = desired_desc
        if client_status:
            a.client_status = client_status
        if followup_eval_id:
            a.followup_eval_id = followup_eval_id
        a.job = None  # stripped for plan size; restored from state on apply
        self.node_update.setdefault(alloc.node_id, []).append(a)

    def append_alloc(self, alloc: Allocation, job: Optional[Job] = None) -> None:
        """Reference Plan.AppendAlloc: the job is attached only when the
        caller passes an updated one (plan normalization); otherwise the
        alloc keeps the job it already carries."""
        if job is not None:
            alloc.job = job
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_preempted_alloc(self, alloc: Allocation, preempting_alloc_id: str) -> None:
        a = alloc.copy()
        a.desired_status = AllocDesiredStatus.EVICT
        a.preempted_by_allocation = preempting_alloc_id
        a.desired_description = (f"Preempted by alloc ID {preempting_alloc_id}")
        a.job = None
        self.node_preemptions.setdefault(alloc.node_id, []).append(a)

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and not self.deployment and not self.deployment_updates
                and not self.node_preemptions)


@dataclass
class PlanResult:
    """What the plan applier actually committed (possibly a partial commit)."""
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional[object] = None
    deployment_updates: List[dict] = field(default_factory=list)
    rejected_nodes: List[str] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0
    # set when placements were dropped by the namespace quota check:
    # the QuotaSpec name that was exhausted.  The scheduler blocks the
    # eval keyed on this quota instead of burning plan retries — an
    # over-quota placement only becomes feasible when the quota is
    # raised or live allocs stop.
    quota_limit_reached: str = ""

    def full_commit(self, plan: Plan) -> tuple:
        """Reference PlanResult.FullCommit: (full, expected, actual) placements."""
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual, expected, actual
