"""Deployment model (reference: nomad/structs/structs.go Deployment/
DeploymentState, used by scheduler/reconcile.go and deploymentwatcher/).
"""
from __future__ import annotations

import uuid

from nomad_tpu.utils import generate_uuid
from dataclasses import dataclass, field
from typing import Dict, Optional


class DeploymentStatus:
    RUNNING = "running"
    PAUSED = "paused"
    FAILED = "failed"
    SUCCESSFUL = "successful"
    CANCELLED = "cancelled"
    PENDING = "pending"
    BLOCKED = "blocked"
    UNBLOCKING = "unblocking"

    TERMINAL = (FAILED, SUCCESSFUL, CANCELLED)

    # status descriptions (subset used by reconciler/watcher)
    DESC_RUNNING = "Deployment is running"
    DESC_RUNNING_NEEDS_PROMOTION = "Deployment is running but requires manual promotion"
    DESC_RUNNING_AUTO_PROMOTION = "Deployment is running pending automatic promotion"
    DESC_FAILED_ALLOCATIONS = "Failed due to unhealthy allocations"
    DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
    DESC_NEWER_JOB = "Cancelled due to newer version of job"
    DESC_SUCCESSFUL = "Deployment completed successfully"
    DESC_MULTIREGION_FAIL = \
        "Failed due to a failed deployment in a peer region"


@dataclass
class DeploymentState:
    """Per-task-group rollout state."""
    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: list = field(default_factory=list)   # alloc ids
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_s: float = 0.0
    require_progress_by: float = 0.0


@dataclass
class Deployment:
    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_spec_modify_index: int = 0
    job_create_index: int = 0
    is_multiregion: bool = False
    # set once this region's SUCCESSFUL multiregion deployment has
    # started the NEXT region's rollout (replicated, so a new leader
    # doesn't double-kick)
    multiregion_kicked: bool = False
    task_groups: Dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DeploymentStatus.RUNNING
    status_description: str = DeploymentStatus.DESC_RUNNING
    eval_priority: int = 50
    create_index: int = 0
    modify_index: int = 0
    create_time: float = 0.0
    modify_time: float = 0.0

    def active(self) -> bool:
        return self.status in (DeploymentStatus.RUNNING, DeploymentStatus.PAUSED,
                               DeploymentStatus.PENDING, DeploymentStatus.BLOCKED,
                               DeploymentStatus.UNBLOCKING)

    def requires_promotion(self) -> bool:
        return any(s.desired_canaries > 0 and not s.promoted
                   for s in self.task_groups.values())

    def has_auto_promote(self) -> bool:
        return (bool(self.task_groups)
                and all(s.auto_promote for s in self.task_groups.values()
                        if s.desired_canaries > 0)
                and any(s.desired_canaries > 0 for s in self.task_groups.values()))

    def has_placed_canaries(self) -> bool:
        return any(s.placed_canaries for s in self.task_groups.values())

    def copy(self) -> "Deployment":
        import copy as _copy
        return _copy.deepcopy(self)
