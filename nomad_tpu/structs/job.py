"""Job specification model (reference: nomad/structs/structs.go Job:4065,
TaskGroup:6116, Task:6898, Constraint/Affinity/Spread).
"""
from __future__ import annotations


from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

from nomad_tpu.structs.resources import NetworkResource, Resources


class JobType:
    SERVICE = "service"
    BATCH = "batch"
    SYSTEM = "system"
    SYSBATCH = "sysbatch"
    CORE = "_core"          # internal GC job (reference nomad/core_sched.go)


class JobStatus:
    PENDING = "pending"
    RUNNING = "running"
    DEAD = "dead"


# Constraint operands (reference structs.Constraint, feasible.go:806-841)
class Operand:
    EQ = "="
    NEQ = "!="
    LT = "<"
    LTE = "<="
    GT = ">"
    GTE = ">="
    REGEX = "regexp"
    VERSION = "version"
    SEMVER = "semver"
    SET_CONTAINS = "set_contains"
    SET_CONTAINS_ALL = "set_contains_all"
    SET_CONTAINS_ANY = "set_contains_any"
    ATTRIBUTE_IS_SET = "is_set"
    ATTRIBUTE_IS_NOT_SET = "is_not_set"
    DISTINCT_HOSTS = "distinct_hosts"
    DISTINCT_PROPERTY = "distinct_property"


@dataclass(frozen=True)
class Constraint:
    ltarget: str = ""        # usually "${attr.x}" / "${node.class}" / "${meta.y}"
    rtarget: str = ""
    operand: str = Operand.EQ

    def __str__(self) -> str:
        return f"{self.ltarget} {self.operand} {self.rtarget}"


@dataclass(frozen=True)
class Affinity:
    ltarget: str = ""
    rtarget: str = ""
    operand: str = Operand.EQ
    weight: int = 50         # in [-100, 100]


@dataclass(frozen=True)
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass(frozen=True)
class Spread:
    attribute: str = ""       # interpolation target, e.g. "${node.datacenter}"
    weight: int = 50          # in (0, 100]
    targets: tuple = ()       # Tuple[SpreadTarget, ...]


@dataclass
class RestartPolicy:
    attempts: int = 2
    interval_s: float = 1800.0
    delay_s: float = 15.0
    mode: str = "fail"        # "fail" | "delay"


@dataclass
class ReschedulePolicy:
    """Reference structs.ReschedulePolicy (defaults per job type)."""
    attempts: int = 0
    interval_s: float = 0.0
    delay_s: float = 30.0
    delay_function: str = "exponential"   # "constant" | "exponential" | "fibonacci"
    max_delay_s: float = 3600.0
    unlimited: bool = True

    @staticmethod
    def default_service() -> "ReschedulePolicy":
        return ReschedulePolicy(delay_s=30.0, delay_function="exponential",
                                max_delay_s=3600.0, unlimited=True)

    @staticmethod
    def default_batch() -> "ReschedulePolicy":
        return ReschedulePolicy(attempts=1, interval_s=86400.0, delay_s=5.0,
                                delay_function="constant", unlimited=False)


@dataclass
class MigrateStrategy:
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0


@dataclass
class UpdateStrategy:
    """Rolling-update / canary configuration (reference structs.UpdateStrategy)."""
    stagger_s: float = 30.0
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0
    progress_deadline_s: float = 600.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0

    def rolling(self) -> bool:
        return self.max_parallel > 0


@dataclass
class MultiregionStrategy:
    """Rollout pacing across regions (reference structs.MultiregionStrategy).
    `on_failure="fail_all"` reverts already-promoted regions when any
    region's deployment fails; `"fail_local"` contains the failure."""
    max_parallel: int = 1
    on_failure: str = "fail_all"   # "fail_all" | "fail_local"


@dataclass
class MultiregionRegion:
    """One region's slice of a multiregion job: optional count override
    applied to every task group, optional datacenter override."""
    name: str = ""
    count: Optional[int] = None
    datacenters: List[str] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)

    def copy(self) -> "MultiregionRegion":
        return replace(self, datacenters=list(self.datacenters),
                       meta=dict(self.meta))


@dataclass
class Multiregion:
    """The `multiregion` jobspec block (reference structs.Multiregion):
    the ordered region list drives a sequential rollout — region N+1's
    deployment starts only once region N's is healthy."""
    strategy: MultiregionStrategy = field(default_factory=MultiregionStrategy)
    regions: List[MultiregionRegion] = field(default_factory=list)

    def region_names(self) -> List[str]:
        return [r.name for r in self.regions]

    def lookup(self, name: str) -> Optional[MultiregionRegion]:
        for r in self.regions:
            if r.name == name:
                return r
        return None

    def copy(self) -> "Multiregion":
        return replace(self, strategy=replace(self.strategy),
                       regions=[r.copy() for r in self.regions])


@dataclass
class EphemeralDisk:
    sticky: bool = False
    size_mb: int = 300
    migrate: bool = False


@dataclass
class PeriodicConfig:
    enabled: bool = True
    spec: str = ""            # cron spec
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    timezone: str = "UTC"


@dataclass
class DispatchPayloadConfig:
    file: str = ""


@dataclass
class ParameterizedJobConfig:
    payload: str = "optional"      # "optional" | "required" | "forbidden"
    meta_required: List[str] = field(default_factory=list)
    meta_optional: List[str] = field(default_factory=list)


@dataclass
class Lifecycle:
    hook: str = ""                 # "prestart" | "poststart" | "poststop"
    sidecar: bool = False


@dataclass
class Service:
    name: str = ""
    provider: str = "consul"       # "consul" | "nomad"
    port_label: str = ""
    tags: List[str] = field(default_factory=list)
    checks: List[dict] = field(default_factory=list)


@dataclass
class ScalingPolicy:
    """Task-group scaling bounds + external-autoscaler policy document
    (reference nomad/structs/structs.go ScalingPolicy / TaskGroup.Scaling:
    the server enforces min/max on Job.Scale; the policy body is opaque
    to the scheduler and consumed by the autoscaler)."""
    min: int = 0
    max: int = 0
    enabled: bool = True
    policy: Dict[str, object] = field(default_factory=dict)


@dataclass
class ScalingEvent:
    """One scale action recorded against a (job, group) — the audit log
    behind `nomad job scale-status` (structs.go ScalingEvent)."""
    time: float = 0.0
    previous_count: int = 0
    count: Optional[int] = None
    message: str = ""
    error: bool = False
    eval_id: str = ""
    meta: Dict[str, object] = field(default_factory=dict)


@dataclass
class Task:
    name: str = "task"
    driver: str = "mock"
    config: Dict[str, object] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    lifecycle: Optional[Lifecycle] = None
    kill_timeout_s: float = 5.0
    leader: bool = False
    services: List[Service] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)
    dispatch_payload: Optional[DispatchPayloadConfig] = None
    artifacts: List[dict] = field(default_factory=list)
    templates: List[dict] = field(default_factory=list)
    vault: Optional[dict] = None

    def copy(self) -> "Task":
        return replace(self, config=dict(self.config), env=dict(self.env),
                       resources=self.resources.copy(),
                       constraints=list(self.constraints),
                       affinities=list(self.affinities),
                       services=list(self.services), meta=dict(self.meta))


@dataclass
class VolumeRequest:
    name: str = ""
    type: str = "host"            # "host" | "csi"
    source: str = ""
    read_only: bool = False
    access_mode: str = ""
    attachment_mode: str = ""
    per_alloc: bool = False


@dataclass
class TaskGroup:
    name: str = "group"
    count: int = 1
    tasks: List[Task] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    migrate: MigrateStrategy = field(default_factory=MigrateStrategy)
    update: Optional[UpdateStrategy] = None
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    networks: List[NetworkResource] = field(default_factory=list)
    services: List[Service] = field(default_factory=list)
    volumes: Dict[str, VolumeRequest] = field(default_factory=dict)
    max_client_disconnect_s: Optional[float] = None
    stop_after_client_disconnect_s: Optional[float] = None
    meta: Dict[str, str] = field(default_factory=dict)
    scaling: Optional[ScalingPolicy] = None

    def copy(self) -> "TaskGroup":
        return replace(self, tasks=[t.copy() for t in self.tasks],
                       constraints=list(self.constraints),
                       affinities=list(self.affinities),
                       spreads=list(self.spreads),
                       networks=[n.copy() for n in self.networks],
                       services=list(self.services), volumes=dict(self.volumes),
                       meta=dict(self.meta))


@dataclass
class Job:
    id: str = ""
    namespace: str = "default"
    region: str = "global"
    name: str = ""
    type: str = JobType.SERVICE
    priority: int = 50
    all_at_once: bool = False
    datacenters: List[str] = field(default_factory=lambda: ["dc1"])
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    task_groups: List[TaskGroup] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    multiregion: Optional[Multiregion] = None
    periodic: Optional[PeriodicConfig] = None
    parameterized: Optional[ParameterizedJobConfig] = None
    payload: bytes = b""
    meta: Dict[str, str] = field(default_factory=dict)
    status: str = JobStatus.PENDING
    stop: bool = False
    version: int = 0
    stable: bool = False
    parent_id: str = ""
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0
    submit_time: float = 0.0

    @property
    def namespaced_id(self) -> str:
        return f"{self.namespace}/{self.id}"

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def is_periodic(self) -> bool:
        return self.periodic is not None

    def is_parameterized(self) -> bool:
        return self.parameterized is not None

    def stopped(self) -> bool:
        return self.stop

    def canonicalize(self) -> None:
        """Merge job-level blocks into task groups and fill defaults
        (reference api/jobs.go Canonicalize + structs Job.Canonicalize:
        the job update block is copied into groups lacking one, reschedule
        policies default per job type)."""
        for tg in self.task_groups:
            if tg.update is None and self.update is not None \
                    and self.type == JobType.SERVICE:
                tg.update = replace(self.update)
            if tg.reschedule_policy is None:
                if self.type == JobType.SERVICE:
                    tg.reschedule_policy = ReschedulePolicy.default_service()
                elif self.type == JobType.BATCH:
                    tg.reschedule_policy = ReschedulePolicy.default_batch()

    def copy(self) -> "Job":
        return replace(self, datacenters=list(self.datacenters),
                       constraints=list(self.constraints),
                       affinities=list(self.affinities),
                       spreads=list(self.spreads),
                       task_groups=[tg.copy() for tg in self.task_groups],
                       multiregion=(self.multiregion.copy()
                                    if self.multiregion else None),
                       meta=dict(self.meta))

    def multiregion_copy(self, region: str, rollout_id: str) -> "Job":
        """The per-region slice of a multiregion job: region set, count
        and datacenter overrides applied, the multiregion block retained
        (the deployment watcher reads it to kick the NEXT region), and
        the rollout id stamped in meta so re-registration is detectable
        and the copy is never re-expanded."""
        c = self.copy()
        c.region = region
        c.meta["multiregion.rollout"] = rollout_id
        mr = c.multiregion.lookup(region) if c.multiregion else None
        if mr is not None:
            if mr.count is not None:
                for tg in c.task_groups:
                    tg.count = mr.count
            if mr.datacenters:
                c.datacenters = list(mr.datacenters)
            if mr.meta:
                c.meta.update(mr.meta)
        return c
