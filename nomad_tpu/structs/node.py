"""Node model (reference: nomad/structs/structs.go Node:1851,
node_class.go:27-37 ComputeClass).
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.structs.resources import (
    ComparableResources,
    NetworkResource,
    NodeDevice,
)
from nomad_tpu.utils import generate_uuid


class NodeStatus:
    INIT = "initializing"
    READY = "ready"
    DOWN = "down"
    DISCONNECTED = "disconnected"


class NodeSchedulingEligibility:
    ELIGIBLE = "eligible"
    INELIGIBLE = "ineligible"


@dataclass
class DrainStrategy:
    deadline_s: float = 3600.0
    ignore_system_jobs: bool = False
    force_deadline: float = 0.0        # absolute time when drain forces
    started_at: float = 0.0


@dataclass
class NodeCpuResources:
    cpu_shares: int = 4000             # total MHz
    total_core_count: int = 4
    reservable_cores: List[int] = field(default_factory=list)

    def shares_per_core(self) -> int:
        if self.total_core_count == 0:
            return 0
        return self.cpu_shares // self.total_core_count


@dataclass
class NodeResources:
    cpu: NodeCpuResources = field(default_factory=NodeCpuResources)
    memory_mb: int = 8192
    disk_mb: int = 100 * 1024
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[NodeDevice] = field(default_factory=list)
    # min/max port of the dynamic port range on this node
    min_dynamic_port: int = 20000
    max_dynamic_port: int = 32000


@dataclass
class NodeReservedResources:
    cpu_shares: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_ports: List[int] = field(default_factory=list)
    cores: List[int] = field(default_factory=list)


@dataclass
class Node:
    id: str = ""
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_resources: NodeResources = field(default_factory=NodeResources)
    reserved_resources: NodeReservedResources = field(default_factory=NodeReservedResources)
    links: Dict[str, str] = field(default_factory=dict)
    drivers: Dict[str, dict] = field(default_factory=dict)   # driver -> {detected, healthy}
    status: str = NodeStatus.INIT
    scheduling_eligibility: str = NodeSchedulingEligibility.ELIGIBLE
    drain_strategy: Optional[DrainStrategy] = None
    status_updated_at: float = 0.0
    last_drain: Optional[dict] = None
    host_volumes: Dict[str, dict] = field(default_factory=dict)  # name -> {path, read_only}
    csi_node_plugins: Dict[str, dict] = field(default_factory=dict)
    csi_controller_plugins: Dict[str, dict] = field(default_factory=dict)
    computed_class: str = ""
    # advertised agent HTTP address ("host:port") — the server-side fs
    # endpoints forward alloc fs/log reads here (reference Node.HTTPAddr,
    # client/fs_endpoint.go forwarding)
    http_addr: str = ""
    # per-node shared secret, proven back to the servers on Secrets.Derive
    # (reference Node.SecretID, node_endpoint.go deriveTokenInternal); never
    # returned by Node.GetNode/Node.List
    secret_id: str = field(default_factory=generate_uuid)
    create_index: int = 0
    modify_index: int = 0

    def ready(self) -> bool:
        """Reference Node.Ready: status ready, not draining, eligible."""
        return (self.status == NodeStatus.READY
                and self.drain_strategy is None
                and self.scheduling_eligibility == NodeSchedulingEligibility.ELIGIBLE)

    @property
    def draining(self) -> bool:
        return self.drain_strategy is not None

    def comparable_resources(self) -> ComparableResources:
        return ComparableResources(
            cpu_shares=self.node_resources.cpu.cpu_shares,
            memory_mb=self.node_resources.memory_mb,
            disk_mb=self.node_resources.disk_mb,
        )

    def comparable_reserved_resources(self) -> ComparableResources:
        return ComparableResources(
            cpu_shares=self.reserved_resources.cpu_shares,
            memory_mb=self.reserved_resources.memory_mb,
            disk_mb=self.reserved_resources.disk_mb,
        )

    def terminal_status(self) -> bool:
        return self.status == NodeStatus.DOWN


def compute_node_class(node: Node) -> str:
    """Hash of the class-relevant fields of a node (reference
    structs/node_class.go:27-37 ComputeClass).  Nodes with the same computed
    class are interchangeable for class-capturable constraints, enabling
    per-class feasibility memoization and blocked-eval ClassEligibility.

    Attributes/metadata with the "unique." prefix are excluded, mirroring
    the reference's EscapedConstraints semantics.
    """
    payload = {
        "datacenter": node.datacenter,
        "node_class": node.node_class,
        "attributes": {k: v for k, v in sorted(node.attributes.items())
                       if not k.startswith("unique.")},
        "meta": {k: v for k, v in sorted(node.meta.items())
                 if not k.startswith("unique.")},
        "drivers": sorted(d for d, info in node.drivers.items()
                          if info.get("detected")),
        "resources": [node.node_resources.cpu.cpu_shares,
                      node.node_resources.memory_mb,
                      node.node_resources.disk_mb],
        "devices": sorted(d.id for d in node.node_resources.devices),
        "host_volumes": sorted(self_k for self_k in node.host_volumes),
    }
    digest = hashlib.blake2b(
        json.dumps(payload, sort_keys=True).encode(), digest_size=8
    ).hexdigest()
    return f"v1:{digest}"
