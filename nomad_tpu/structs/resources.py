"""Resource model + host-side fit/score reference semantics.

Reference: nomad/structs/structs.go (Resources/AllocatedResources/
ComparableResources, :3964+) and nomad/structs/funcs.go:166-297 (AllocsFit,
ScoreFitBinPack, ScoreFitSpread).  The host-side functions here define the
*semantics contract*; the vectorized device versions in `nomad_tpu.ops.fit`
are golden-tested against them.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

MB = 1  # all memory/disk figures are in megabytes, cpu in MHz shares


@dataclass
class NetworkPort:
    label: str = ""
    value: int = 0          # static port number, or assigned dynamic port
    to: int = 0             # mapped port inside the task namespace
    host_network: str = "default"


@dataclass
class NetworkResource:
    mode: str = "host"      # "host" | "bridge" | "none" | "cni/*"
    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    dns: Optional[dict] = None
    reserved_ports: List[NetworkPort] = field(default_factory=list)
    dynamic_ports: List[NetworkPort] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        return replace(
            self,
            reserved_ports=[replace(p) for p in self.reserved_ports],
            dynamic_ports=[replace(p) for p in self.dynamic_ports],
        )


@dataclass
class DeviceRequest:
    """A task's request for devices (reference structs.RequestedDevice)."""
    name: str = ""            # "vendor/type/model", "type/model" or "type"
    count: int = 1
    constraints: List = field(default_factory=list)   # List[Constraint]
    affinities: List = field(default_factory=list)    # List[Affinity]


@dataclass
class NodeDevice:
    """An instance group of devices on a node (reference structs.NodeDeviceResource).
    `unhealthy_ids` is fed by the client's device fingerprint stream
    (reference plugins/device/device.go:25-37 Fingerprint — per-instance
    Healthy flags): unhealthy instances stay listed (operators see them)
    but are excluded from scheduling capacity and assignment."""
    vendor: str = ""
    type: str = ""            # e.g. "gpu", "fpga"
    name: str = ""            # model name
    instance_ids: List[str] = field(default_factory=list)
    attributes: Dict[str, object] = field(default_factory=dict)
    unhealthy_ids: List[str] = field(default_factory=list)

    @property
    def id(self) -> str:
        return f"{self.vendor}/{self.type}/{self.name}"

    def healthy_ids(self) -> List[str]:
        bad = set(self.unhealthy_ids)
        return [i for i in self.instance_ids if i not in bad]

    def matches(self, requested: str) -> bool:
        """Match semantics of structs.NodeDeviceResource.ID matching:
        request may be 'type', 'type/name' or 'vendor/type/name'."""
        parts = requested.split("/")
        if len(parts) == 1:
            return parts[0] == self.type
        if len(parts) == 2:
            return parts[0] == self.type and parts[1] == self.name
        if len(parts) == 3:
            return (parts[0] == self.vendor and parts[1] == self.type
                    and parts[2] == self.name)
        return False


@dataclass
class Resources:
    """Per-task requested resources (reference structs.Resources)."""
    cpu: int = 100               # MHz shares
    cores: int = 0               # reserved whole cores (exclusive)
    memory_mb: int = 300
    memory_max_mb: int = 0       # oversubscription ceiling (0 = disabled)
    disk_mb: int = 0             # task-level disk is summed at group level
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[DeviceRequest] = field(default_factory=list)

    def copy(self) -> "Resources":
        return replace(
            self,
            networks=[n.copy() for n in self.networks],
            devices=[replace(d, constraints=list(d.constraints),
                             affinities=list(d.affinities)) for d in self.devices],
        )


@dataclass
class ComparableResources:
    """Flattened, comparable resource totals (reference
    structs.ComparableResources / AllocatedResources.Comparable)."""
    cpu_shares: int = 0
    reserved_cores: Tuple[int, ...] = ()
    memory_mb: int = 0
    memory_max_mb: int = 0
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)

    def add(self, other: "ComparableResources") -> None:
        self.cpu_shares += other.cpu_shares
        self.reserved_cores = tuple(sorted(set(self.reserved_cores) | set(other.reserved_cores)))
        self.memory_mb += other.memory_mb
        self.memory_max_mb += other.memory_max_mb if other.memory_max_mb else other.memory_mb
        self.disk_mb += other.disk_mb
        self.networks.extend(other.networks)

    def superset(self, other: "ComparableResources") -> Tuple[bool, str]:
        """Is self a superset of other?  Returns (ok, exhausted-dimension)."""
        if self.cpu_shares < other.cpu_shares:
            return False, "cpu"
        if self.memory_mb < other.memory_mb:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        return True, ""


def allocs_fit_host(node, allocs, check_devices: bool = False):
    """Host reference of structs.AllocsFit (funcs.go:166-233).

    Returns (fit: bool, dimension: str, used: ComparableResources).
    `node` is a structs.Node; `allocs` iterable of Allocation (terminal ones
    are ignored).  Port accounting lives in the dense path: per-node port
    bitsets in nomad_tpu.encode.matrixizer.ClusterMatrix and host claim
    assignment in nomad_tpu.scheduler.placement.PortClaims.
    """
    used = ComparableResources()
    seen_cores: set = set()
    core_overlap = False
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        cr = alloc.comparable_resources()
        for core in cr.reserved_cores:
            if core in seen_cores:
                core_overlap = True
            seen_cores.add(core)
        used.add(cr)
    if core_overlap:
        return False, "cores", used

    avail = node.comparable_resources()
    reserved = node.comparable_reserved_resources()
    avail.cpu_shares -= reserved.cpu_shares
    avail.memory_mb -= reserved.memory_mb
    avail.disk_mb -= reserved.disk_mb
    ok, dim = avail.superset(used)
    if not ok:
        return False, dim, used

    if check_devices:
        from nomad_tpu.scheduler.devices import device_accounter_fits
        if not device_accounter_fits(node, allocs):
            return False, "device oversubscribed", used

    return True, "", used


def _free_ratio(used: float, capacity: float) -> float:
    """1 - used/capacity with IEEE-style handling of capacity <= 0 (a fully
    reserved node): any usage -> -inf (overfit, clamps to the worst score),
    zero usage -> 1.0 (nothing used of nothing).  The Go reference divides
    straight through and relies on float Inf/NaN falling out of the clamp;
    we pin the 0/0 case to a defined value instead."""
    if capacity <= 0.0:
        return 1.0 if used <= 0.0 else float("-inf")
    return 1.0 - used / capacity


def _free_percentages(node, util: ComparableResources) -> Tuple[float, float]:
    """`node` is either a structs.Node or a bare ComparableResources of
    usable capacity (funcs.go ScoreFit takes *ComparableResources — direct
    callers pass reservation-adjusted totals themselves)."""
    if hasattr(node, "comparable_reserved_resources"):
        reserved = node.comparable_reserved_resources()
        res = node.comparable_resources()
        node_cpu = float(res.cpu_shares) - float(reserved.cpu_shares)
        node_mem = float(res.memory_mb) - float(reserved.memory_mb)
    else:
        node_cpu = float(node.cpu_shares)
        node_mem = float(node.memory_mb)
    return (_free_ratio(float(util.cpu_shares), node_cpu),
            _free_ratio(float(util.memory_mb), node_mem))


MAX_FIT_SCORE = 18.0  # reference scheduler/rank.go binPackingMaxFitScore


def score_fit_binpack_host(node, util: ComparableResources) -> float:
    """BestFit v3 (funcs.go:259-279): 20 - (10^freeCpu + 10^freeMem), in [0,18]."""
    free_cpu, free_mem = _free_percentages(node, util)
    total = math.pow(10, free_cpu) + math.pow(10, free_mem)
    return min(18.0, max(0.0, 20.0 - total))


def score_fit_spread_host(node, util: ComparableResources) -> float:
    """Worst Fit (funcs.go:286-297): (10^freeCpu + 10^freeMem) - 2, in [0,18]."""
    free_cpu, free_mem = _free_percentages(node, util)
    total = math.pow(10, free_cpu) + math.pow(10, free_mem)
    return min(18.0, max(0.0, total - 2.0))
