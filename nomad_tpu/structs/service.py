"""Nomad-native service registrations (reference
nomad/structs/service_registration.go + client/serviceregistration/nsd —
the built-in service discovery backend that replaces Consul for
`provider = "nomad"` services: clients register the services of running
allocations with the servers, deregister them on stop, and the registry
is queryable at /v1/services).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class ServiceRegistration:
    """One service instance bound to one allocation (reference
    service_registration.go ServiceRegistration)."""
    id: str = ""                    # _nomad-task-<alloc>-<task>-<svc>-<port>
    service_name: str = ""
    namespace: str = "default"
    node_id: str = ""
    datacenter: str = ""
    job_id: str = ""
    alloc_id: str = ""
    tags: List[str] = field(default_factory=list)
    address: str = ""
    port: int = 0
    # check-driven health: "passing" | "critical" | "pending" — fed by the
    # client's check runner (nsd keeps checks client-side; health rides
    # the registration so /v1/services and the deployment watcher see it)
    health: str = "passing"
    create_index: int = 0
    modify_index: int = 0


def registration_id(alloc_id: str, task: str, service: str,
                    port_label: str) -> str:
    return f"_nomad-task-{alloc_id}-{task or 'group'}-{service}-{port_label or 'none'}"
