"""Shared data model (reference: nomad/structs/).

Plain Python dataclasses for the control-plane objects; the dense device
encoding lives in `nomad_tpu.encode`.
"""

from nomad_tpu.structs.resources import (
    ComparableResources,
    DeviceRequest,
    NetworkPort,
    NetworkResource,
    NodeDevice,
    Resources,
    allocs_fit_host,
    score_fit_binpack_host,
    score_fit_spread_host,
)
from nomad_tpu.structs.job import (
    Affinity,
    Constraint,
    DispatchPayloadConfig,
    EphemeralDisk,
    Job,
    JobStatus,
    JobType,
    MigrateStrategy,
    Multiregion,
    MultiregionRegion,
    MultiregionStrategy,
    PeriodicConfig,
    ReschedulePolicy,
    RestartPolicy,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
    UpdateStrategy,
)
from nomad_tpu.structs.node import (
    DrainStrategy,
    Node,
    NodeReservedResources,
    NodeResources,
    NodeSchedulingEligibility,
    NodeStatus,
    compute_node_class,
)
from nomad_tpu.structs.alloc import (
    AllocClientStatus,
    AllocDesiredStatus,
    Allocation,
    AllocMetric,
    DesiredTransition,
    RescheduleEvent,
    RescheduleTracker,
    TaskState,
)
from nomad_tpu.structs.evaluation import (
    EvalStatus,
    EvalTrigger,
    Evaluation,
)
from nomad_tpu.structs.plan import (
    Plan,
    PlanAnnotations,
    PlanResult,
    DesiredUpdates,
)
from nomad_tpu.structs.deployment import (
    Deployment,
    DeploymentState,
    DeploymentStatus,
)
from nomad_tpu.structs.config import SchedulerConfiguration
from nomad_tpu.structs.namespace import (
    Namespace,
    QuotaSpec,
    alloc_quota_usage,
)

__all__ = [k for k in dir() if not k.startswith("_")]
