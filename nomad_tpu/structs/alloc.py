"""Allocation model (reference: nomad/structs/structs.go Allocation:9466,
AllocMetric:10341, DesiredTransition, RescheduleTracker).
"""
from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.structs.resources import ComparableResources, Resources
from nomad_tpu.structs.job import Job


class AllocDesiredStatus:
    RUN = "run"
    STOP = "stop"
    EVICT = "evict"


class AllocClientStatus:
    PENDING = "pending"
    RUNNING = "running"
    COMPLETE = "complete"
    FAILED = "failed"
    LOST = "lost"
    UNKNOWN = "unknown"


@dataclass
class TaskState:
    state: str = "pending"            # pending | running | dead
    failed: bool = False
    restarts: int = 0
    started_at: float = 0.0
    finished_at: float = 0.0
    events: List[dict] = field(default_factory=list)


@dataclass
class DesiredTransition:
    """Server-set hints for the scheduler (reference structs.DesiredTransition)."""
    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def should_migrate(self) -> bool:
        return bool(self.migrate)

    def should_force_reschedule(self) -> bool:
        return bool(self.force_reschedule)


@dataclass
class RescheduleEvent:
    reschedule_time: float = 0.0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_s: float = 0.0


@dataclass
class RescheduleTracker:
    events: List[RescheduleEvent] = field(default_factory=list)


@dataclass
class AllocatedTaskResources:
    cpu_shares: int = 0
    reserved_cores: tuple = ()
    memory_mb: int = 0
    memory_max_mb: int = 0
    networks: List = field(default_factory=list)
    devices: List[dict] = field(default_factory=list)  # [{vendor,type,name,device_ids}]


@dataclass
class AllocatedResources:
    """Reference structs.AllocatedResources: per-task + shared (disk/ports)."""
    tasks: Dict[str, AllocatedTaskResources] = field(default_factory=dict)
    shared_disk_mb: int = 0
    shared_networks: List = field(default_factory=list)
    shared_ports: List = field(default_factory=list)   # List[NetworkPort]

    def comparable(self) -> ComparableResources:
        c = ComparableResources()
        for tr in self.tasks.values():
            c.add(ComparableResources(
                cpu_shares=tr.cpu_shares,
                reserved_cores=tuple(tr.reserved_cores),
                memory_mb=tr.memory_mb,
                memory_max_mb=tr.memory_max_mb,
                networks=list(tr.networks),
            ))
        c.disk_mb = self.shared_disk_mb
        c.networks.extend(self.shared_networks)
        return c


@dataclass
class AllocMetric:
    """Placement telemetry surfaced in `alloc status -verbose`
    (reference structs.AllocMetric / PopulateScoreMetaData)."""
    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)   # per-dc
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    quota_exhausted: List[str] = field(default_factory=list)
    scores: Dict[str, float] = field(default_factory=dict)          # node.class -> score
    score_meta: List[dict] = field(default_factory=list)            # top-K [{node_id, scores{}, norm_score}]
    allocation_time_s: float = 0.0
    coalesced_failures: int = 0

    TOP_K = 5

    def exhausted_node(self, node_id: str, dimension: str) -> None:
        self.nodes_exhausted += 1
        if dimension:
            self.dimension_exhausted[dimension] = self.dimension_exhausted.get(dimension, 0) + 1

    def filter_node(self, reason: str) -> None:
        self.nodes_filtered += 1
        if reason:
            self.constraint_filtered[reason] = self.constraint_filtered.get(reason, 0) + 1

    def populate_score_meta(self, entries: List[dict]) -> None:
        """Keep top-K by normalized score (reference kheap-backed
        PopulateScoreMetaData, structs.go:10341)."""
        self.score_meta = heapq.nlargest(self.TOP_K, entries,
                                         key=lambda e: e.get("norm_score", 0.0))

    def copy(self) -> "AllocMetric":
        m = AllocMetric()
        m.__dict__.update({k: (dict(v) if isinstance(v, dict) else list(v) if isinstance(v, list) else v)
                           for k, v in self.__dict__.items()})
        return m


@dataclass
class Allocation:
    id: str = ""
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""                 # "<job>.<group>[<index>]"
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    allocated_resources: AllocatedResources = field(default_factory=AllocatedResources)
    desired_status: str = AllocDesiredStatus.RUN
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = AllocClientStatus.PENDING
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[dict] = None    # {healthy: bool, timestamp, canary: bool}
    reschedule_tracker: Optional[RescheduleTracker] = None
    previous_allocation: str = ""
    next_allocation: str = ""
    followup_eval_id: str = ""
    # when the reconciler marked this alloc unknown (node disconnected);
    # 0.0 = not disconnected.  Drives max_client_disconnect expiry.
    disconnected_at: float = 0.0
    preempted_by_allocation: str = ""
    preempted_allocations: List[str] = field(default_factory=list)
    metrics: AllocMetric = field(default_factory=AllocMetric)
    alloc_modify_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time: float = 0.0
    modify_time: float = 0.0

    # ----- status helpers (reference Allocation.TerminalStatus etc.) -----

    def terminal_status(self) -> bool:
        """Desired-status stop/evict, or a terminal client status."""
        if self.desired_status in (AllocDesiredStatus.STOP, AllocDesiredStatus.EVICT):
            return True
        return self.client_terminal_status()

    def client_terminal_status(self) -> bool:
        return self.client_status in (AllocClientStatus.COMPLETE,
                                      AllocClientStatus.FAILED,
                                      AllocClientStatus.LOST)

    def server_terminal_status(self) -> bool:
        return self.desired_status in (AllocDesiredStatus.STOP, AllocDesiredStatus.EVICT)

    def ran_successfully(self) -> bool:
        return self.client_status == AllocClientStatus.COMPLETE

    def migrate_status(self) -> bool:
        return self.desired_transition.should_migrate()

    def comparable_resources(self) -> ComparableResources:
        # memoized per allocated_resources object (called several times
        # per alloc in the placement/apply hot path); the cache key is the
        # object identity, so replacing allocated_resources invalidates it
        ar = self.allocated_resources
        cached = getattr(self, "_cmp_cache", None)
        if cached is not None and cached[0] is ar:
            return cached[1]
        c = ar.comparable()
        self._cmp_cache = (ar, c)
        return c

    def index(self) -> int:
        """Parse the bracketed index out of the alloc name."""
        l, r = self.name.rfind("["), self.name.rfind("]")
        if l == -1 or r == -1:
            return -1
        return int(self.name[l + 1:r])

    def is_canary(self) -> bool:
        return bool(self.deployment_status and self.deployment_status.get("canary"))

    def is_healthy(self) -> bool:
        return bool(self.deployment_status and self.deployment_status.get("healthy") is True)

    def is_unhealthy(self) -> bool:
        return bool(self.deployment_status and self.deployment_status.get("healthy") is False)

    def copy(self) -> "Allocation":
        import copy as _copy
        return _copy.deepcopy(self)


def alloc_name(job_id: str, group: str, index: int) -> str:
    return f"{job_id}.{group}[{index}]"
